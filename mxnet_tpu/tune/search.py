"""Measured compile-space search (ISSUE 20): score candidates, guard
them, pick a winner.

A *candidate* is one point in the compile space: a Pallas knob dict
(tune/overrides.py names) plus an allowlisted XLA ``compiler_options``
dict. The *baseline* — empty on both axes — is always candidate zero,
so the winner is >= baseline on the measured metric BY CONSTRUCTION.

Scoring: median warm wall time of `trials` dispatches (per-trial fresh
donated buffers, `block_until_ready` fenced). The check_fusion HLO
counters are the tie-breaker AND the hard guard:

  guard 1 (budget)      an explicit (lo, hi)/exact budget table — the
                        check_fusion.BUDGETS row for gated executables —
                        must hold on the candidate's optimized HLO;
  guard 2 (regression)  relative to the measured BASELINE structure:
                        more copies, more collectives, or fewer aliased
                        (donated-in-place) inputs than baseline rejects
                        the candidate regardless of speed;
  guard 3 (numerics)    candidate outputs vs baseline outputs on
                        identical inputs, per the executable's declared
                        contract (`tune.register_contract`): bitwise
                        for greedy decode, documented fp tolerance for
                        training steps;
  guard 4 (dead knobs)  a candidate whose Pallas override was IGNORED
                        by the kernel pickers (doesn't divide, wrong
                        granularity — `pallas_block_override_ignored`
                        grew during its compile) is measuring the
                        default config under a wrong label: rejected.

Near-ties (within `TIE_BAND` of the best median) resolve by HLO
structure — fewer copies, then fewer fusions, then baseline-first — so
a flag that only shrinks the graph still wins when wall time is noise.

The XLA flag allowlist is CURATED: every entry is a scalar DebugOption
verified to ride `compiled = lowered.compile(compiler_options=...)`
on the pinned toolchain (repeated-field flags like
``xla_disable_hlo_passes`` cannot — jax's env_option_overrides carries
scalars only). The guard, not the allowlist, is what keeps a flag
honest: ``xla_cpu_multi_thread_eigen=False`` really builds (and really
gets rejected for inflating copies).
"""
from __future__ import annotations

import math
import statistics
from contextlib import contextmanager
from dataclasses import dataclass, field
from time import perf_counter

from . import apply as _apply
from . import overrides as _overrides

__all__ = ["Candidate", "Workload", "SearchResult", "CandidateResult",
           "search", "capture_workload", "default_flag_candidates",
           "check_budget", "XLA_FLAG_ALLOWLIST", "TIE_BAND"]

# near-tie band for the structural tie-breaker: medians within 2% are
# timing noise on the CPU mesh (and on a busy TPU host)
TIE_BAND = 0.02

# scalar DebugOptions verified compilable per-executable on the pinned
# toolchain (jax 0.4.37 / jaxlib 0.4.36); values are the NON-DEFAULT
# setting a flag candidate toggles to
XLA_FLAG_ALLOWLIST = {
    "xla_cpu_copy_insertion_use_region_analysis": True,
    "xla_cpu_enable_fast_min_max": True,
    "xla_cpu_enable_concurrency_optimized_scheduler": True,
    "xla_cpu_multi_thread_eigen": False,
    "xla_backend_optimization_level": 2,
    "xla_llvm_disable_expensive_passes": True,
    "xla_tpu_enable_latency_hiding_scheduler": True,   # TPU-only
}

# flags meaningless off their platform (compiling with them raises on
# the other backend); keyed by jax.default_backend() prefix
_PLATFORM_ONLY = {"xla_cpu_": "cpu", "xla_tpu_": "tpu"}


@dataclass(frozen=True)
class Candidate:
    """One compile-space point. `pallas` uses tune/overrides.py knob
    names; `flags` is an XLA compiler_options dict."""
    name: str
    pallas: dict = field(default_factory=dict)
    flags: dict = field(default_factory=dict)

    @property
    def is_baseline(self):
        return not self.pallas and not self.flags


@dataclass
class CandidateResult:
    candidate: Candidate
    score_ms: float = math.inf
    trial_ms: list = field(default_factory=list)
    hlo: dict = None
    rejected: str = None           # guard-rejection reason, None = OK
    compile_s: float = 0.0


@dataclass
class SearchResult:
    executable: str
    platform: str
    shape_class: str
    baseline: CandidateResult
    winner: CandidateResult
    candidates: list
    trials: int

    @property
    def improved(self):
        return not self.winner.candidate.is_baseline

    @property
    def speedup(self):
        if self.winner.score_ms <= 0:
            return 1.0
        return self.baseline.score_ms / self.winner.score_ms

    def winner_entry(self):
        """The TuneStore entry for the winner, or None when the
        baseline won (nothing to persist — defaults ARE the winner)."""
        if not self.improved:
            return None
        w = self.winner
        return {
            "executable": self.executable,
            "platform": self.platform,
            "shape_class": self.shape_class,
            "plan": _apply.plan_signature(self.executable),
            "pallas": dict(w.candidate.pallas),
            "flags": dict(w.candidate.flags),
            "score_ms": round(w.score_ms, 6),
            "baseline_ms": round(self.baseline.score_ms, 6),
            "trials": self.trials,
            "hlo": {k: w.hlo.get(k) for k in
                    ("fusions", "copies", "collective_total",
                     "aliased_inputs")} if w.hlo else {},
        }


class Workload:
    """What the search needs from one executable:

    ij           the InstrumentedJit to tune
    executable   its compilex name (budget table / store key)
    make_args()  -> (args, kwargs) with FRESH device buffers of
                 identical values on every call — donated inputs are
                 consumed per dispatch, and the numerics guard compares
                 candidate outputs on equal inputs
    contract     numerics contract override; None reads the
                 `tune.register_contract` registry for the executable
    """

    def __init__(self, ij, make_args, executable=None, contract=None):
        self.ij = ij
        self.make_args = make_args
        self.executable = executable or ij.executable
        self._contract = contract

    @property
    def contract(self):
        return self._contract or _apply.contract_for(self.executable)


class _Snap:
    """Host snapshot of one argument leaf (an opaque pytree LEAF — a
    tuple here would be descended into by tree_map). Taken BEFORE the
    recorded dispatch executes, so donation has not consumed the
    buffer; the sharding rides along so replay compiles the same
    layout."""
    __slots__ = ("kind", "val", "sharding")

    def __init__(self, x):
        import jax
        import numpy as np
        self.sharding = None
        if isinstance(x, jax.Array):
            try:
                self.kind, self.val = "arr", np.asarray(x)
                self.sharding = x.sharding
            except Exception:
                self.kind, self.val = "live", x   # exotic dtype: keep
                                                  # the object (never a
                                                  # donated buffer here)
        else:
            self.kind, self.val = "py", x

    def replay(self):
        import jax
        if self.kind != "arr":
            return self.val
        try:
            return jax.device_put(self.val, self.sharding)
        except Exception:
            return jax.device_put(self.val)


@contextmanager
def capture_workload(*executables):
    """Record the NEXT dispatch of each named compilex executable into a
    replayable Workload: the InstrumentedJit plus host snapshots of its
    concrete arguments, so `make_args()` rebuilds fresh donated buffers
    with identical values for every trial. Yields a dict the caller
    reads AFTER driving one real step/turn:

        with capture_workload("captured_step") as caught:
            trainer_step(batch)          # the dispatch being recorded
        wl = caught["captured_step"]

    Stacks on top of an existing dispatch hook (autotune apply), which
    keeps running underneath."""
    import jax
    from ..observability import compilex as _compilex

    want = set(executables)
    caught = {}
    prev = _compilex.dispatch_hook()

    def _rec(ij, args, kwargs):
        if ij.executable in want and ij.executable not in caught:
            snaps = jax.tree_util.tree_map(_Snap, (args, dict(kwargs)))

            def make_args(_snaps=snaps):
                return jax.tree_util.tree_map(
                    lambda s: s.replay(), _snaps,
                    is_leaf=lambda s: isinstance(s, _Snap))

            caught[ij.executable] = Workload(ij, make_args)
        if prev is not None:
            return prev(ij, args, kwargs)
        return False, None

    _compilex.set_dispatch_hook(_rec)
    try:
        yield caught
    finally:
        _compilex.set_dispatch_hook(prev)


def default_flag_candidates(platform=None):
    """One single-flag candidate per allowlisted flag valid on this
    platform — the curated XLA dimension of the search space."""
    if platform is None:
        import jax
        platform = jax.default_backend()
    cands = []
    for flag, val in XLA_FLAG_ALLOWLIST.items():
        only = next((p for pre, p in _PLATFORM_ONLY.items()
                     if flag.startswith(pre)), None)
        if only is not None and only != platform:
            continue
        cands.append(Candidate(name=f"flag:{flag}={val}",
                               flags={flag: val}))
    return cands


def check_budget(info, budget):
    """check_fusion-style budget check: (lo, hi) bands inclusive, dicts
    compared per-op exactly, scalars exactly. Returns violation strings
    (empty = within budget). Mirrors tools/check_fusion.check_budget so
    the guard and the gate agree on semantics without the library
    importing from tools/."""
    errs = []
    for key, want in (budget or {}).items():
        got = info.get(key)
        if isinstance(want, tuple) and len(want) == 2:
            lo, hi = want
            if not (lo <= got <= hi):
                errs.append(f"{key}={got} outside [{lo}, {hi}]")
        elif isinstance(want, dict):
            if dict(got or {}) != dict(want):
                errs.append(f"{key}={got} != {want}")
        elif got != want:
            errs.append(f"{key}={got} != {want}")
    return errs


def _ignored_override_count():
    from ..observability.metrics_registry import registry
    return sum(int(c.value) for c in
               registry().series("pallas_block_override_ignored"))


def _leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


def _numerics_ok(ref, out, contract):
    import numpy as np
    rl, ol = _leaves(ref), _leaves(out)
    if len(rl) != len(ol):
        return False, "output structure differs"
    for i, (r, o) in enumerate(zip(rl, ol)):
        r = np.asarray(r)
        o = np.asarray(o)
        if r.shape != o.shape or r.dtype != o.dtype:
            return False, f"leaf {i} shape/dtype differs"
        if contract[0] == "bitwise":
            if not np.array_equal(r, o, equal_nan=True):
                return False, f"leaf {i} not bitwise-equal"
        else:
            _, rtol, atol = contract
            if not np.allclose(r, o, rtol=rtol, atol=atol,
                               equal_nan=True):
                worst = float(np.max(np.abs(
                    r.astype("float64") - o.astype("float64"))))
                return False, (f"leaf {i} outside tolerance "
                               f"(rtol={rtol}, atol={atol}, "
                               f"max_abs_diff={worst:.3g})")
    return True, None


def _time_trials(compiled, make_args, trials, warmup=1):
    import jax
    times = []
    for t in range(warmup + trials):
        args, kwargs = make_args()
        t0 = perf_counter()
        jax.block_until_ready(compiled(*args, **kwargs))
        dt = (perf_counter() - t0) * 1e3
        if t >= warmup:
            times.append(dt)
    return times


def search(workload, candidates=None, trials=5, budget=None,
           log=None):
    """Run the measured search for one workload; returns SearchResult.

    `candidates` defaults to the platform's flag allowlist; the
    baseline is always prepended. `budget` is an optional
    check_fusion-style table applied as guard 1 (the CLI passes the
    BUDGETS row of gated executables). `log` is an optional callable
    for progress lines."""
    import jax
    platform = jax.default_backend()
    ij = workload.ij
    log = log or (lambda s: None)
    if candidates is None:
        candidates = default_flag_candidates(platform)
    cands = [Candidate("baseline")] + [c for c in candidates
                                       if not c.is_baseline]
    args0, kwargs0 = workload.make_args()
    sclass = _apply.shape_class(args0, kwargs0)
    contract = workload.contract

    results = []
    base = None
    ref_out = None
    for cand in cands:
        rec = CandidateResult(candidate=cand)
        results.append(rec)
        entry = {"pallas": cand.pallas, "flags": cand.flags}
        ignored0 = _ignored_override_count()
        t0 = perf_counter()
        try:
            args, kwargs = workload.make_args()
            compiled, info = _apply.compile_winner(ij, args, kwargs,
                                                   entry)
        except Exception as e:
            rec.rejected = f"compile_error: {e!r}"
            log(f"  {cand.name}: REJECTED ({rec.rejected})")
            if cand.is_baseline:
                raise RuntimeError(
                    f"baseline compile failed for {workload.executable}"
                ) from e
            continue
        rec.compile_s = perf_counter() - t0
        rec.hlo = info
        # guard 4: a Pallas candidate whose override the kernel pickers
        # ignored is mislabelled default-config — reject, don't mislead
        if cand.pallas and _ignored_override_count() > ignored0:
            rec.rejected = "dead_pallas_override"
            log(f"  {cand.name}: REJECTED ({rec.rejected})")
            continue
        # guard 1: explicit budget table (gated executables)
        errs = check_budget(info, budget)
        if errs:
            rec.rejected = "budget: " + "; ".join(errs)
            log(f"  {cand.name}: REJECTED ({rec.rejected})")
            if cand.is_baseline:
                # the DEFAULT build breaking its own gate budget is a
                # config error, not a candidate to tune around
                raise RuntimeError(
                    f"baseline of {workload.executable} breaks its "
                    f"budget: {rec.rejected}")
            continue
        # guard 2: structural regression vs the measured baseline
        if base is not None and base.hlo:
            b = base.hlo
            if info["copies"] > b["copies"]:
                rec.rejected = (f"hlo_regression: copies "
                                f"{info['copies']} > {b['copies']}")
            elif info["collective_total"] > b["collective_total"]:
                rec.rejected = (f"hlo_regression: collectives "
                                f"{info['collective_total']} > "
                                f"{b['collective_total']}")
            elif info["aliased_inputs"] < b["aliased_inputs"]:
                rec.rejected = (f"hlo_regression: aliased_inputs "
                                f"{info['aliased_inputs']} < "
                                f"{b['aliased_inputs']}")
            if rec.rejected:
                log(f"  {cand.name}: REJECTED ({rec.rejected})")
                continue
        # guard 3: numerics vs baseline outputs on identical inputs
        import numpy as np
        args, kwargs = workload.make_args()
        out = compiled(*args, **kwargs)
        if cand.is_baseline:
            ref_out = jax.tree_util.tree_map(np.asarray, out)
        else:
            ok, why = _numerics_ok(ref_out, out, contract)
            if not ok:
                rec.rejected = f"numerics[{contract[0]}]: {why}"
                log(f"  {cand.name}: REJECTED ({rec.rejected})")
                continue
        del out
        rec.trial_ms = _time_trials(compiled, workload.make_args,
                                    trials)
        rec.score_ms = statistics.median(rec.trial_ms)
        if cand.is_baseline:
            base = rec
        log(f"  {cand.name}: median={rec.score_ms:.3f}ms "
            f"copies={info['copies']} fusions={info['fusions']}")

    accepted = [r for r in results if r.rejected is None]
    best_ms = min(r.score_ms for r in accepted)
    near = [r for r in accepted
            if r.score_ms <= best_ms * (1.0 + TIE_BAND)]
    # structural tie-breaker; baseline-first on full structural ties
    # (results order has baseline at index 0, min() is stable)
    winner = min(near, key=lambda r: (r.hlo["copies"], r.hlo["fusions"],
                                      r.hlo["module_bytes"]))
    # leave the published gauges describing the WINNER's structure (the
    # per-candidate compiles walked them through every config)
    _apply._publish(ij, winner.hlo)
    return SearchResult(executable=workload.executable,
                        platform=platform, shape_class=sclass,
                        baseline=base, winner=winner,
                        candidates=results, trials=trials)
