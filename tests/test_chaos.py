"""Chaos harness wired into tier-1 (ISSUE 3 acceptance): a preempted,
corrupt-fed, NaN-hit training run must recover to bitwise parity with a
fault-free run, with every recovery visible as metrics."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import chaos_check  # noqa: E402


def test_chaos_parity(tmp_path):
    res = chaos_check.run(str(tmp_path), seed=0, steps=14)
    assert res["parity"] == "bitwise"
    assert res["preempted_after"] >= 1
    assert len(res["corrupt_records"]) <= 5
    assert res["delta_data_records_skipped"] >= chaos_check.N_CORRUPT
    assert res["delta_engine_task_failures"] >= 1
    assert res["delta_trainer_steps_skipped"] >= 1
    assert res["delta_checkpoint_fallbacks"] >= 1
    # the emergency checkpoint restored onto a different device count
    assert res["resharded_restore_devices"] == 2


def test_chaos_cli_smoke():
    """The argv surface parses (no run: that is the test above)."""
    assert callable(chaos_check.main)
    assert chaos_check.N_CORRUPT <= 5
