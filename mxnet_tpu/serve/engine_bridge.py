"""Dependency-engine drive for the serving scheduler (ISSUE 6).

The serving crank is host-side async work — exactly what the dependency
engine (mxnet_tpu/engine.py) schedules for prefetch and checkpoint IO —
so the decode loop runs as engine tasks rather than a dedicated thread:

  * ONE loop task at a time, serialised on a private engine `Var` (the
    same write-var discipline as the prefetcher's staging slots, so the
    race detector covers the serving loop too);
  * `kick()` arms the loop when work arrives and is a no-op while a loop
    task is already scheduled — submits never pile up tasks;
  * the task cranks `scheduler.step()` until the engine is idle
    (bounded per-task burst, then re-pushes itself, so checkpoint saves
    and prefetch staging interleave with decoding instead of starving
    behind an unbounded serving task).

A loop-task failure surfaces through the engine's sticky failure report
(`engine.failures()`), like every other engine task.
"""
from __future__ import annotations

import threading
import time

from .. import engine

__all__ = ["EngineLoop"]

# steps one engine task cranks before re-pushing itself: long enough to
# amortise the push, short enough that other engine users interleave
_BURST = 64


class EngineLoop:
    def __init__(self, scheduler):
        self._sched = scheduler
        self._var = engine.Var()
        self._lock = threading.Lock()
        self._armed = False
        self._closed = False

    def kick(self):
        """Ensure a loop task is scheduled (no-op when one already is)."""
        with self._lock:
            if self._armed or self._closed:
                return
            self._armed = True
        engine.push(self._loop_task, write_vars=[self._var])

    def _loop_task(self):
        for _ in range(_BURST):
            if self._closed:
                break
            if not self._sched.step():
                # no progress: either drained, or queued work is waiting
                # on pages that only in-flight decodes can free — the
                # truthiness of step() guarantees actives keep making
                # progress, so "no progress + pending" means drained-race
                with self._lock:
                    if self._closed or not self._sched.pending_work():
                        self._armed = False
                        return
                continue
        # burst spent (or closing): yield the worker, keep the loop armed
        with self._lock:
            if self._closed or not self._sched.pending_work():
                self._armed = False
                return
        engine.push(self._loop_task, write_vars=[self._var])

    def wait_idle(self, timeout=None):
        """Block until the scheduler drains (engine-task completion plus a
        pending-work poll, since a new submit can re-arm the loop)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            engine.wait_for_var(self._var)
            if not self._sched.pending_work():
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            self.kick()
            time.sleep(0.001)

    def close(self):
        with self._lock:
            self._closed = True
        engine.wait_for_var(self._var)
