"""Hand-written TPU Pallas kernels for the hot ops.

Reference parity: the reference fuses attention/layernorm via cuDNN and
hand-written CUDA (src/operator/contrib); here the fused fast paths are
Mosaic/Pallas kernels targeting VMEM + MXU directly.

Kernels:
  * flash_attention — memory-efficient attention, online softmax, O(S) memory,
    grid (batch*heads, q_blocks, kv_blocks) with VMEM accumulators. Forward
    saves per-row logsumexp; backward is the FlashAttention-2 style pair of
    Pallas kernels (dk/dv over kv-blocks, dq over q-blocks) with in-kernel
    recompute of the probabilities — O(S) memory end to end.
  * flash_block_attention — (out, lse) blockwise partial with gradients
    through both outputs; the ring-attention building block (the lse
    cotangent folds into the Pallas backward as a delta shift).
  * fused_layer_norm — single-pass layernorm.

All kernels fall back to pure-XLA implementations off-TPU (CPU test mesh) or
for shapes that don't tile (seq not multiple of block after padding). Set
MXTPU_PALLAS_INTERPRET=1 to run the kernels in Pallas interpret mode on CPU
(used by tests to pin the kernel numerics without a chip).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from .. import _env
from ..observability.metrics_registry import registry as _metrics_registry
from ..tune import overrides as _tune_overrides

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
    # older jax spells it TPUCompilerParams; module-local alias keeps the
    # call sites on the current spelling without mutating jax's namespace
    _CompilerParams = getattr(pltpu, "CompilerParams",
                              getattr(pltpu, "TPUCompilerParams", None))
    if _CompilerParams is None:  # pallas too old for either spelling:
        _HAS_PALLAS = False      # route to the non-pallas fallback
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["flash_attention", "flash_block_attention", "fused_layer_norm",
           "attention_reference", "on_tpu", "conv1x1_bn_stats",
           "single_query_cached_attention", "ragged_paged_attention"]


def on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _interpret():
    """Pallas interpret mode: lets the CPU test mesh execute the real kernel
    bodies (slowly) so their numerics are pinned without TPU hardware."""
    return os.environ.get("MXTPU_PALLAS_INTERPRET") == "1"


def _pallas_ok(seq_len):
    if os.environ.get("MXTPU_PALLAS_DISABLE") == "1":  # A/B vs XLA path
        return False
    return (_HAS_PALLAS and (on_tpu() or _interpret())
            and seq_len % 128 == 0 and seq_len >= 128)


_breg = _metrics_registry()
_ignored_warned = set()          # (knob, value, dim): warn once each


def _note_ignored(source, knob, val, dim, fallback):
    """A forced block override the kernel cannot honour used to be
    SILENTLY dropped — the tuner (and any operator A/B-ing knobs) then
    measures the default config under the override's label. Count every
    dead override on `pallas_block_override_ignored{knob=}` and warn
    once per (knob, value, dim)."""
    _breg.counter("pallas_block_override_ignored", knob=knob).inc()
    key = (knob, val, dim)
    if key not in _ignored_warned:
        _ignored_warned.add(key)
        import warnings
        warnings.warn(
            f"{knob}={val} (from {source}) is incompatible with size "
            f"{dim}; using {fallback} — the override is DEAD",
            RuntimeWarning, stacklevel=4)


def _knob(name, env):
    """Resolve one tunable kernel knob: the autotuner's thread-local
    override scope (tune/overrides.py) wins, the MXTPU_* env var is the
    operator-facing fallback. Returns (value, source); 0 = unset."""
    cfg = _tune_overrides.current()
    if cfg is not None and name in cfg:
        return int(cfg[name]), "tune override"
    return _env.env_int(env, 0, minimum=0), "env"


def _block_sizes(sq, sk):
    """Largest tiling block (<=512) that divides each sequence length —
    bigger blocks amortise grid overhead and feed the MXU larger dots;
    override with MXTPU_FLASH_BLOCK_Q / MXTPU_FLASH_BLOCK_K (or a
    tune/overrides.py scope). A forced value that does not divide the
    sequence falls back LOUDLY (`pallas_block_override_ignored`)."""
    def auto(s):
        for b in (512, 256, 128):
            if s % b == 0:
                return b
        return 128

    def pick(s, name, env):
        forced, src = _knob(name, env)
        if forced and s % forced == 0:
            return min(forced, s)
        fb = auto(s)
        if forced:
            _note_ignored(src, env, forced, s, fb)
        return fb
    return (pick(sq, "flash_block_q", "MXTPU_FLASH_BLOCK_Q"),
            pick(sk, "flash_block_k", "MXTPU_FLASH_BLOCK_K"))


def _rpa_block_k(psize):
    """Sub-page K block of the ragged-paged-attention kernels (ISSUE
    20): the inner grid walks `psize // block` steps per page, each
    DMA-ing a (block, dh) tile — smaller blocks overlap compute with
    more, smaller DMAs; the default (= psize) keeps one page per step.
    MXTPU_RPA_BLOCK_K / tune override `rpa_block_k`; must divide the
    page size and keep the 8-sublane tile, else the default is used
    loudly."""
    forced, src = _knob("rpa_block_k", "MXTPU_RPA_BLOCK_K")
    if not forced:
        return psize
    if forced % 8 == 0 and 8 <= forced <= psize and psize % forced == 0:
        return forced
    _note_ignored(src, "MXTPU_RPA_BLOCK_K", forced, psize, psize)
    return psize


def _rpa_sublanes(W):
    """Padded query-row count of the WIDENED (multi-query verify) RPA
    launch: default rounds W up to the Mosaic 8-sublane tile; a larger
    forced value (MXTPU_RPA_SUBLANES / tune override `rpa_sublanes`)
    trades padded-row compute for bigger VPU tiles. Must be >= W and a
    multiple of 8, else the default is used loudly."""
    default = max(8, -(-W // 8) * 8)
    forced, src = _knob("rpa_sublanes", "MXTPU_RPA_SUBLANES")
    if not forced:
        return default
    if forced % 8 == 0 and forced >= W:
        return max(forced, 8)
    _note_ignored(src, "MXTPU_RPA_SUBLANES", forced, W, default)
    return default


def _sds(shape, dtype, *refs):
    """ShapeDtypeStruct whose vma is the union of the inputs' varying axes —
    under shard_map(check_vma=True) pallas_call out_shapes must carry vma
    or lowering refuses (and the try/except would silently fall back)."""
    vma = None
    try:
        sets = [jax.typeof(r).vma for r in refs]
        vma = frozenset().union(*sets) if sets else None
    except Exception:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


_warned_fallback = set()


def _warn_fallback(site, err):
    """The Pallas path raising and silently taking the XLA path cost a 10%
    bench regression once (r2); surface it loudly, once per site."""
    if site not in _warned_fallback:
        _warned_fallback.add(site)
        import warnings
        warnings.warn(f"pallas {site} kernel failed, using XLA fallback: "
                      f"{err!r}", RuntimeWarning, stacklevel=3)


# ---------------------------------------------------------------------------
# reference XLA attention (also the backward path + CPU fallback)
# ---------------------------------------------------------------------------
def attention_reference(q, k, v, causal=False, sm_scale=None, mask=None):
    """q,k,v: (B, H, S, D). Plain XLA attention — fused well by XLA, used as
    the fallback and as the recompute backward for the Pallas forward.

    mask: boolean (True = attend) or additive float (0 = attend, large
    negative = masked), broadcastable to (B, H, Sq, Sk)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        qi = lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0)
        kj = lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1)
        s = jnp.where(qi >= kj, s, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            s = jnp.where(mask, s, -1e30)
        else:  # additive convention
            s = s + mask.astype(s.dtype)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# ---------------------------------------------------------------------------
# Pallas flash attention forward
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(*refs, sm_scale, causal, block_q, block_k,
                      num_heads, has_lengths):
    """has_lengths: a scalar-prefetch (B,) int32 `kv_lengths` ref leads the
    arg list; key positions >= kv_lengths[b] are masked (padding mask) and
    fully-masked kv blocks are skipped dynamically."""
    if has_lengths:
        (vl_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        vl_ref = None
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qb = pl.program_id(1)
    q_start = qb * block_q
    k_start = kb * block_k
    vl = vl_ref[pl.program_id(0) // num_heads] if has_lengths else None

    def compute():
        # dots run in the INPUT dtype (bf16 on the bench path — 2x MXU rate
        # vs f32) with fp32 accumulation; softmax math stays fp32
        q = q_ref[0]                               # (bq, d)
        k = k_ref[0]                               # (bk, d)
        v = v_ref[0]                               # (bk, d)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qi = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kj = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= kj, s, -1e30)
        if has_lengths:
            kj = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kj < vl, s, -1e30)

        m_prev = m_scr[:, :1]                      # (bq, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                     # (bq, bk) fp32
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # skip kv blocks that are fully masked (above the causal diagonal /
    # entirely beyond the valid length)
    live = True
    if causal:
        live = k_start <= q_start + block_q - 1
    if has_lengths:
        live = jnp.logical_and(live, k_start < vl) if causal \
            else k_start < vl
    if causal or has_lengths:
        @pl.when(live)
        def _():
            compute()
    else:
        compute()

    @pl.when(kb == nk - 1)
    def _finalize():
        # guard: a row with every key masked (kv_length 0) has l == 0
        o_ref[0] = (acc_scr[:] /
                    jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)
        # lse broadcast across the 128-lane minor dim (Mosaic needs the last
        # two block dims (8,128)-aligned, so a (block_q,) vector can't be an
        # output on its own)
        lse_ref[0] = m_scr[:] + jnp.log(jnp.maximum(l_scr[:], 1e-30))


def _flash_fwd_pallas(q, k, v, causal, sm_scale, lengths=None,
                      block_q=None, block_k=None):
    """Returns (out, lse); lse is the per-row logsumexp of the scaled
    logits, shape (B*H, S, 128) fp32 with the value broadcast across the
    minor (lane) dim — the backward kernels' softmax residual.
    lengths: optional (B,) int32 kv valid lengths (padding mask).
    Sq and Sk may differ (cross-attention); causal requires Sq == Sk."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    if block_q is None or block_k is None:
        block_q, block_k = _block_sizes(sq, sk)
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)
    grid = (bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))
    has_lengths = lengths is not None
    kern = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_heads=h,
        has_lengths=has_lengths)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1 if has_lengths else 0,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, i, j, *_: (bh_, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, i, j, *_: (bh_, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, i, j, *_: (bh_, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, i, j, *_: (bh_, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda bh_, i, j, *_: (bh_, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    call = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            _sds((bh, sq, d), q.dtype, q, k, v),
            _sds((bh, sq, 128), jnp.float32, q, k, v),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )
    if has_lengths:
        out, lse = call(lengths.astype(jnp.int32), qr, kr, vr)
    else:
        out, lse = call(qr, kr, vr)
    return out.reshape(b, h, sq, d), lse


def flash_attention(q, k, v, causal=False, sm_scale=None, kv_lengths=None):
    """Fused attention. q,k,v: (B, H, S, D) -> (B, H, S, D).

    On TPU with S % 128 == 0 runs the Pallas flash kernel (O(S) memory,
    MXU matmuls in fp32 accumulation); otherwise the XLA reference path.

    kv_lengths: optional (B,) int32 per-sequence valid key length (the
    reference's padding mask expressed TPU-natively — key positions
    >= kv_lengths[b] are masked, and fully-masked kv blocks are skipped
    inside the kernel via scalar prefetch)."""
    if kv_lengths is None:
        return _flash_plain(q, k, v, causal, sm_scale)
    return _flash_vl(q, k, v, kv_lengths, causal, sm_scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_plain(q, k, v, causal=False, sm_scale=None):
    return _flash_attention_impl(q, k, v, causal, sm_scale)


def _lengths_mask(lengths, seq_len):
    """(B,) lengths -> (B, 1, 1, S) boolean mask for the XLA fallback."""
    pos = jnp.arange(seq_len)[None, :]
    return (pos < lengths[:, None])[:, None, None, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_vl(q, k, v, lengths, causal=False, sm_scale=None):
    return _flash_vl_impl(q, k, v, lengths, causal, sm_scale)


def _flash_vl_impl(q, k, v, lengths, causal, sm_scale):
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if _pallas_ok(q.shape[2]) and _pallas_ok(k.shape[2]):
        try:
            return _flash_fwd_pallas(q, k, v, causal, sm_scale,
                                     lengths=lengths)[0]
        except Exception as e:
            _warn_fallback("flash_fwd_vl", e)
    return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                               mask=_lengths_mask(lengths, k.shape[2]))


def _flash_attention_impl(q, k, v, causal, sm_scale):
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if _pallas_ok(q.shape[2]) and _pallas_ok(k.shape[2]):
        try:
            return _flash_fwd_pallas(q, k, v, causal, sm_scale)[0]
        except Exception as e:
            _warn_fallback("flash_fwd", e)
    return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)


# ---------------------------------------------------------------------------
# Pallas flash attention backward (FlashAttention-2 split):
#   kernel 1 — dk/dv: kv-blocks parallel, q-blocks innermost/sequential
#   kernel 2 — dq:    q-blocks parallel, kv-blocks innermost/sequential
# Both recompute p = exp(s - lse) from the forward's logsumexp, so nothing
# O(S^2) is ever materialised.
# ---------------------------------------------------------------------------
def _flash_bwd_dkv_kernel(*refs, sm_scale, causal, block_q, block_k,
                          num_heads, has_lengths):
    if has_lengths:
        (vl_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        vl_ref = None
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    kb = pl.program_id(1)
    qb = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qb * block_q
    k_start = kb * block_k
    vl = vl_ref[pl.program_id(0) // num_heads] if has_lengths else None

    def compute():
        q = q_ref[0]                               # (bq, d) input dtype
        k = k_ref[0]                               # (bk, d)
        v = v_ref[0]                               # (bk, d)
        do = do_ref[0]                             # (bq, d)
        lse = lse_ref[0][:, :1]                    # (bq, 1) lane-broadcast
        delta = delta_ref[0][:, :1]                # (bq, 1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qi = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kj = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= kj, s, -1e30)
        if has_lengths:
            kj = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kj < vl, s, -1e30)
        p = jnp.exp(s - lse).astype(do.dtype)      # (bq, bk)
        dv_scr[:] += jax.lax.dot_general(          # p^T @ dO
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(                  # dO @ V^T
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p.astype(jnp.float32) * (dp - delta)
              * sm_scale).astype(q.dtype)
        dk_scr[:] += jax.lax.dot_general(          # dS^T @ Q
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    live = True
    if causal:
        live = k_start <= q_start + block_q - 1
    if has_lengths:
        live = jnp.logical_and(live, k_start < vl) if causal \
            else k_start < vl
    if causal or has_lengths:
        @pl.when(live)
        def _():
            compute()
    else:
        compute()

    @pl.when(qb == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(*refs, sm_scale, causal, block_q, block_k,
                         num_heads, has_lengths):
    if has_lengths:
        (vl_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_scr) = refs
    else:
        vl_ref = None
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_scr) = refs
    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = qb * block_q
    k_start = kb * block_k
    vl = vl_ref[pl.program_id(0) // num_heads] if has_lengths else None

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qi = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kj = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= kj, s, -1e30)
        if has_lengths:
            kj = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kj < vl, s, -1e30)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_scr[:] += jax.lax.dot_general(          # dS @ K
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    live = True
    if causal:
        live = k_start <= q_start + block_q - 1
    if has_lengths:
        live = jnp.logical_and(live, k_start < vl) if causal \
            else k_start < vl
    if causal or has_lengths:
        @pl.when(live)
        def _():
            compute()
    else:
        compute()

    @pl.when(kb == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, g, causal, sm_scale, lengths=None,
                      block_q=None, block_k=None, delta_shift=None):
    """delta_shift (B,H,Sq) fp32, optional: subtracted from the standard
    delta = rowsum(dO∘O). Used by flash_block_attention to fold an lse
    cotangent into the backward (dS gains +g_lse∘p, i.e. delta -= g_lse)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    if block_q is None or block_k is None:
        block_q, block_k = _block_sizes(sq, sk)
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)
    gr = g.reshape(bh, sq, d)
    # delta_i = rowsum(dO ∘ O): the softmax-jacobian correction term; cheap
    # elementwise+reduce, left to XLA. Lane-broadcast to 128 like lse so the
    # block shape is Mosaic-tileable.
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1).reshape(bh, sq)
    if delta_shift is not None:
        delta = delta - delta_shift.astype(jnp.float32).reshape(bh, sq)
    delta = jnp.broadcast_to(delta[..., None], (bh, sq, 128))
    lse = jnp.broadcast_to(lse[..., None], (bh, sq, 128))  # compact residual
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)
    has_lengths = lengths is not None
    nsp = 1 if has_lengths else 0
    scal = (lengths.astype(jnp.int32),) if has_lengths else ()

    qspec = pl.BlockSpec((1, block_q, d), lambda b_, j, i, *_: (b_, i, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda b_, j, i, *_: (b_, j, 0))
    rowq = pl.BlockSpec((1, block_q, 128), lambda b_, j, i, *_: (b_, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          num_heads=h, has_lengths=has_lengths),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=nsp,
            grid=(bh, nk, nq),
            in_specs=[qspec, kspec, kspec, qspec, rowq, rowq],
            out_specs=[kspec, kspec],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
        ),
        out_shape=[_sds((bh, sk, d), q.dtype, q, k, v, g)] * 2,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*scal, qr, kr, vr, gr, lse, delta)

    qspec2 = pl.BlockSpec((1, block_q, d), lambda b_, i, j, *_: (b_, i, 0))
    kspec2 = pl.BlockSpec((1, block_k, d), lambda b_, i, j, *_: (b_, j, 0))
    rowq2 = pl.BlockSpec((1, block_q, 128), lambda b_, i, j, *_: (b_, i, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          num_heads=h, has_lengths=has_lengths),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=nsp,
            grid=(bh, nq, nk),
            in_specs=[qspec2, kspec2, kspec2, qspec2, rowq2, rowq2],
            out_specs=qspec2,
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=_sds((bh, sq, d), q.dtype, q, k, v, g),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*scal, qr, kr, vr, gr, lse, delta)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


def _flash_fwd_rule(q, k, v, causal, sm_scale):
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if _pallas_ok(q.shape[2]) and _pallas_ok(k.shape[2]):
        try:
            out, lse = _flash_fwd_pallas(q, k, v, causal, scale)
            # residual kept compact: (bh, sq), not the lane-broadcast
            # (bh, sq, 128) the kernel writes (128x the HBM held fwd->bwd)
            return out, (q, k, v, out, lse[..., 0])
        except Exception as e:
            _warn_fallback("flash_fwd", e)
    out = attention_reference(q, k, v, causal=causal, sm_scale=scale)
    return out, (q, k, v, None, None)


def _flash_bwd_rule(causal, sm_scale, res, g):
    q, k, v, o, lse = res
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if o is not None and _pallas_ok(q.shape[2]):
        try:
            return _flash_bwd_pallas(q, k, v, o, lse, g, causal, scale)
        except Exception as e:
            _warn_fallback("flash_bwd", e)
    # fallback: recompute-backward through the XLA reference
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(q_, k_, v_, causal=causal,
                                               sm_scale=scale), q, k, v)
    return vjp(g)


_flash_plain.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _flash_vl_fwd_rule(q, k, v, lengths, causal, sm_scale):
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if _pallas_ok(q.shape[2]) and _pallas_ok(k.shape[2]):
        try:
            out, lse = _flash_fwd_pallas(q, k, v, causal, scale,
                                         lengths=lengths)
            return out, (q, k, v, lengths, out, lse[..., 0])
        except Exception as e:
            _warn_fallback("flash_fwd_vl", e)
    out = attention_reference(q, k, v, causal=causal, sm_scale=scale,
                              mask=_lengths_mask(lengths, k.shape[2]))
    return out, (q, k, v, lengths, None, None)


def _flash_vl_bwd_rule(causal, sm_scale, res, g):
    import numpy as np
    q, k, v, lengths, o, lse = res
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    dlen = np.zeros(lengths.shape, dtype=jax.dtypes.float0)
    if o is not None and _pallas_ok(q.shape[2]):
        try:
            dq, dk, dv = _flash_bwd_pallas(q, k, v, o, lse, g, causal, scale,
                                           lengths=lengths)
            return dq, dk, dv, dlen
        except Exception as e:
            _warn_fallback("flash_bwd_vl", e)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(
            q_, k_, v_, causal=causal, sm_scale=scale,
            mask=_lengths_mask(lengths, k.shape[2])), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, dlen


_flash_vl.defvjp(_flash_vl_fwd_rule, _flash_vl_bwd_rule)


# ---------------------------------------------------------------------------
# flash block attention: (out, lse) with gradients through BOTH — the ring
# attention building block (partial softmax results merge across ring steps
# via lse, so the lse cotangent is nonzero: d lse/dS = p folds into the
# standard backward as delta -= g_lse).
# ---------------------------------------------------------------------------
def _block_fwd_xla(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0)
        kj = lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1)
        s = jnp.where(qi >= kj, s, -1e30)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)
    return out, lse


def _block_bwd_xla(q, k, v, out, lse, g, g_lse, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0)
        kj = lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1)
        s = jnp.where(qi >= kj, s, -1e30)
    p = jnp.exp(s - lse[..., None])
    gf = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
    dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v.astype(jnp.float32))
    delta = (jnp.sum(gf * out.astype(jnp.float32), axis=-1)
             - g_lse.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_block_impl(q, k, v, causal, sm_scale):
    """Shared primal: (out, lse, used_pallas)."""
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if _pallas_ok(q.shape[2]) and _pallas_ok(k.shape[2]):
        try:
            out, lse = _flash_fwd_pallas(q, k, v, causal, scale)
            b, h, s, _ = q.shape
            return out, lse[..., 0].reshape(b, h, s), True
        except Exception as e:
            _warn_fallback("flash_block_fwd", e)
    out, lse = _block_fwd_xla(q, k, v, causal, scale)
    return out, lse, False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_block_attention(q, k, v, causal=False, sm_scale=None):
    """Blockwise attention partial: returns (out, lse) where `out` is the
    softmax attention over ONLY these keys and `lse` its per-row logsumexp
    of scaled logits. Partials from disjoint key sets merge exactly:
        lse = logaddexp(lse_a, lse_b)
        out = out_a*exp(lse_a-lse) + out_b*exp(lse_b-lse)
    — the combine used by parallel/ring_attention.py. Pallas on TPU-tiling
    shapes, XLA otherwise; differentiable through BOTH outputs."""
    out, lse, _ = _flash_block_impl(q, k, v, causal, sm_scale)
    return out, lse


def _flash_block_fwd_rule(q, k, v, causal, sm_scale):
    out, lse, used_pallas = _flash_block_impl(q, k, v, causal, sm_scale)
    return (out, lse), (q, k, v, out, lse, used_pallas)


def _flash_block_bwd_rule(causal, sm_scale, res, cts):
    q, k, v, out, lse, used_pallas = res
    g, g_lse = cts
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if used_pallas:
        try:
            return _flash_bwd_pallas(
                q, k, v, out, lse.reshape(-1, lse.shape[-1]), g, causal,
                scale, delta_shift=g_lse)
        except Exception as e:
            _warn_fallback("flash_block_bwd", e)
    return _block_bwd_xla(q, k, v, out, lse, g, g_lse, causal, scale)


flash_block_attention.defvjp(_flash_block_fwd_rule, _flash_block_bwd_rule)


# ---------------------------------------------------------------------------
# single-query cached attention + ragged paged attention (ISSUE 6)
#
# `single_query_cached_attention` is the SHARED decode-attention math: the
# dense-cache incremental decoder (models/transformer.py decode_step) and the
# serving engine's paged-KV fallback path both call this exact function, so
# a request decoded through the paged cache is bitwise-identical to one
# decoded through the dense cache (given the same context width).
#
# `ragged_paged_attention` (arXiv:2604.15464 style) lets requests of
# DIFFERENT lengths share one attention launch per decode step: each slot
# owns a page table into a fixed device-resident page pool, and the Pallas
# kernel walks that table with scalar-prefetch index maps (the page id is
# read from SMEM before the DMA is issued, so the gather never materialises
# a dense (S, Lmax) context in HBM). Off-TPU (the CPU test mesh) a pure-lax
# gather fallback reproduces the same numbers through the shared math above.
# ---------------------------------------------------------------------------
def single_query_cached_attention(qh, kc, vc, mask=None):
    """Attention of a single query token over a cached context.

    qh: (B, H, 1, dh); kc/vc: (B, H, L, dh); mask: boolean broadcastable to
    (B, H, 1, L), True = attend (None = attend everywhere). Returns
    (B, H, 1, dh). fp32 score accumulation, softmax in fp32, output in the
    value dtype — the decode-path contract shared by the dense and paged
    decoders."""
    dh = qh.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kc,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(dh))
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(vc.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, vc)


def _dequant_gathered(pages, page_tables, scales, dtype):
    """Gather (S, npages, psize, H, dh) pages; with per-page (P, H)
    `scales` (int8 KV mode, ISSUE 14) dequantize the gathered context —
    never the whole pool — into `dtype`."""
    ctx = pages[page_tables]
    if scales is not None:
        ctx = ctx.astype(dtype) * scales[page_tables][:, :, None, :, None]
    return ctx


def _paged_attention_lax(q, k_pages, v_pages, page_tables, lengths,
                         k_scales=None, v_scales=None):
    """Pure-lax fallback: gather each slot's pages into a dense context,
    then run the SAME shared math as the dense decoder (so CPU serving is
    bitwise-parity with `decode_step` on equal context width).

    q: (S, H, dh); k_pages/v_pages: (P, psize, H, dh);
    page_tables: (S, npages) int32; lengths: (S,) int32 valid positions
    (including the current token). k_scales/v_scales: optional (P, H)
    per-page/per-head dequant scales for int8 page pools (ISSUE 14) —
    only the GATHERED context dequantizes, never the pool. Returns
    (S, H, dh)."""
    S, H, dh = q.shape
    psize = k_pages.shape[1]
    npages = page_tables.shape[1]
    L = npages * psize
    kc = _dequant_gathered(k_pages, page_tables, k_scales, q.dtype) \
        .reshape(S, L, H, dh).transpose(0, 2, 1, 3)
    vc = _dequant_gathered(v_pages, page_tables, v_scales, q.dtype) \
        .reshape(S, L, H, dh).transpose(0, 2, 1, 3)
    mask = (jnp.arange(L)[None, :] < lengths[:, None])[:, None, None, :]
    return single_query_cached_attention(q[:, :, None, :], kc, vc,
                                         mask)[:, :, 0]


def _paged_attention_lax_multi(q, k_pages, v_pages, page_tables, lengths,
                               k_scales=None, v_scales=None):
    """Pure-lax fallback for the WIDENED (speculative-verify) launch:
    gather each slot's pages into a dense context, then the SAME shared
    math as `_paged_attention_lax`, with one extra query axis.

    q: (S, W, H, dh) — W query tokens per slot at consecutive positions;
    lengths: (S,) int32 keys visible to query 0 (including its own
    position); query i sees exactly `lengths + i` keys, which is the
    ragged-per-slot-query-length shape speculative verification and
    chunked prompt prefill need. Returns (S, W, H, dh)."""
    S, W, H, dh = q.shape
    psize = k_pages.shape[1]
    npages = page_tables.shape[1]
    L = npages * psize
    kc = _dequant_gathered(k_pages, page_tables, k_scales, q.dtype) \
        .reshape(S, L, H, dh).transpose(0, 2, 1, 3)
    vc = _dequant_gathered(v_pages, page_tables, v_scales, q.dtype) \
        .reshape(S, L, H, dh).transpose(0, 2, 1, 3)
    vis = lengths[:, None] + jnp.arange(W, dtype=lengths.dtype)[None, :]
    mask = (jnp.arange(L)[None, None, :]
            < vis[:, :, None])[:, None, :, :]        # (S, 1, W, L)
    qh = q.transpose(0, 2, 1, 3)                     # (S, H, W, dh)
    out = single_query_cached_attention(qh, kc, vc, mask)
    return out.transpose(0, 2, 1, 3)


def _rpa_kernel(*refs, psize, block_k, num_heads, sm_scale, quant=False):
    """Ragged paged attention, one (slot, head) per grid row, one
    (block_k, dh) KV tile per inner step — `psize // block_k` steps per
    page (block_k == psize is the one-page-per-step default; the
    autotuner searches smaller tiles, `_rpa_block_k`). The page id for
    (slot, page_slot) was already consumed by the BlockSpec index maps
    (scalar prefetch); here we only need the slot's valid length for
    masking and dead-page skipping.

    quant (ISSUE 14): the page pools are int8 and two extra scalar-
    prefetch refs carry the per-page/per-head dequant scales as BITCAST
    int32 (scalar prefetch is SMEM/int territory; `bitcast_convert_type`
    recovers the f32 in-kernel) — the page block dequantizes in VMEM
    right after the DMA, so HBM only ever moves int8 bytes."""
    if quant:
        (pt_ref, len_ref, ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        ks_ref = vs_ref = None
        (pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    npb = psize // block_k                  # sub-page blocks per page
    g = pl.program_id(0)                    # slot * num_heads + head
    j = pl.program_id(1)                    # page slot * npb + block
    nj = pl.num_programs(1)
    s_idx = g // num_heads
    length = len_ref[s_idx]
    k_start = j * block_k
    if quant:
        page = pt_ref[s_idx, j // npb]
        h_idx = g % num_heads
        ks = lax.bitcast_convert_type(ks_ref[h_idx, page], jnp.float32)
        vs = lax.bitcast_convert_type(vs_ref[h_idx, page], jnp.float32)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # blocks entirely beyond the valid length are skipped — the ragged
    # part: a 3-token request costs one block of work while its
    # 300-token neighbour walks its whole table, in the same launch
    @pl.when(k_start < length)
    def _compute():
        q = q_ref[0]                        # (1, dh)
        k = k_ref[0, 0]                     # (block_k, dh)
        v = v_ref[0, 0]                     # (block_k, dh)
        if quant:
            # dequantize in VMEM, same element-wise form as the lax
            # fallback's gathered dequant (parity pinned in interpret)
            k = k.astype(jnp.float32) * ks
            v = v.astype(jnp.float32) * vs
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        kj = k_start + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        s = jnp.where(kj < length, s, -1e30)
        m_prev = m_scr[:1, :1]              # (1, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)              # (1, psize) fp32
        l_new = alpha * l_scr[:1, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc_scr[:1] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # (8, x) scratch: every row carries the running value (a (1, x)
        # block would violate Mosaic's (8, 128) min tile); row 0 is read
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = jnp.broadcast_to(acc, acc_scr.shape)

    @pl.when(j == nj - 1)
    def _finalize():
        # a slot with length 0 (empty decode slot) has l == 0: guard the
        # divide; its output is garbage the scheduler never reads
        o_ref[0] = (acc_scr[:1] /
                    jnp.maximum(l_scr[:1, :1], 1e-30)).astype(o_ref.dtype)


def _scale_bits(scales):
    """(P, H) f32 scales -> (H, P) int32 bitcast for scalar prefetch
    (SMEM carries ints; the kernel bitcasts the f32 back)."""
    return lax.bitcast_convert_type(
        scales.astype(jnp.float32).T, jnp.int32)


def _rpa_pallas(q, k_pages, v_pages, page_tables, lengths, sm_scale,
                k_scales=None, v_scales=None):
    S, H, dh = q.shape
    psize = k_pages.shape[1]
    npages = page_tables.shape[1]
    quant = k_scales is not None
    bk = _rpa_block_k(psize)
    npb = psize // bk               # sub-page K blocks per page
    qr = q.reshape(S * H, 1, dh)
    # page-major layout for the kernel: (H, P, psize, dh) so one (slot,
    # head, page) block is a contiguous (psize, dh) tile
    kr = k_pages.transpose(2, 0, 1, 3)
    vr = v_pages.transpose(2, 0, 1, 3)
    grid = (S * H, npages * npb)
    kern = functools.partial(_rpa_kernel, psize=psize, block_k=bk,
                             num_heads=H, sm_scale=sm_scale, quant=quant)
    nsp = 4 if quant else 2
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=nsp,        # page tables + lengths (+ scales)
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, dh), lambda g, j, pt, ln, *_: (g, 0, 0)),
            # the paged gather: the page id comes from the scalar-
            # prefetched table, so the DMA fetches exactly the pages the
            # slot owns — never a dense (S, Lmax) context; with bk <
            # psize the dim-2 block index walks the npb tiles of a page
            pl.BlockSpec((1, 1, bk, dh),
                         lambda g, j, pt, ln, *_, _h=H, _b=npb:
                         (g % _h, pt[g // _h, j // _b], j % _b, 0)),
            pl.BlockSpec((1, 1, bk, dh),
                         lambda g, j, pt, ln, *_, _h=H, _b=npb:
                         (g % _h, pt[g // _h, j // _b], j % _b, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, dh),
                               lambda g, j, pt, ln, *_: (g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((8, dh), jnp.float32),
        ],
    )
    scal = (page_tables.astype(jnp.int32), lengths.astype(jnp.int32))
    if quant:
        scal += (_scale_bits(k_scales), _scale_bits(v_scales))
    out = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=_sds((S * H, 1, dh), q.dtype, q, k_pages, v_pages),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(*scal, qr, kr, vr)
    return out.reshape(S, H, dh)


def _rpa_multi_kernel(*refs, psize, block_k, num_heads, sm_scale,
                      quant=False):
    """Widened ragged paged attention (ISSUE 12): W query rows per
    (slot, head) grid row, one KV page per inner step. Query row i masks
    keys at `len_ref[slot] + i` — consecutive positions, so a single
    per-slot scalar carries the whole ragged query-length structure.
    Rows beyond a slot's real window produce garbage nobody commits.
    quant: int8 page pools with bitcast-int32 scalar-prefetch scales,
    dequantized in VMEM (same scheme as `_rpa_kernel`)."""
    if quant:
        (pt_ref, len_ref, ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        ks_ref = vs_ref = None
        (pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    npb = psize // block_k                  # sub-page blocks per page
    g = pl.program_id(0)                    # slot * num_heads + head
    j = pl.program_id(1)                    # page slot * npb + block
    nj = pl.num_programs(1)
    s_idx = g // num_heads
    length = len_ref[s_idx]                 # keys visible to query row 0
    k_start = j * block_k
    wp = q_ref.shape[1]                     # padded query rows (>= 8)
    if quant:
        page = pt_ref[s_idx, j // npb]
        h_idx = g % num_heads
        ks = lax.bitcast_convert_type(ks_ref[h_idx, page], jnp.float32)
        vs = lax.bitcast_convert_type(vs_ref[h_idx, page], jnp.float32)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # a block is live when ANY query row can see it: row wp-1 sees
    # length + wp - 1 keys
    @pl.when(k_start < length + wp - 1)
    def _compute():
        q = q_ref[0]                        # (wp, dh)
        k = k_ref[0, 0]                     # (block_k, dh)
        v = v_ref[0, 0]                     # (block_k, dh)
        if quant:
            k = k.astype(jnp.float32) * ks
            v = v.astype(jnp.float32) * vs
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        qi = lax.broadcasted_iota(jnp.int32, (wp, block_k), 0)
        kj = k_start + lax.broadcasted_iota(jnp.int32, (wp, block_k), 1)
        s = jnp.where(kj < length + qi, s, -1e30)
        m_prev = m_scr[:, :1]               # (wp, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)              # (wp, block_k) fp32
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nj - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] /
                    jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def _rpa_multi_pallas(q, k_pages, v_pages, page_tables, lengths, sm_scale,
                      k_scales=None, v_scales=None):
    S, W, H, dh = q.shape
    psize = k_pages.shape[1]
    npages = page_tables.shape[1]
    quant = k_scales is not None
    bk = _rpa_block_k(psize)
    npb = psize // bk               # sub-page K blocks per page
    # pad the query-row dim to the Mosaic 8-sublane tile (or the forced
    # tuner sublane count); extra rows attend a few more (valid-page)
    # keys and are sliced away below
    wp = _rpa_sublanes(W)
    qr = q.transpose(0, 2, 1, 3).reshape(S * H, W, dh)
    if wp != W:
        qr = jnp.pad(qr, ((0, 0), (0, wp - W), (0, 0)))
    kr = k_pages.transpose(2, 0, 1, 3)      # (H, P, psize, dh)
    vr = v_pages.transpose(2, 0, 1, 3)
    grid = (S * H, npages * npb)
    kern = functools.partial(_rpa_multi_kernel, psize=psize, block_k=bk,
                             num_heads=H, sm_scale=sm_scale, quant=quant)
    nsp = 4 if quant else 2
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=nsp,        # page tables + lengths (+ scales)
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, wp, dh), lambda g, j, pt, ln, *_: (g, 0, 0)),
            pl.BlockSpec((1, 1, bk, dh),
                         lambda g, j, pt, ln, *_, _h=H, _b=npb:
                         (g % _h, pt[g // _h, j // _b], j % _b, 0)),
            pl.BlockSpec((1, 1, bk, dh),
                         lambda g, j, pt, ln, *_, _h=H, _b=npb:
                         (g % _h, pt[g // _h, j // _b], j % _b, 0)),
        ],
        out_specs=pl.BlockSpec((1, wp, dh),
                               lambda g, j, pt, ln, *_: (g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((wp, 128), jnp.float32),
            pltpu.VMEM((wp, 128), jnp.float32),
            pltpu.VMEM((wp, dh), jnp.float32),
        ],
    )
    scal = (page_tables.astype(jnp.int32), lengths.astype(jnp.int32))
    if quant:
        scal += (_scale_bits(k_scales), _scale_bits(v_scales))
    out = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=_sds((S * H, wp, dh), q.dtype, q, k_pages, v_pages),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(*scal, qr, kr, vr)
    return out[:, :W].reshape(S, H, W, dh).transpose(0, 2, 1, 3)


def _rpa_pallas_ok(psize):
    if os.environ.get("MXTPU_PALLAS_DISABLE") == "1":
        return False
    return (_HAS_PALLAS and (on_tpu() or _interpret())
            and psize % 8 == 0 and psize >= 8)


def ragged_paged_attention(q, k_pages, v_pages, page_tables, lengths,
                           sm_scale=None, k_scales=None, v_scales=None):
    """One shared attention launch per decode step over a paged KV cache.

    q: (S, H, dh) — ONE query token per decode slot — or (S, W, H, dh)
    (ISSUE 12): W query tokens per slot at CONSECUTIVE positions, the
    ragged per-slot-query-length shape speculative verification and
    chunked prompt prefill use (query i of a slot sees `lengths + i`
    keys; rows past a slot's real window compute garbage nobody reads).
    k_pages/v_pages: (P, psize, H, dh) fixed-size page pools;
    page_tables: (S, npages) int32 page ids per slot (unused entries
    must point at a valid page — the pool's reserved null page 0);
    lengths: (S,) int32 valid cached positions per slot INCLUDING the
    current (first) token. Returns (S, H, dh) or (S, W, H, dh).

    k_scales/v_scales (ISSUE 14): per-page/per-head (P, H) f32 dequant
    scales for int8 page pools. The Pallas kernels carry them through
    scalar prefetch (bitcast int32) and dequantize each page block in
    VMEM after the DMA — HBM traffic stays int8, the dequant rides free
    inside the kernel; the lax fallback dequantizes only the GATHERED
    context.

    On TPU (or MXTPU_PALLAS_INTERPRET=1) runs the Pallas kernel: the page
    table rides in scalar-prefetch SMEM and the BlockSpec index maps read
    it to DMA exactly the owned pages, skipping pages beyond each slot's
    length — mixed-length slots share one launch. Elsewhere the pure-lax
    gather fallback reproduces the same numbers through
    `single_query_cached_attention` (inference-only; no custom vjp).

    Tunable knobs (ISSUE 20; MXTPU_RPA_BLOCK_K / MXTPU_RPA_SUBLANES or a
    tune/overrides.py scope): sub-page K tile size of the inner grid
    (`_rpa_block_k`) and the padded query-row count of the widened form
    (`_rpa_sublanes`). Invalid values fall back loudly
    (`pallas_block_override_ignored`)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if (k_scales is None) != (v_scales is None):
        raise ValueError("k_scales and v_scales must be given together")
    if q.ndim == 4:
        if _rpa_pallas_ok(k_pages.shape[1]):
            try:
                return _rpa_multi_pallas(q, k_pages, v_pages, page_tables,
                                         lengths, sm_scale,
                                         k_scales=k_scales,
                                         v_scales=v_scales)
            except Exception as e:
                _warn_fallback("ragged_paged_multi", e)
        return _paged_attention_lax_multi(q, k_pages, v_pages, page_tables,
                                          lengths, k_scales=k_scales,
                                          v_scales=v_scales)
    if _rpa_pallas_ok(k_pages.shape[1]):
        try:
            return _rpa_pallas(q, k_pages, v_pages, page_tables, lengths,
                               sm_scale, k_scales=k_scales,
                               v_scales=v_scales)
        except Exception as e:
            _warn_fallback("ragged_paged", e)
    return _paged_attention_lax(q, k_pages, v_pages, page_tables, lengths,
                                k_scales=k_scales, v_scales=v_scales)


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------
def _ln_kernel(x_ref, g_ref, b_ref, o_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y = xc * rstd
    o_ref[:] = (y * g_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ln(x, gamma, beta, eps):
    y, _m, _r = _fused_ln_fwd_impl(x, gamma, beta, eps)
    return y


def _fused_ln_fwd_impl(x, gamma, beta, eps):
    d = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    lead = x.shape[:-1]
    if (_HAS_PALLAS and (on_tpu() or _interpret()) and d % 128 == 0
            and rows % 8 == 0 and rows >= 8):
        br = min(256, rows)
        while rows % br:
            br //= 2
        x2 = x.reshape(rows, d)
        out, mean, rstd = pl.pallas_call(
            functools.partial(_ln_kernel, eps=eps),
            grid=(rows // br,),
            in_specs=[
                pl.BlockSpec((br, d), lambda i: (i, 0)),
                pl.BlockSpec((d,), lambda i: (0,)),
                pl.BlockSpec((d,), lambda i: (0,)),
            ],
            out_specs=[
                pl.BlockSpec((br, d), lambda i: (i, 0)),
                pl.BlockSpec((br, 1), lambda i: (i, 0)),
                pl.BlockSpec((br, 1), lambda i: (i, 0)),
            ],
            out_shape=[
                _sds((rows, d), x.dtype, x, gamma, beta),
                _sds((rows, 1), jnp.float32, x, gamma, beta),
                _sds((rows, 1), jnp.float32, x, gamma, beta),
            ],
            interpret=_interpret(),
        )(x2, gamma, beta)
        return (out.reshape(x.shape), mean.reshape(lead + (1,)),
                rstd.reshape(lead + (1,)))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y = ((xc * rstd) * gamma.astype(jnp.float32)
         + beta.astype(jnp.float32)).astype(x.dtype)
    return y, mean, rstd


def _fused_ln_vjp_fwd(x, gamma, beta, eps):
    y, mean, rstd = _fused_ln_fwd_impl(x, gamma, beta, eps)
    return y, (x, gamma, mean, rstd)


def _fused_ln_vjp_bwd(eps, res, dy):
    x, gamma, mean, rstd = res
    red = tuple(range(x.ndim - 1))
    xhat = (x.astype(jnp.float32) - mean) * rstd
    dyf = dy.astype(jnp.float32)
    dgamma = jnp.sum(dyf * xhat, axis=red)
    dbeta = jnp.sum(dyf, axis=red)
    dxhat = dyf * gamma.astype(jnp.float32)
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = (dxhat - m1 - xhat * m2) * rstd
    return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


_fused_ln.defvjp(_fused_ln_vjp_fwd, _fused_ln_vjp_bwd)


def fused_layer_norm(x, gamma, beta, eps=1e-5):
    """LayerNorm over the last axis. Pallas single-pass forward on TPU (XLA
    fallback elsewhere) with a closed-form custom-vjp backward, so it is
    trainable on the Pallas path too."""
    return _fused_ln(x, gamma, beta, float(eps))


# ---------------------------------------------------------------------------
# experimental: 1x1-conv (matmul) with BN-stats epilogue
# ---------------------------------------------------------------------------
def _conv1x1_stats_kernel(x_ref, w_ref, y_ref, s_ref, q_ref):
    i = pl.program_id(0)
    x = x_ref[...]                                          # (bm, K)
    w = w_ref[...]                                          # (K, N)
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)   # (bm, N) f32
    y_ref[...] = y.astype(y_ref.dtype)
    s = jnp.sum(y, axis=0)
    q = jnp.sum(y * y, axis=0)

    @pl.when(i == 0)
    def _init():
        s_ref[...] = jnp.zeros_like(s_ref)
        q_ref[...] = jnp.zeros_like(q_ref)

    # (8, N) accumulator: every row carries the full total (a (1, N)
    # block would violate Mosaic's (8, 128) min tile); row 0 is read
    s_ref[...] += jnp.broadcast_to(s[None, :], s_ref.shape)
    q_ref[...] += jnp.broadcast_to(q[None, :], q_ref.shape)


def conv1x1_bn_stats(x2d, w, bm=1024):
    """EXPERIMENTAL (perf probe, not wired into models): 1x1-conv as a
    (M, K) @ (K, N) matmul that computes the per-channel fp32 BN stats
    (mean, E[y^2]) WHILE each output tile is still in VMEM — deleting
    the separate stats pass's full HBM read of y. tools/
    probe_fused_convbn.py carries the keep-or-reject timings vs XLA
    conv + fused reduce (docs/PERF.md); numerics pinned in
    tests/test_pallas.py. Returns (y (M, N) in x's dtype, mean (N,) f32,
    meansq (N,) f32)."""
    if not (_HAS_PALLAS and (on_tpu() or _interpret())):
        # match the kernel's numerics: fp32 accumulate + fp32 stats,
        # THEN cast y — bf16-rounded stats would diverge from the TPU
        # path (and meansq - mean^2 could even go slightly negative)
        yf = jnp.dot(x2d, w, preferred_element_type=jnp.float32)
        return (yf.astype(x2d.dtype), jnp.mean(yf, 0),
                jnp.mean(yf * yf, 0))
    m, k = x2d.shape
    n = w.shape[1]
    bm = min(bm, m)
    pad = (-m) % bm
    xp = jnp.pad(x2d, ((0, pad), (0, 0))) if pad else x2d
    y, s, q = pl.pallas_call(
        _conv1x1_stats_kernel,
        grid=(xp.shape[0] // bm,),
        in_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0)),
                  pl.BlockSpec((k, n), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((bm, n), lambda i: (i, 0)),
                   pl.BlockSpec((8, n), lambda i: (0, 0)),
                   pl.BlockSpec((8, n), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((xp.shape[0], n), x2d.dtype),
                   jax.ShapeDtypeStruct((8, n), jnp.float32),
                   jax.ShapeDtypeStruct((8, n), jnp.float32)],
        interpret=_interpret(),
    )(xp, w)
    inv = 1.0 / m
    return y[:m], s[0] * inv, q[0] * inv
