"""INT8 quantization tests (SURVEY.md §2 #49; reference:
tests/python/quantization/test_quantization.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.gluon import nn


def test_quantize_dequantize_roundtrip():
    x = nd.array(np.linspace(-2.0, 2.0, 64).astype(np.float32))
    xq, mn, mx_ = q.quantize(x)
    assert "int8" in str(xq.dtype)
    back = q.dequantize(xq, mn, mx_)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=2.0 / 127)


def test_quantized_dense_matches_fp():
    mx.random.seed(0)
    dense = nn.Dense(16, in_units=32)
    dense.initialize()
    qd = q.QuantizedDense(dense)
    assert str(qd.wq.dtype) == "int8"
    x = nd.random.uniform(-1, 1, shape=(4, 32))
    y_fp = dense(x).asnumpy()
    y_q = qd(x).asnumpy()
    # int8 symmetric: ~1% of dynamic range
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.05, err


def test_quantized_conv_matches_fp():
    mx.random.seed(1)
    conv = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=4)
    conv.initialize()
    x = nd.random.uniform(-1, 1, shape=(2, 4, 8, 8))
    y_fp = conv(x).asnumpy()
    qc = q.QuantizedConv2D(conv)
    y_q = qc(x).asnumpy()
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.05, err


def test_quantize_net_end_to_end():
    mx.random.seed(2)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(10, in_units=32))
    net.initialize()
    x = nd.random.uniform(-1, 1, shape=(8, 16))
    y_fp = net(x).asnumpy()
    qnet = q.quantize_net(net)
    assert len(qnet.quantized_layers) == 2
    y_q = qnet(x).asnumpy()
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.1, err
    # argmax (classification decision) should essentially agree
    agree = (y_fp.argmax(1) == y_q.argmax(1)).mean()
    assert agree >= 0.75


def test_quantize_net_calibration_freezes_scales():
    mx.random.seed(3)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4))
    net.initialize()
    calib = [nd.random.uniform(-1, 1, shape=(4, 4)) for _ in range(3)]
    qnet = q.quantize_net(net, calib_data=calib, num_calib_batches=3)
    (layer,) = qnet.quantized_layers
    assert layer._act_scale is not None and layer._act_scale > 0
    x = nd.random.uniform(-1, 1, shape=(4, 4))
    err = np.abs(net(x).asnumpy() - qnet(x).asnumpy()).max()
    assert err < 0.1


def test_quantize_net_exclude_layers():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net.initialize()
    qnet = q.quantize_net(net, exclude_layers=["1"])
    assert len(qnet.quantized_layers) == 1


def test_quantize_net_no_quantizable_raises():
    net = nn.HybridSequential()
    net.add(nn.Dropout(0.5))
    with pytest.raises(Exception):
        q.quantize_net(net)


def test_quantize_net_nested_sequential():
    """Nested Sequential containers are rewired too (not silently fp)."""
    mx.random.seed(4)
    inner = nn.HybridSequential()
    inner.add(nn.Dense(16, activation="relu", in_units=8))
    net = nn.HybridSequential()
    net.add(inner, nn.Dense(4, in_units=16))
    net.initialize()
    x = nd.random.uniform(-1, 1, shape=(4, 8))
    y_fp = net(x).asnumpy()
    qnet = q.quantize_net(net)
    assert len(qnet.quantized_layers) == 2
    y_q = qnet(x).asnumpy()
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.1, err


def test_quantize_net_custom_block_refused():
    """Quantizable layers hidden in a custom block raise instead of
    silently running fp32."""
    class Custom(nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.fc = nn.Dense(4, in_units=4)

        def hybrid_forward(self, F, x):
            return self.fc(x)

    net = nn.HybridSequential()
    net.add(Custom())
    net.initialize()
    with pytest.raises(Exception):
        q.quantize_net(net)


def test_quantized_conv_dilation_and_groups():
    mx.random.seed(5)
    conv = nn.Conv2D(8, kernel_size=3, padding=2, dilation=2, groups=2,
                     in_channels=4)
    conv.initialize()
    x = nd.random.uniform(-1, 1, shape=(2, 4, 8, 8))
    y_fp = conv(x).asnumpy()
    qc = q.QuantizedConv2D(conv)
    y_q = qc(x).asnumpy()
    assert y_q.shape == y_fp.shape
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.05, err


def test_quantized_dense_sigmoid_activation():
    dense = nn.Dense(4, activation="sigmoid", in_units=4)
    dense.initialize()
    x = nd.random.uniform(-1, 1, shape=(2, 4))
    y_fp = dense(x).asnumpy()
    y_q = q.QuantizedDense(dense)(x).asnumpy()
    np.testing.assert_allclose(y_fp, y_q, atol=0.02)
