"""Compile observatory: per-executable compile + HLO telemetry and the
persistent compilation cache (ISSUE 11).

Every jitted executable the framework owns — the captured training step
(cachedop.py, replicated or rule-sharded), the serve prefill/decode pair
(serve/decode.py), the fused multi-tensor update kernels
(optimizer/multi_tensor.py), the cached jitted backward (autograd.py) —
is wrapped in `instrument(jax.jit(...), "<executable>")`. The wrapper:

  * detects each compilation (the jit executable cache grew during the
    dispatch) and records `compiles{executable=}` plus a
    `compile_seconds{executable=}` histogram of the compiling call's
    wall clock (trace + XLA compile + first execution — the latency a
    training loop actually stalls for);
  * attributes jax's own backend-compile duration events to the
    executable that was dispatching (`compile_backend_seconds{executable=}`
    — pure XLA time, no first-step execution in it);
  * lowers-and-inspects the OPTIMIZED HLO of the fresh executable (an
    AOT `lower().compile()` against abstract avals — the jaxpr re-trace
    is cached, so traced python bodies do NOT re-run; the duplicate XLA
    compile is what the inspection costs, absorbed by the persistent
    cache when enabled) and publishes `hlo_fusions{executable=}`,
    `hlo_collectives{executable=,op=}`, `hlo_collective_total`,
    `hlo_copies`, `hlo_aliased_inputs` (donation health: every aliased
    input is a donated buffer XLA updates in place instead of copying),
    `hlo_bytes` (module text size) and `cost_analysis()` flops/bytes
    where the backend provides them;
  * emits a `compile.<executable>` Chrome-trace 'X' span over the
    compiling dispatch when the tracer is active, so compiles are
    visible in the trace next to the steps they stall.

`tools/check_fusion.py` budgets these counts in tier-1 the way
`check_dispatch.py` budgets dispatches (docs/OBSERVABILITY.md "Compile
observatory").

Persistent compilation cache: `set_compilation_cache(dir)` (exported as
`mx.set_compilation_cache`; env `MXTPU_COMPILE_CACHE=dir` wires it at
import) points jax's disk cache at `dir`, so a second process compiling
the same program deserialises from disk instead of re-running XLA —
fleet-scale cold starts hit disk. `compile_cache_hits` /
`compile_cache_misses` counters track the disk cache from jax's own
monitoring events; `compile_cache_stats()` reads them.

Inspection policy (`MXTPU_HLO_TELEMETRY`): ``auto`` (default) inspects
the FIRST compile of each executable name per process — enough for the
metric families and a bounded cost; ``1``/``always`` inspects every
compile (what check_fusion forces); ``0`` disables. Long compiles
(over `MXTPU_HLO_MAX_S`, default 20s) skip inspection unless the
persistent cache is enabled (then the duplicate compile is a disk hit);
skips are counted on `hlo_inspect_skipped{executable=}`.
"""
from __future__ import annotations

import os
import re
import threading
import weakref
from time import perf_counter_ns

import jax

from .. import _env
from . import tracer as _tracer
from .metrics_registry import registry as _registry

__all__ = ["instrument", "InstrumentedJit", "inspect_hlo_text",
           "analyze_jit", "analyze_compiled", "set_compilation_cache",
           "compilation_cache_dir", "compile_cache_stats", "executables",
           "instrumented", "COLLECTIVE_OPS", "set_dispatch_hook",
           "dispatch_hook"]

# HLO collective opcodes tallied into hlo_collectives{op=}; async
# ("-start") forms count toward the same op, "-done" halves do not.
COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                  "all-to-all", "collective-permute")

_reg = _registry()
_cache_hits = _reg.counter("compile_cache_hits")
_cache_misses = _reg.counter("compile_cache_misses")

_tl = threading.local()          # .label: executable currently dispatching
                                 # .inspecting: inside an AOT inspection
                                 # .cache_pending: disk-cache lookup open
_inspected = set()               # names inspected at least once ("auto")

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_REQ_EVENT = "/jax/compilation_cache/compile_requests_use_cache"


def _on_event(event, **kw):
    """Disk-cache outcome pairing, race-free per thread: a request event
    opens a pending lookup; a hit event closes it as a hit; a
    backend-compile duration (the XLA fallback on a miss) closes it as a
    miss in `_on_duration`. Counters only ever increment."""
    if getattr(_tl, "inspecting", False):
        return                   # the inspection recompile is bookkeeping,
                                 # not a real cold-start cache outcome
    if event == _CACHE_REQ_EVENT:
        # jax fires this whenever the cache MACHINERY is enabled, even
        # with no cache directory configured (every lookup then misses
        # by construction) — only count outcomes of a real disk cache
        if compilation_cache_dir():
            _tl.cache_pending = True
    elif event == _CACHE_HIT_EVENT:
        _tl.cache_pending = False
        _cache_hits.inc()


def _on_duration(event, duration, **kw):
    if event != _BACKEND_COMPILE_EVENT:
        return
    if getattr(_tl, "cache_pending", False):
        _tl.cache_pending = False
        _cache_misses.inc()      # lookup fell through to a real compile
    label = getattr(_tl, "label", None)
    if label is not None and not getattr(_tl, "inspecting", False):
        _reg.histogram("compile_backend_seconds",
                       executable=label).observe(duration)


def _register_listeners():
    """Hook jax's monitoring stream once; a jax without it (API drift)
    degrades to wall-clock-only telemetry, never an import error."""
    try:
        from jax._src import monitoring
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        return True
    except Exception:
        return False


_listeners_ok = _register_listeners()


# --------------------------------------------------------- HLO parsing
# one optimized-HLO instruction: `%name = <shape> opcode(operands...)`.
# The shape class must admit TPU layout/tiling and memory-space
# annotations (`bf16[8,128]{1,0:T(8,128)S(1)}`) or every annotated
# instruction silently drops out of the counts on the platform this
# telemetry exists for; it stays conservative (no '=' or quotes) so the
# scan cannot wander into metadata strings and false-match.
_OP_RE = re.compile(r"=\s*[\w\[\],{}<>()/:. ]*?\s([a-z][a-z0-9\-]*)\(")


def inspect_hlo_text(text):
    """Count the structure of one optimized-HLO module text: fusions,
    collectives (per op + total), copies, donated-input aliases, module
    byte size, and the full opcode histogram. Pure function — the gate
    and tests call it on any `compiled.as_text()`."""
    ops = {}
    for m in _OP_RE.finditer(text):
        op = m.group(1)
        ops[op] = ops.get(op, 0) + 1
    colls = {}
    for op in COLLECTIVE_OPS:
        n = ops.get(op, 0) + ops.get(op + "-start", 0)
        if n:
            colls[op] = n
    return {
        "fusions": ops.get("fusion", 0),
        "collectives": colls,
        "collective_total": sum(colls.values()),
        "copies": ops.get("copy", 0) + ops.get("copy-start", 0),
        "aliased_inputs": text.count("may-alias") + text.count("must-alias"),
        "module_bytes": len(text),
        "ops": ops,
    }


def analyze_compiled(compiled):
    """`inspect_hlo_text` of a jax.stages.Compiled plus its
    cost_analysis flops / bytes-accessed where the backend reports them."""
    info = inspect_hlo_text(compiled.as_text())
    try:
        ca = compiled.cost_analysis()
        d = ca[0] if isinstance(ca, (list, tuple)) else ca
        if d:
            info["flops"] = float(d.get("flops", 0.0))
            info["bytes_accessed"] = float(d.get("bytes accessed", 0.0))
    except Exception:
        pass
    return info


def _abstract(x):
    """Shape/dtype/sharding skeleton of one argument leaf — lets the
    inspection lower() run after dispatch even where donation already
    consumed the concrete buffers (aval metadata survives deletion)."""
    if isinstance(x, jax.Array):
        try:
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=x.sharding)
        except Exception:
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x


def analyze_jit(jfn, *args, **kwargs):
    """AOT-compile `jfn` for the avals/shardings of `args`/`kwargs` and
    return its optimized-HLO counts (no dispatch, no registry writes).
    Accepts an InstrumentedJit or a bare jitted callable."""
    jfn = getattr(jfn, "_jfn", jfn)
    aargs, akwargs = jax.tree_util.tree_map(_abstract, (args, kwargs))
    prev = getattr(_tl, "inspecting", False)
    _tl.inspecting = True
    try:
        return analyze_compiled(jfn.lower(*aargs, **akwargs).compile())
    finally:
        _tl.inspecting = prev


# --------------------------------------------------------- dispatch hook
# one process-wide interception point over EVERY instrumented dispatch:
# `fn(ij, args, kwargs) -> (handled, out)`. handled=True short-circuits
# the normal jit route with `out` (the autotuner's winner-application
# path, tune/apply.py); handled=False falls through untouched (the
# workload-capture recorder, tune/search.py, stacks by chaining). The
# hook owns its own error containment — an exception here propagates to
# the caller like any dispatch failure.
_hook = None


def set_dispatch_hook(fn):
    """Install (or with None, remove) the dispatch hook. Returns the
    previous hook so callers can chain/restore."""
    global _hook
    prev = _hook
    _hook = fn
    return prev


def dispatch_hook():
    """The active dispatch hook, or None."""
    return _hook


# ------------------------------------------------------- the instrument
def _policy():
    return os.environ.get("MXTPU_HLO_TELEMETRY", "auto").lower()


def _max_inspect_s():
    return _env.env_float("MXTPU_HLO_MAX_S", 20.0, minimum=0.0)


class InstrumentedJit:
    """Transparent wrapper around one jitted callable: dispatch passes
    straight through (same args, same outputs, same exceptions, donation
    untouched); compiles are detected, timed, inspected and published as
    labelled registry series. Attribute access proxies to the wrapped
    jit function, so `.lower()` / `.clear_cache()` keep working."""

    __slots__ = ("_jfn", "executable", "_csize", "_called", "_compiles",
                 "_seconds", "last_hlo", "last_compile_seconds",
                 "last_abstract", "__weakref__")

    def __init__(self, jfn, executable):
        self._jfn = jfn
        self.executable = executable
        self._csize = getattr(jfn, "_cache_size", None)
        self._called = False
        self._compiles = _reg.counter("compiles", executable=executable)
        self._seconds = _reg.histogram("compile_seconds",
                                       executable=executable)
        self.last_hlo = None
        self.last_compile_seconds = None
        # aval/sharding skeleton of the last COMPILING call's arguments:
        # lets analysis/graphlint.py re-lower the executable post-hoc
        # (no python re-trace, no concrete buffers held alive)
        self.last_abstract = None
        # a fresh wrapper must not shadow a COMPILED same-name sibling
        # in the weak registry (two serve runtimes both instrument
        # "serve_decode"; only one ever dispatches) — _note_compile
        # re-registers, so the last wrapper that actually compiled wins
        if executable not in _instances:
            _instances[executable] = self

    @property
    def compile_count(self):
        return int(self._compiles.value)

    def __getattr__(self, name):
        return getattr(self._jfn, name)

    def __call__(self, *args, **kwargs):
        hook = _hook
        if hook is not None:
            handled, out = hook(self, args, kwargs)
            if handled:
                return out
        csize = self._csize
        n0 = csize() if csize is not None else None
        t0_ns = perf_counter_ns()
        prev = getattr(_tl, "label", None)
        _tl.label = self.executable
        try:
            out = self._jfn(*args, **kwargs)
        finally:
            _tl.label = prev
        if n0 is not None:
            grew = csize() > n0
        else:                      # no _cache_size (API drift): first call
            grew = not self._called
        self._called = True
        if grew:
            self._note_compile(args, kwargs, t0_ns)
        return out

    # ------------------------------------------------------- cold path
    def _note_compile(self, args, kwargs, t0_ns):
        t1_ns = perf_counter_ns()
        dt = (t1_ns - t0_ns) / 1e9
        self._compiles.inc()
        self._seconds.observe(dt)
        self.last_compile_seconds = dt
        try:
            self.last_abstract = jax.tree_util.tree_map(
                _abstract, (args, dict(kwargs)))
        except Exception:
            self.last_abstract = None    # exotic pytree: lint skips it
        _instances[self.executable] = self   # last COMPILED wins
        if _tracer.ACTIVE:
            _tracer.complete(f"compile.{self.executable}", t0_ns, t1_ns,
                             cat="compile",
                             args={"executable": self.executable,
                                   "seconds": round(dt, 4)})
        pol = _policy()
        if pol in ("0", "off", "never"):
            return
        if pol == "auto" and self.executable in _inspected:
            return
        if dt > _max_inspect_s() and not compilation_cache_dir():
            # the inspection recompile would cost another `dt` of XLA
            # with nothing to absorb it — record the skip and move on
            _reg.counter("hlo_inspect_skipped",
                         executable=self.executable).inc()
            return
        try:
            info = analyze_jit(self._jfn, *args, **kwargs)
        except Exception as e:
            _reg.counter("hlo_inspect_errors",
                         executable=self.executable).inc()
            if _tracer.ACTIVE:
                _tracer.instant("compile.inspect_error", cat="compile",
                                args={"executable": self.executable,
                                      "error": str(e)[:200]})
            return
        _inspected.add(self.executable)
        self.last_hlo = info
        ex = self.executable
        _reg.gauge("hlo_fusions", executable=ex).set(info["fusions"])
        _reg.gauge("hlo_collective_total",
                   executable=ex).set(info["collective_total"])
        for op, n in info["collectives"].items():
            _reg.gauge("hlo_collectives", executable=ex, op=op).set(n)
        _reg.gauge("hlo_copies", executable=ex).set(info["copies"])
        _reg.gauge("hlo_aliased_inputs",
                   executable=ex).set(info["aliased_inputs"])
        _reg.gauge("hlo_bytes", executable=ex).set(info["module_bytes"])
        if "flops" in info:
            _reg.gauge("hlo_flops", executable=ex).set(info["flops"])
            _reg.gauge("hlo_bytes_accessed",
                       executable=ex).set(info.get("bytes_accessed", 0.0))


def instrument(jfn, executable):
    """Wrap a jitted callable with compile/HLO telemetry under the given
    executable name. The wrapper is call-transparent; see class doc."""
    return InstrumentedJit(jfn, executable)


def executables():
    """{executable name: compiles observed} for every instrumented
    executable in this process, derived from the registry's `compiles`
    series (one source of truth with the snapshot/reset machinery)."""
    return {dict(c.labels).get("executable"): int(c.value)
            for c in _reg.series("compiles")}


_instances = weakref.WeakValueDictionary()   # executable -> live wrapper
                                             # (latest instance wins)


def instrumented():
    """{executable name: live InstrumentedJit} — every instrumented
    executable still alive in this process. What
    analysis/graphlint.py / tools/check_static.py iterate to lint the
    framework's real programs instead of hand-kept fixtures."""
    return dict(_instances)


# -------------------------------------------- persistent compile cache
def set_compilation_cache(path, min_compile_seconds=0.0):
    """Point jax's persistent compilation cache at `path` (created if
    missing) so later processes deserialise identical programs from disk
    instead of re-running XLA; `None` disables. `min_compile_seconds`
    is the write threshold (0 caches everything — CPU-mesh compiles are
    fast but still worth skipping in a fleet cold start).

    Exported as `mx.set_compilation_cache`; `MXTPU_COMPILE_CACHE=dir`
    applies it at import time. Cache outcomes land on
    `compile_cache_hits` / `compile_cache_misses` (`compile_cache_stats()`).
    """
    if path is None:
        jax.config.update("jax_compilation_cache_dir", None)
        return None
    path = os.fspath(path)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_seconds))
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass                      # knob absent on older jax: defaults apply
    return path


def compilation_cache_dir():
    """The active persistent-cache directory, or None when disabled."""
    try:
        return jax.config.jax_compilation_cache_dir
    except Exception:
        return None


def compile_cache_stats():
    """(hits, misses) of the persistent compilation cache so far (both 0
    when the cache is disabled — lookups never happen)."""
    return int(_cache_hits.value), int(_cache_misses.value)


# env wiring: an import of mxnet_tpu with MXTPU_COMPILE_CACHE set gets
# the disk cache with no code change (the fleet cold-start path)
_env_dir = os.environ.get("MXTPU_COMPILE_CACHE")
if _env_dir:
    try:
        set_compilation_cache(_env_dir)
    except Exception:             # unwritable dir etc. — never break import
        pass
