"""MLP on MNIST — BASELINE.json config #1 (Gluon nn.Sequential, imperative)."""
from __future__ import annotations

from ..gluon import nn

__all__ = ["get_mlp"]


def get_mlp(hidden=(128, 64), classes=10, activation="relu"):
    net = nn.HybridSequential(prefix="mlp_")
    with net.name_scope():
        for h in hidden:
            net.add(nn.Dense(h, activation=activation))
        net.add(nn.Dense(classes))
    return net
