"""Classic reference op-name compatibility surface (reference:
src/operator/tensor/elemwise_binary_op_basic.cc, regression_output-inl.h,
optimizer_op.cc, nn/im2col.cc, and the python/mxnet/ndarray op namespace).

Three groups, all TPU-first:
- aliases and small math ops the reference exposes under its own names
  (elemwise_*, broadcast_axes, softsign, argmax_channel, ...): thin
  `_apply` dispatches over jnp — they fuse into surrounding programs.
- loss heads (LinearRegressionOutput et al.): reuse the SAME custom_vjp
  kernels the symbol executor registers, so imperative and symbolic
  training have one set of gradient semantics.
- single-tensor optimizer update ops (sgd_update, adam_update, ...):
  the reference's imperative update primitives for hand-rolled training
  loops. State inputs (mom/mean/var/...) are updated IN PLACE (SSA
  rebind), matching the reference's mutate-inputs contract; the new
  weight is returned (and written to `out` when given).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply

__all__ = [
    "broadcast_axes", "broadcast_hypot", "elemwise_add", "elemwise_sub",
    "elemwise_mul", "elemwise_div", "identity", "SwapAxis", "crop",
    "softsign", "argmax_channel", "degrees", "radians", "logical_and",
    "logical_or", "logical_xor", "isnan", "isinf", "isfinite", "logaddexp",
    "cumprod", "trace", "tril", "triu", "lcm", "gcd", "histogram",
    "bincount", "SoftmaxActivation",
    "LinearRegressionOutput", "MAERegressionOutput",
    "LogisticRegressionOutput", "SVMOutput",
    "im2col", "col2im", "RNN",
    "multi_sum_sq", "sgd_update", "sgd_mom_update", "mp_sgd_update",
    "mp_sgd_mom_update", "nag_mom_update", "adam_update", "signsgd_update",
    "signum_update", "rmsprop_update", "rmspropalex_update", "ftrl_update",
    "lamb_update_phase1", "lamb_update_phase2",
    "choose_element_0index", "fill_element_0index",
    "IdentityAttachKLSparseReg"]


# ------------------------------------------------------- aliases, small math
def _unary(jfn):
    def f(data, **kw):
        return _apply(lambda x: jfn(x), [data])
    return f


def _binary(jfn):
    def f(lhs, rhs, **kw):
        return _apply(jfn, [lhs, rhs])
    return f


elemwise_add = _binary(jnp.add)
elemwise_sub = _binary(jnp.subtract)
elemwise_mul = _binary(jnp.multiply)
elemwise_div = _binary(jnp.divide)
broadcast_hypot = _binary(jnp.hypot)
logical_and = _binary(jnp.logical_and)
logical_or = _binary(jnp.logical_or)
logical_xor = _binary(jnp.logical_xor)
lcm = _binary(jnp.lcm)
gcd = _binary(jnp.gcd)
logaddexp = _binary(jnp.logaddexp)
degrees = _unary(jnp.degrees)
radians = _unary(jnp.radians)
isnan = _unary(jnp.isnan)
isinf = _unary(jnp.isinf)
isfinite = _unary(jnp.isfinite)


def identity(data, **kw):
    return _apply(lambda x: x, [data])


def softsign(data, **kw):
    return _apply(lambda x: x / (1 + jnp.abs(x)), [data])


def argmax_channel(data, **kw):
    """Per-sample argmax over the channel axis (axis 1; the classic
    softmax-prediction helper — (N, C) logits -> (N,) classes)."""
    return _apply(lambda x: jnp.argmax(x, axis=1).astype(jnp.float32),
                  [data])


def broadcast_axes(data, axis=0, size=1, **kw):
    from .tensor_ops import broadcast_axis
    return broadcast_axis(data, axis, size)


def SwapAxis(data, dim1=0, dim2=0, **kw):
    from .tensor_ops import swapaxes
    return swapaxes(data, dim1, dim2)


def crop(data, begin, end, step=None, **kw):
    """Deprecated reference alias of `slice` (NOT the symbol Crop op)."""
    from .tensor_ops import slice as _slice
    return _slice(data, begin, end, step)


def cumprod(data, axis=None, **kw):
    return _apply(lambda x: jnp.cumprod(x, axis=axis), [data])


def trace(data, offset=0, axis1=0, axis2=1, **kw):
    return _apply(lambda x: jnp.trace(x, offset=offset, axis1=axis1,
                                      axis2=axis2), [data])


def tril(data, k=0, **kw):
    return _apply(lambda x: jnp.tril(x, k=k), [data])


def triu(data, k=0, **kw):
    return _apply(lambda x: jnp.triu(x, k=k), [data])


def histogram(data, bins=10, range=None, **kw):
    """(counts, bin_edges) like numpy; bin count is static so the whole op
    is one fused jit-able program."""
    if isinstance(bins, NDArray):
        return _apply(lambda x, b: tuple(jnp.histogram(x, bins=b)),
                      [data, bins], n_out=2)
    return _apply(lambda x: tuple(jnp.histogram(x, bins=bins, range=range)),
                  [data], n_out=2)


def bincount(data, weights=None, minlength=0, **kw):
    """Eager-only when minlength doesn't cover the data (output length is
    data-dependent — SURVEY §8 pattern)."""
    length = int(max(int(minlength),
                     int(jnp.max(data._data)) + 1 if data.size else 1))
    if weights is None:
        return _apply(lambda x: jnp.bincount(x.astype(jnp.int32),
                                             length=length), [data])
    return _apply(lambda x, w: jnp.bincount(x.astype(jnp.int32), weights=w,
                                            length=length),
                  [data, weights])


def SoftmaxActivation(data, mode="instance", **kw):
    """Deprecated reference op: softmax over features ('instance') or over
    the channel axis at each position ('channel')."""
    axis = -1 if mode == "instance" else 1
    return _apply(lambda x: jax.nn.softmax(x, axis=axis), [data])


# ------------------------------------------------------------- loss heads
def _head(op_name):
    def f(data, label=None, grad_scale=1.0, **kw):
        # resolved lazily: the kernels register when symbol/ops.py loads,
        # which is after this module during package init
        from .. import symbol  # noqa: F401  (ensures registration ran)
        from ..symbol.symbol import _OP_REGISTRY
        kernel = _OP_REGISTRY[op_name]
        if label is None:
            return _apply(lambda x: kernel(x), [data])
        return _apply(lambda x, l: kernel(x, l, grad_scale=grad_scale),
                      [data, label])
    f.__name__ = op_name
    return f


LinearRegressionOutput = _head("LinearRegressionOutput")
MAERegressionOutput = _head("MAERegressionOutput")
LogisticRegressionOutput = _head("LogisticRegressionOutput")


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_op(x, y, margin, reg, linear):
    return x


def _svm_fwd(x, y, margin, reg, linear):
    return x, (x, y)


def _svm_bwd(margin, reg, linear, res, g):
    x, y = res
    iy = y.astype(jnp.int32)
    oh = jax.nn.one_hot(iy, x.shape[-1], dtype=x.dtype)
    viol = (margin - (2 * oh - 1) * x) > 0   # margin violated per class
    if linear:
        gx = jnp.where(viol, -(2 * oh - 1) * reg, 0.0)
    else:
        gx = jnp.where(viol, -2 * (margin - (2 * oh - 1) * x)
                       * (2 * oh - 1) * reg, 0.0)
    return (gx.astype(x.dtype), jnp.zeros(y.shape, y.dtype))


_svm_op.defvjp(_svm_fwd, _svm_bwd)


def svm_output_k(x, y, margin=1.0, reg=1.0, linear=False):
    """Raw-array SVMOutput core (identity fwd, hinge bwd) shared by the
    nd wrapper below and the sym registration."""
    return _svm_op(x, y, float(margin), float(reg), bool(linear))


def SVMOutput(data, label=None, margin=1.0, regularization_coefficient=1.0,
              use_linear=False, **kw):
    """Reference SVMOutput (src/operator/svm_output.cc): forward is the
    identity; backward is the (squared) hinge-loss gradient at the true
    class margin."""
    if label is None:
        return _apply(lambda x: x, [data])
    return _apply(lambda x, y: svm_output_k(
        x, y, margin, regularization_coefficient, use_linear),
        [data, label])


# ---------------------------------------------------------------- im2col
def _im2col_fn(x, kernel, stride, dilate, pad):
    sp = "DHW"[3 - (x.ndim - 2):]         # 1D "W", 2D "HW", 3D "DHW"
    dn = ("NC" + sp, "OI" + sp, "NC" + sp)
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=kernel, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn)
    n = x.shape[0]
    return patches.reshape(n, patches.shape[1], -1)


def _norm2(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def im2col(data, kernel, stride=1, dilate=1, pad=0, **kw):
    """Sliding-window unfold, NCHW -> (N, C*prod(kernel), L) (reference:
    src/operator/nn/im2col.cc). One XLA patches op, no per-window loops."""
    nd_spatial = data.ndim - 2
    kernel = _norm2(kernel, nd_spatial)
    stride, dilate, pad = (_norm2(stride, nd_spatial),
                           _norm2(dilate, nd_spatial),
                           _norm2(pad, nd_spatial))
    return _apply(lambda x: _im2col_fn(x, kernel, stride, dilate, pad),
                  [data])


def col2im(data, output_size, kernel, stride=1, dilate=1, pad=0, **kw):
    """Fold columns back, summing overlaps — implemented as the exact
    adjoint (jax.vjp) of im2col, which is its mathematical definition."""
    nd_spatial = len(tuple(output_size)) if not isinstance(output_size, int) \
        else 1
    out_sp = _norm2(output_size, nd_spatial)
    kernel = _norm2(kernel, len(out_sp))
    stride, dilate, pad = (_norm2(stride, len(out_sp)),
                           _norm2(dilate, len(out_sp)),
                           _norm2(pad, len(out_sp)))

    def fn(cols):
        n = cols.shape[0]
        c = cols.shape[1] // int(_np.prod(kernel))
        ref = jnp.zeros((n, c) + out_sp, cols.dtype)
        _, vjp = jax.vjp(
            lambda img: _im2col_fn(img, kernel, stride, dilate, pad), ref)
        return vjp(cols)[0]
    return _apply(fn, [data])


# ------------------------------------------------------------------ nd.RNN
def RNN(data, *state_and_params, state_outputs=False, mode="lstm", **kwargs):
    """Imperative fused RNN — the same kernel the sym.RNN node compiles
    (symbol/ops.py _rnn_eval), dispatched eagerly. The kernel always
    produces (out, h[, c]); `state_outputs` picks what the caller sees."""
    from ..symbol.ops import _rnn_eval
    ns = 2 if mode == "lstm" else 1
    res = _apply(lambda *a: _rnn_eval(*a, state_outputs=state_outputs,
                                      mode=mode, **kwargs),
                 [data] + list(state_and_params), n_out=1 + ns)
    return res if state_outputs else res[0]


# ------------------------------------------- optimizer update primitives
def _prep_grad(g, w, rescale_grad, clip_gradient, wd):
    g = g * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * w


def _emit(weight, new_w, out):
    target = out if out is not None else weight
    target._assign_value(new_w.astype(target.dtype))
    return target


def multi_sum_sq(*arrays, num_arrays=None, **kw):
    """Per-tensor sum of squares in one fused program (reference:
    multi_sum_sq.cc; feeds LARS-style global norms)."""
    arrs = list(arrays[:num_arrays] if num_arrays else arrays)
    return _apply(lambda *xs: jnp.stack(
        [jnp.sum(x.astype(jnp.float32) * x) for x in xs]), arrs)


def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, out=None, **kw):
    new_w = _apply(lambda w, g: w - lr * _prep_grad(
        g, w, rescale_grad, clip_gradient, wd), [weight, grad])
    return _emit(weight, new_w._data, out)


def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None, **kw):
    def fn(w, g, m):
        new_m = momentum * m - lr * _prep_grad(g, w, rescale_grad,
                                               clip_gradient, wd)
        return new_m, w + new_m
    new_m, new_w = _apply(fn, [weight, grad, mom], n_out=2)
    mom._assign_value(new_m._data)
    return _emit(weight, new_w._data, out)


def mp_sgd_update(weight, grad, weight32, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, out=None, **kw):
    """Multi-precision: master fp32 weight carries the update, the low-
    precision weight is its cast (reference mp_sgd_update)."""
    new_w32 = _apply(lambda w32, g: w32 - lr * _prep_grad(
        g.astype(jnp.float32), w32, rescale_grad, clip_gradient, wd),
        [weight32, grad])
    weight32._assign_value(new_w32._data)
    return _emit(weight, new_w32._data, out)


def mp_sgd_mom_update(weight, grad, mom, weight32, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      out=None, **kw):
    def fn(w32, g, m):
        new_m = momentum * m - lr * _prep_grad(
            g.astype(jnp.float32), w32, rescale_grad, clip_gradient, wd)
        return new_m, w32 + new_m
    new_m, new_w32 = _apply(fn, [weight32, grad, mom], n_out=2)
    mom._assign_value(new_m._data)
    weight32._assign_value(new_w32._data)
    return _emit(weight, new_w32._data, out)


def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None, **kw):
    def fn(w, g, m):
        gr = _prep_grad(g, w, rescale_grad, clip_gradient, wd)
        new_m = momentum * m + gr
        return new_m, w - lr * (gr + momentum * new_m)
    new_m, new_w = _apply(fn, [weight, grad, mom], n_out=2)
    mom._assign_value(new_m._data)
    return _emit(weight, new_w._data, out)


def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                out=None, **kw):
    def fn(w, g, m, v):
        gr = _prep_grad(g, w, rescale_grad, clip_gradient, wd)
        new_m = beta1 * m + (1 - beta1) * gr
        new_v = beta2 * v + (1 - beta2) * gr * gr
        return new_m, new_v, w - lr * new_m / (jnp.sqrt(new_v) + epsilon)
    new_m, new_v, new_w = _apply(fn, [weight, grad, mean, var], n_out=3)
    mean._assign_value(new_m._data)
    var._assign_value(new_v._data)
    return _emit(weight, new_w._data, out)


def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, out=None, **kw):
    new_w = _apply(lambda w, g: w - lr * jnp.sign(_prep_grad(
        g, w, rescale_grad, clip_gradient, wd)), [weight, grad])
    return _emit(weight, new_w._data, out)


def signum_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0,
                  out=None, **kw):
    def fn(w, g, m):
        gr = _prep_grad(g, w, rescale_grad, clip_gradient, wd)
        new_m = momentum * m - (1 - momentum) * gr
        return new_m, (1 - lr * wd_lh) * w + lr * jnp.sign(new_m)
    new_m, new_w = _apply(fn, [weight, grad, mom], n_out=2)
    mom._assign_value(new_m._data)
    return _emit(weight, new_w._data, out)


def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None, **kw):
    def fn(w, g, nn_):
        gr = _prep_grad(g, w, rescale_grad, clip_gradient, wd)
        new_n = gamma1 * nn_ + (1 - gamma1) * gr * gr
        return new_n, w - lr * gr / jnp.sqrt(new_n + epsilon)
    new_n, new_w = _apply(fn, [weight, grad, n], n_out=2)
    n._assign_value(new_n._data)
    return _emit(weight, new_w._data, out)


def rmspropalex_update(weight, grad, n, g, delta, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, out=None, **kw):
    """RMSProp with the Alex Graves centering + momentum variant."""
    def fn(w, gr_, n_, gavg, d):
        gr = _prep_grad(gr_, w, rescale_grad, clip_gradient, wd)
        new_n = gamma1 * n_ + (1 - gamma1) * gr * gr
        new_g = gamma1 * gavg + (1 - gamma1) * gr
        new_d = gamma2 * d - lr * gr / jnp.sqrt(
            new_n - new_g * new_g + epsilon)
        return new_n, new_g, new_d, w + new_d
    new_n, new_g, new_d, new_w = _apply(fn, [weight, grad, n, g, delta],
                                        n_out=4)
    n._assign_value(new_n._data)
    g._assign_value(new_g._data)
    delta._assign_value(new_d._data)
    return _emit(weight, new_w._data, out)


def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, out=None, **kw):
    def fn(w, g, z_, n_):
        gr = g * rescale_grad
        if clip_gradient is not None and clip_gradient > 0:
            gr = jnp.clip(gr, -clip_gradient, clip_gradient)
        new_n = n_ + gr * gr
        sigma = (jnp.sqrt(new_n) - jnp.sqrt(n_)) / lr
        new_z = z_ + gr - sigma * w
        new_w = jnp.where(
            jnp.abs(new_z) > lamda1,
            -(new_z - jnp.sign(new_z) * lamda1)
            / ((beta + jnp.sqrt(new_n)) / lr + wd), 0.0)
        return new_z, new_n, new_w.astype(w.dtype)
    new_z, new_n, new_w = _apply(fn, [weight, grad, z, n], n_out=3)
    z._assign_value(new_z._data)
    n._assign_value(new_n._data)
    return _emit(weight, new_w._data, out)


def lamb_update_phase1(weight, grad, mean, var, t, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, bias_correction=True, **kw):
    """LAMB step direction (reference lamb_update_phase1): returns g' =
    m_hat/(sqrt(v_hat)+eps) + wd*w; phase2 applies the trust ratio."""
    def fn(w, g, m, v):
        gr = g * rescale_grad
        if clip_gradient is not None and clip_gradient > 0:
            gr = jnp.clip(gr, -clip_gradient, clip_gradient)
        new_m = beta1 * m + (1 - beta1) * gr
        new_v = beta2 * v + (1 - beta2) * gr * gr
        if bias_correction:
            mh = new_m / (1 - beta1 ** t)
            vh = new_v / (1 - beta2 ** t)
        else:
            mh, vh = new_m, new_v
        return new_m, new_v, mh / (jnp.sqrt(vh) + epsilon) + wd * w
    new_m, new_v, gprime = _apply(fn, [weight, grad, mean, var], n_out=3)
    mean._assign_value(new_m._data)
    var._assign_value(new_v._data)
    return gprime


def lamb_update_phase2(weight, g_prime, r1, r2, lr, lower_bound=-1.0,
                       upper_bound=-1.0, out=None, **kw):
    def fn(w, gp, r1_, r2_):
        r1c = r1_
        if lower_bound > 0:
            r1c = jnp.maximum(r1c, lower_bound)
        if upper_bound > 0:
            r1c = jnp.minimum(r1c, upper_bound)
        ratio = jnp.where(jnp.logical_and(r1c > 0, r2_ > 0), r1c / r2_, 1.0)
        return w - lr * ratio * gp
    new_w = _apply(fn, [weight, g_prime, r1, r2])
    return _emit(weight, new_w._data, out)


def choose_element_0index(lhs, rhs, **kw):
    """Pick lhs[i, rhs[i]] along axis 1 (reference:
    choose_element_0index — the classic softmax-label gather)."""
    return _apply(lambda a, i: jnp.take_along_axis(
        a, i.astype(jnp.int32)[:, None], 1)[:, 0], [lhs, rhs])


def fill_element_0index(lhs, mhs, rhs, **kw):
    """lhs with lhs[i, rhs[i]] = mhs[i] (reference:
    fill_element_0index)."""
    return _apply(lambda a, v, i: a.at[
        jnp.arange(a.shape[0]), i.astype(jnp.int32)].set(v),
        [lhs, mhs, rhs])


def IdentityAttachKLSparseReg(data, sparseness_target=0.1,
                              penalty=0.001, momentum=0.9, **kw):
    """Identity forward; backward adds the KL-sparseness penalty
    gradient on the mean activation (reference:
    identity_attach_KL_sparse_reg.cc). The running-average momentum of
    the upstream op is folded into the per-batch mean (documented
    divergence: stateless, XLA-pure)."""
    import functools

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
    def op(x, rho, pen):
        return x

    def fwd(x, rho, pen):
        return x, x

    def bwd(rho, pen, x, g):
        rho_hat = jnp.clip(jnp.mean(x, axis=0), 1e-6, 1 - 1e-6)
        dkl = (-rho / rho_hat + (1 - rho) / (1 - rho_hat)) / x.shape[0]
        return (g + pen * dkl[None, :].astype(x.dtype),)

    op.defvjp(fwd, bwd)
    return _apply(lambda x: op(x, float(sparseness_target),
                               float(penalty)), [data])
