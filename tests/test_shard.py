"""Rule-driven parameter sharding (mxnet_tpu/shard/, ISSUE 8): rule
matching edge cases, the (2,2) rule-sharded captured step vs the
replicated baseline (documented fp tolerance — the partitioner reorders
the contraction; see docs/PERFORMANCE.md "Parameter sharding"),
per-device param-byte reduction, partition specs in the checkpoint
manifest, the save-on-(2,2)/restore-on-(1,2) elastic path, and
`Trainer.resize_mesh` live resharding vs a cold resharded restore."""
import os
import tempfile
import warnings

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd, shard
from mxnet_tpu.observability import registry

BATCH, DIM, CLS = 8, 16, 4


def _mesh22():
    return shard.make_mesh_2d(dp=2, tp=2)


# ------------------------------------------------------------- rules
def test_first_match_wins_over_later_rules():
    """Overlap precedence: rule order IS the priority order."""
    rules = ((r"_weight$", P("tp")),      # broad, first
             (r"dense0_weight$", P("dp")))  # specific, but too late
    specs, _ = shard.match_partition_rules(
        rules, {"dense0_weight": (8, 4)})
    assert specs["dense0_weight"] == P("tp")
    # flipped order: the specific rule now wins
    specs, _ = shard.match_partition_rules(
        tuple(reversed(rules)), {"dense0_weight": (8, 4)})
    assert specs["dense0_weight"] == P("dp")


def test_anchored_vs_substring_matching():
    """Matching is re.search (substring); ^...$ anchors make it exact."""
    sub, _ = shard.match_partition_rules(
        ((r"dense", P("dp")), (r".*", None)),
        {"predense0_weight": (8, 4)})
    assert sub["predense0_weight"] == P("dp")       # substring hit
    anchored, _ = shard.match_partition_rules(
        (("^dense", P("dp")), (r".*", None)),
        {"predense0_weight": (8, 4)})
    assert anchored["predense0_weight"] == P()      # anchored miss -> None


def test_unmatched_param_reported_and_replicated():
    specs, report = shard.match_partition_rules(
        ((r"_weight$", P("dp")),), {"odd_thing": (4, 4)})
    assert specs["odd_thing"] == P()
    assert report["unmatched"] == ["odd_thing"]
    with pytest.raises(Exception, match="no partition rule"):
        shard.match_partition_rules(((r"_weight$", P("dp")),),
                                    {"odd_thing": (4, 4)},
                                    on_unmatched="error")


def test_scalars_and_non_divisible_dims_replicate_with_report():
    mesh = _mesh22()
    specs, report = shard.match_partition_rules(
        ((r".*", P("dp")),),
        {"scalar": (), "one": (1,), "odd": (7, 4), "even": (4, 4)},
        mesh=mesh)
    assert specs["scalar"] == P() and specs["one"] == P()
    assert specs["odd"] == P()          # 7 % 2 != 0 -> replicated
    assert specs["even"] == P("dp")
    assert ("odd", 0, "dp", "not_divisible") in report["fallbacks"]


def test_validate_rules_rejects_garbage():
    with pytest.raises(Exception, match="bad regex"):
        shard.validate_rules((("(", P("dp")),))
    with pytest.raises(Exception, match="spec must be"):
        shard.validate_rules((("x", 42),))
    # tuples convert, None passes
    out = shard.validate_rules((("x", ("dp", None)), ("y", None)))
    assert out[0][1] == P("dp", None) and out[1][1] is None


def test_spec_json_roundtrip():
    for spec in (P(), P("dp"), P(None, "tp"), P(("dp", "tp"), None)):
        assert shard.spec_from_json(shard.spec_to_json(spec)) == spec


def test_default_rules_cover_model_zoo_names():
    """DEFAULT_RULES: attention/ffn weights -> tp, other weights -> dp,
    norms/biases replicated, nothing unmatched."""
    mesh = _mesh22()
    names = {
        "dense0_weight": (32, 16), "dense0_bias": (32,),
        "batchnorm0_gamma": (32,), "batchnorm0_running_mean": (32,),
        "conv0_weight": (8, 3, 3, 4),
        "transformernmt0_embed_weight": (32, 16),
        "enc0_selfattention0_qkv_weight": (48, 16),
        "enc0_selfattention0_proj_weight": (16, 16),
        "enc0__ffn0_ffn1_weight": (32, 16),
    }
    specs, report = shard.match_partition_rules(shard.DEFAULT_RULES,
                                                names, mesh=mesh)
    assert report["unmatched"] == []
    assert specs["dense0_weight"] == P("dp")
    assert specs["conv0_weight"] == P("dp")
    assert specs["dense0_bias"] == P()
    assert specs["batchnorm0_gamma"] == P()
    assert specs["transformernmt0_embed_weight"] == P("tp")
    assert specs["enc0_selfattention0_qkv_weight"] == P("tp")
    assert specs["enc0__ffn0_ffn1_weight"] == P("tp")


# ------------------------------------------------------------- plan
def test_plan_shardings_and_bytes():
    plan = shard.plan({"dp": 2, "tp": 2})
    sh = plan.sharding("dense0_weight", (32, 16))
    assert sh == NamedSharding(plan.mesh, P("dp"))
    assert plan.batch_sharding() == NamedSharding(plan.mesh, P("dp"))
    per_dev, total = plan.param_bytes_per_device(
        {"dense0_weight": np.zeros((32, 16), np.float32),
         "dense0_bias": np.zeros((32,), np.float32)})
    assert total == 32 * 16 * 4 + 32 * 4
    assert per_dev == 32 * 16 * 4 // 2 + 32 * 4   # weight dp-halved
    # state leaves: elementwise ride the weight spec, scalars replicate
    assert plan.state_spec("dense0_weight", (32, 16), (32, 16)) == P("dp")
    assert plan.state_spec("dense0_weight", (32, 16), ()) == P()
    p2 = plan.with_mesh({"dp": 1, "tp": 2})
    assert p2.rules == plan.rules and p2.signature() != plan.signature()


# ------------------------------------- the rule-sharded captured step
def _data():
    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(BATCH, DIM).astype(np.float32))
    y = nd.array(rng.randint(0, CLS, BATCH).astype(np.float32))
    return X, y


def _build(X, seed=0):
    mx.random.seed(seed)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"),
            gluon.nn.Dense(16, activation="relu"),
            gluon.nn.Dense(CLS))
    net.initialize(mx.init.Xavier())
    net(X)
    return net


_lossf = gluon.loss.SoftmaxCrossEntropyLoss()


def _weights(net):
    return [p.data().asnumpy().astype(np.float32)
            for p in net.collect_params().values()]


# rules that exercise BOTH layouts on a plain MLP: layer 0/1 weights
# FSDP over dp, the head TP over tp, biases replicated
_MLP_RULES = ((r"_bias$", None),
              (r"dense2_weight$", P("tp", None)),
              (r"_weight$", P("dp", None)),
              (r".*", None))


def test_sharded_captured_step_matches_replicated_baseline():
    """(2,2) rule-sharded captured step vs the imperative replicated
    baseline: allclose at the documented fp tolerance (TP splits the
    contraction; FSDP changes only the schedule), params genuinely live
    sharded, per-device bytes drop, and the per-spec collective bytes
    are accounted."""
    X, y = _data()
    net_i = _build(X)
    tr_i = gluon.Trainer(net_i.collect_params(), "adam",
                         {"learning_rate": 0.05})
    for _ in range(4):
        with autograd.record():
            L = _lossf(net_i(X), y).mean()
        L.backward()
        tr_i.step(BATCH)
    imp = _weights(net_i)

    net_s = _build(X)
    tr_s = gluon.Trainer(net_s.collect_params(), "adam",
                         {"learning_rate": 0.05}, kvstore="ici")
    plan = tr_s.shard(mesh={"dp": 2, "tp": 2}, rules=_MLP_RULES)
    assert plan.report()["unmatched"] == []
    step = tr_s.capture(lambda a, b: _lossf(net_s(a), b).mean())
    for _ in range(4):
        step(X, y)
        assert step.last_fallback_reason is None
    assert step.cache_size == 1
    for a, b in zip(_weights(net_s), imp):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)

    # params live sharded between steps: the FSDP weight's per-device
    # shard is half the logical array, the TP head a quarter... of its
    # own layout; biases stay replicated
    w0 = list(net_s.collect_params().values())[0].data()._data
    assert w0.sharding.spec == P("dp")
    assert w0.addressable_shards[0].data.nbytes == w0.nbytes // 2
    params = {p.name: p.data()._data
              for p in net_s.collect_params().values()}
    per_dev, total = plan.param_bytes_per_device(params)
    assert per_dev < total
    # per-spec collective accounting (kv_collective_bytes{op=,spec=})
    snap = registry().snapshot()
    series = {tuple(sorted(s["labels"].items()))
              for s in snap.get("kv_collective_bytes", [])}
    assert any(lbl == (("op", "spmd_grad_reduce"),
                       ("spec", "PartitionSpec('dp',)")) or
               lbl == (("op", "spmd_grad_reduce"),
                       ("spec", str(P("dp")))) for lbl in series)


def test_sharded_step_single_dispatch_and_no_retrace():
    X, y = _data()
    from mxnet_tpu import profiler
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore="ici")
    tr.shard(mesh={"dp": 2, "tp": 2}, rules=_MLP_RULES)
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
    step(X, y)
    step(X, y)
    profiler.reset_dispatches()
    step(X, y)
    assert profiler.dispatch_count() == 1
    assert step.cache_size == 1


def test_shard_plan_refuses_imperative_fallback():
    """With a plan attached a capture failure must raise, not silently
    train garbage on mesh-resident params."""
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore="ici")
    tr.shard(mesh={"dp": 2, "tp": 2}, rules=_MLP_RULES)

    def bad_loss(a, b):
        L = _lossf(net(a), b).mean()
        float(L.asnumpy())              # host sync inside the forward
        return L

    step = tr.capture(bad_loss)
    with pytest.raises(Exception, match="cannot fall back"):
        step(X, y)
    # unsupported-optimizer configurations are refused up front
    net2 = _build(X)
    tr2 = gluon.Trainer(net2.collect_params(), "dcasgd",
                        {"learning_rate": 0.05}, kvstore="ici")
    with pytest.raises(Exception, match="custom imperative"):
        tr2.shard(mesh={"dp": 2, "tp": 2})
    # sharded_update composes with the 1-D path only
    net3 = _build(X)
    tr3 = gluon.Trainer(net3.collect_params(), "sgd",
                        {"learning_rate": 0.05}, kvstore="ici")
    tr3.shard(mesh={"dp": 2, "tp": 2}, rules=_MLP_RULES)
    step3 = tr3.capture(lambda a, b: _lossf(net3(a), b).mean(),
                        sharded_update=True)
    with pytest.raises(Exception, match="drop sharded_update"):
        step3(X, y)


def test_partial_batch_degrades_instead_of_aborting():
    """A final batch the dp axis does not divide must NOT kill a run
    that has no imperative fallback: the batch replicates for that step
    (one extra cache entry) and the update matches the imperative
    partial-batch step."""
    X, y = _data()
    Xo = nd.array(X.asnumpy()[:5])          # 5 % 2 != 0
    yo = nd.array(y.asnumpy()[:5])

    net_i = _build(X)
    tr_i = gluon.Trainer(net_i.collect_params(), "sgd",
                         {"learning_rate": 0.05})
    for a, b, n in ((X, y, BATCH), (Xo, yo, 5)):
        with autograd.record():
            L = _lossf(net_i(a), b).mean()
        L.backward()
        tr_i.step(n)
    imp = _weights(net_i)

    net_s = _build(X)
    tr_s = gluon.Trainer(net_s.collect_params(), "sgd",
                         {"learning_rate": 0.05}, kvstore="ici")
    tr_s.shard(mesh={"dp": 2, "tp": 2}, rules=_MLP_RULES)
    step = tr_s.capture(lambda a, b: _lossf(net_s(a), b).mean())
    step(X, y)
    step(Xo, yo)                            # partial batch: degrades
    assert step.last_fallback_reason is None
    assert step.cache_size == 2             # one entry per batch shape
    for a, b in zip(_weights(net_s), imp):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)


def test_sharded_step_with_device_prefetcher_zero_sync_h2d():
    from mxnet_tpu.prefetch import DevicePrefetcher
    X, y = _data()
    Xh, yh = X.asnumpy(), y.asnumpy()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore="ici")
    tr.shard(mesh={"dp": 2, "tp": 2}, rules=_MLP_RULES)
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
    step(X, y)                                   # compile
    sync = registry().counter("prefetch_h2d_sync")
    pf = DevicePrefetcher(((Xh, yh) for _ in range(3)),
                          capture_spec=tr._kvstore)
    before = sync.value
    for xb, yb in pf:
        step(xb, yb)
        assert step.last_fallback_reason is None
    pf.close()
    assert sync.value == before
    assert step.cache_size == 1


# -------------------------------------------------- elastic resharding
def test_manifest_partition_specs_and_elastic_restore():
    """Save on (2,2): the manifest records each param's PartitionSpec;
    restore onto a (1,2) template reshards (template wins) and the
    values round-trip exactly."""
    plan22 = shard.plan({"dp": 2, "tp": 2}, rules=_MLP_RULES)
    rng = np.random.RandomState(3)
    w = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    b = jnp.asarray(rng.randn(8).astype(np.float32))
    params = {
        "dense0_weight": jax.device_put(
            w, plan22.sharding("dense0_weight", w.shape)),
        "dense0_bias": jax.device_put(
            b, plan22.sharding("dense0_bias", b.shape)),
    }
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save_sharded(d, 0, params)
        specs = checkpoint.saved_partition_specs(d, 0)
        assert specs["dense0_weight"] == P("dp")
        assert specs["dense0_bias"] == P()
        # restore onto the SHRUNK mesh: the (1,2) template's layout wins
        plan12 = plan22.with_mesh({"dp": 1, "tp": 2})
        tmpl = {
            "dense0_weight": jax.device_put(
                jnp.zeros_like(w),
                plan12.sharding("dense0_weight", w.shape)),
            "dense0_bias": jax.device_put(
                jnp.zeros_like(b),
                plan12.sharding("dense0_bias", b.shape)),
        }
        out = checkpoint.load_sharded(d, 0, tmpl)
        np.testing.assert_array_equal(np.asarray(out["dense0_weight"]), w)
        np.testing.assert_array_equal(np.asarray(out["dense0_bias"]), b)
        assert len(out["dense0_weight"].sharding.device_set) == 2
        # pre-flight diagnosis: spec_mismatches names the layouts that
        # will reshard instead of failing deep in device_put — while
        # validate_checkpoint stays clean (a mismatch is NOT corruption)
        step_dir = checkpoint._step_path(d, 0)
        diag = checkpoint.spec_mismatches(step_dir, tmpl)
        assert any("dense0_weight" in line for line in diag)
        assert checkpoint.validate_checkpoint(step_dir) == []
        # equivalent layouts never read as a mismatch (trailing-None
        # canonicalisation: P('dp') == P('dp', None))
        plan22b = shard.plan({"dp": 2, "tp": 2}, rules=_MLP_RULES)
        same = {
            "dense0_weight": jax.device_put(
                jnp.zeros_like(w),
                NamedSharding(plan22b.mesh, P("dp", None))),
            "dense0_bias": jax.device_put(
                jnp.zeros_like(b), NamedSharding(plan22b.mesh, P())),
        }
        assert checkpoint.spec_mismatches(step_dir, same) == []


def test_resize_mesh_live_matches_cold_resharded_restore():
    """Trainer.resize_mesh (2,2)->(1,2): live collective reshard keeps
    params/state bitwise, counts `shard_resharded_bytes` without any
    host gather, and training after the resize matches a cold resharded
    restore of the same state bit for bit."""
    X, y = _data()

    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.05}, kvstore="ici")
    tr.shard(mesh={"dp": 2, "tp": 2}, rules=_MLP_RULES)
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
    for _ in range(3):
        step(X, y)
    w_before = _weights(net)

    rb = registry().counter("shard_resharded_bytes")
    hg = registry().counter("shard_host_gather_bytes")
    b0, h0 = rb.value, hg.value
    tr.resize_mesh({"dp": 1, "tp": 2})
    assert rb.value > b0                  # state moved through redistribute
    assert hg.value == h0 == 0            # ... with no full host gather
    for a, b in zip(_weights(net), w_before):
        np.testing.assert_array_equal(a, b)
    p0 = list(net.collect_params().values())[0].data()._data
    assert len(p0.sharding.device_set) == 2     # now on the (1,2) mesh
    for _ in range(2):
        step(X, y)
        assert step.last_fallback_reason is None
    live = _weights(net)

    # cold twin: identical state restored onto a FRESH (1,2) trainer
    with tempfile.TemporaryDirectory() as d:
        f = os.path.join(d, "states.bin")
        net_c = _build(X)
        tr_c = gluon.Trainer(net_c.collect_params(), "adam",
                             {"learning_rate": 0.05}, kvstore="ici")
        tr_c.shard(mesh={"dp": 2, "tp": 2}, rules=_MLP_RULES)
        step_c = tr_c.capture(lambda a, b: _lossf(net_c(a), b).mean())
        for _ in range(3):
            step_c(X, y)
        tr_c.save_states(f)
        net_r = _build(X, seed=9)       # different init, fully restored
        for p, q in zip(net_r.collect_params().values(),
                        net_c.collect_params().values()):
            p.set_data(nd.array(q.data().asnumpy()))
        tr_r = gluon.Trainer(net_r.collect_params(), "adam",
                             {"learning_rate": 0.05}, kvstore="ici")
        tr_r.load_states(f)
        tr_r.shard(mesh={"dp": 1, "tp": 2}, rules=_MLP_RULES)
        step_r = tr_r.capture(lambda a, b: _lossf(net_r(a), b).mean())
        for _ in range(2):
            step_r(X, y)
        for a, b in zip(live, _weights(net_r)):
            np.testing.assert_array_equal(a, b)


def test_resize_same_device_set_respec_and_cycles():
    """(2,2)->(4,1) keeps the SAME device set — the donating jitted-
    identity respec path (collectives, source buffers donated) — and
    repeated shrink/grow cycles keep training without leaking stale
    executables (the respec cache is bounded)."""
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.05}, kvstore="ici")
    tr.shard(mesh={"dp": 2, "tp": 2}, rules=_MLP_RULES)
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
    for _ in range(3):
        step(X, y)
    w_before = _weights(net)
    tr.resize_mesh({"dp": 4, "tp": 1})
    for a, b in zip(_weights(net), w_before):
        np.testing.assert_array_equal(a, b)
    p0 = list(net.collect_params().values())[0].data()._data
    assert len(p0.sharding.device_set) == 4
    for axes in ({"dp": 2, "tp": 2}, {"dp": 4, "tp": 1},
                 {"dp": 2, "tp": 2}):
        tr.resize_mesh(axes)
        step(X, y)
        assert step.last_fallback_reason is None


def test_resize_mesh_grow_back_is_bitwise_with_zero_host_gather():
    """The GROW direction (1,2)->(2,2) — the supervisor's elastic
    grow-back (ISSUE 18) — is held to the same bar as the shrink:
    params/optimizer state bitwise across the resize, zero
    `shard_host_gather_bytes`, and a full shrink -> grow round trip
    lands back on the original layout bit for bit and keeps training."""
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.05}, kvstore="ici")
    tr.shard(mesh={"dp": 1, "tp": 2}, rules=_MLP_RULES)
    step = tr.capture(lambda a, b: _lossf(net(a), b).mean())
    for _ in range(3):
        step(X, y)
    w_before = _weights(net)

    rb = registry().counter("shard_resharded_bytes")
    hg = registry().counter("shard_host_gather_bytes")
    b0, h0 = rb.value, hg.value
    tr.resize_mesh({"dp": 2, "tp": 2})          # GROW onto new devices
    assert rb.value > b0
    assert hg.value == h0 == 0
    for a, b in zip(_weights(net), w_before):
        np.testing.assert_array_equal(a, b)
    p0 = list(net.collect_params().values())[0].data()._data
    assert len(p0.sharding.device_set) == 4     # now on the (2,2) mesh
    for _ in range(2):
        step(X, y)
        assert step.last_fallback_reason is None

    # the round trip the supervisor drives: shrink away, grow back
    w_mid = _weights(net)
    grown_sig = tr.shard_plan.signature()
    tr.resize_mesh({"dp": 1, "tp": 2})
    tr.resize_mesh({"dp": 2, "tp": 2})
    for a, b in zip(_weights(net), w_mid):
        np.testing.assert_array_equal(a, b)
    assert hg.value == 0
    # the regrown plan is a NEW object but the SAME structural layout:
    # its signature matches, so compiled executables are reusable
    assert tr.shard_plan.signature() == grown_sig
    step(X, y)
    assert step.last_fallback_reason is None


def test_plan_signature_is_structural():
    """Two independently-built plans with identical rules/axes/devices
    share a signature (executable-cache reuse across a grow-back); any
    structural difference — mesh shape, device set, rules — splits it."""
    p1 = shard.plan({"dp": 2, "tp": 2}, rules=_MLP_RULES)
    p2 = shard.plan({"dp": 2, "tp": 2}, rules=_MLP_RULES)
    assert p1 is not p2 and p1.plan_id != p2.plan_id
    assert p1.signature() == p2.signature()
    assert p1.with_mesh({"dp": 1, "tp": 2}).signature() != p1.signature()
    assert shard.plan({"dp": 2, "tp": 2}).signature() != p1.signature()
    devs = list(p1.mesh.devices.flatten())
    swapped = shard.plan(
        {"dp": 2, "tp": 2}, rules=_MLP_RULES,
        devices=[devs[1], devs[0]] + devs[2:])
    assert swapped.signature() != p1.signature()


def test_redistribute_same_mesh_respec_is_exact():
    mesh = _mesh22()
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                       NamedSharding(mesh, P("dp")))
    ref = np.asarray(x)
    out = shard.redistribute_array(x, NamedSharding(mesh, P(None, "tp")))
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert out.sharding.spec == P(None, "tp")
    # already in layout: returned unchanged, nothing counted
    c = registry().counter("shard_resharded_bytes")
    before = c.value
    again = shard.redistribute_array(out, NamedSharding(mesh,
                                                        P(None, "tp")))
    assert again is out and c.value == before


# ------------------------------------------------- prefetch placement
def test_prefetch_leaf_sharding_2d_and_non_leading_axis():
    from mxnet_tpu.prefetch import _leaf_sharding
    mesh = _mesh22()
    lead = NamedSharding(mesh, P("dp"))
    # divisible leading dim: spec applies untouched
    assert _leaf_sharding(lead, 2, (8, 4)) is lead
    # non-leading batch axis: dim 1 checked, not dim 0
    mid = NamedSharding(mesh, P(None, "dp"))
    assert _leaf_sharding(mid, 2, (3, 8)) is mid
    # scalar: replicated silently (no warning)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = _leaf_sharding(lead, 0, ())
    assert out.spec == P()
    # non-divisible batch dim: replicated WITH a (once-per-layout) warning
    with pytest.warns(RuntimeWarning, match="REPLICATED"):
        out = _leaf_sharding(mid, 2, (3, 7))
    assert out.spec == P()
    with warnings.catch_warnings():
        warnings.simplefilter("error")      # second time: silent
        out2 = _leaf_sharding(mid, 2, (3, 7))
    assert out2.spec == P()


def test_resolve_placement_accepts_plan_and_namedsharding():
    from mxnet_tpu.prefetch import resolve_placement
    plan = shard.plan({"dp": 2, "tp": 2})
    assert resolve_placement(plan) == plan.batch_sharding()
    sh = NamedSharding(plan.mesh, P(None, "dp"))
    assert resolve_placement(sh) is sh
    # a kvstore with a plan resolves to the plan's batch sharding
    kv = mx.kv.create("ici")
    kv.set_shard_plan(plan)
    assert resolve_placement(kv) == plan.batch_sharding()
