"""CachedOp-style one-executable training step (reference capability:
src/imperative/cached_op.cc — the engine behind `HybridBlock.hybridize` —
extended here to the WHOLE training step, the paper's "lazy graphs lower
to one jitted XLA executable" claim applied end to end).

`Trainer.capture(loss_fn)` (convenience: `mx.jit_step(trainer, loss_fn)`)
returns a `CachedStep` that compiles one full step into ONE jitted XLA
executable:

  * hybridized forward + loss — `loss_fn(*batch)` is traced functionally
    (parameters become program inputs via the same `_TraceContext`
    mechanism HybridBlock uses, so hybridized blocks inline and BatchNorm
    aux updates become extra outputs);
  * the backward via `jax.vjp` of that trace — no tape, no re-trace;
  * in-graph gradient reduction over the 'ici' mesh (the kvstore's
    `graph_allreduce` / `graph_reduce_scatter` lowering replaces the
    host-driven `allreduce_flat` round-trip, so XLA's latency-hiding
    scheduler overlaps the psum with backward compute, arXiv:2301.13062);
  * the AMP unscale + nonfinite/overflow guard as a `lax.cond` (the skip
    branch passes weights/state through untouched);
  * the multi-tensor optimizer update (the same staged numerics as the
    fused bucketed kernel — `multi_tensor.apply_param_update`).

Parameter and optimizer-state buffers are DONATED to the executable, so
Adam-family steps update in place instead of doubling live HBM.

Executables are cached by (batch avals, parameter signature, optimizer
state signature, scale mode, hyperparameters, mesh); per-step values —
lr/wd schedules, loss scale, rescale, the grad.nan poison, the RNG key —
ride in as weak-typed arguments and never retrace. Unsupported
configurations (custom-update optimizers, `update_on_kvstore`, gradient
compression, multi-process 'ici' without a mesh, host syncs inside
`loss_fn`) fall back TRANSPARENTLY to the imperative record/backward/step
path, with the reason recorded on `cachedop_fallbacks{reason=}`.

`sharded_update=True` (arXiv:2004.13336) additionally reduce-scatters
each eligible gradient, updates only this replica's row-shard of the
weight and optimizer state, and all-gathers the new weights inside the
same program; optimizer state stays row-sharded across steps (each
replica only ever touches its shard). Eligible = elementwise update rule
(`Optimizer.elementwise`) and dim 0 divisible by the mesh axis;
ineligible parameters take the replicated psum+update path in the same
executable.

Input interplay (mxnet_tpu/prefetch.py): a batch staged by the device
prefetcher with this step's exact mesh sharding enters the executable
with NO second placement; host batches pay a counted synchronous
transfer (`prefetch_h2d_sync`), and device-committed batches in a
different layout reshard with `cachedop_fallbacks{reason=resharded_input}`.

Reliability interplay (docs/RELIABILITY.md): captured steps still honor
the step watchdog (`MXTPU_STEP_TIMEOUT_MS`) and the `grad.nan` fault
point — the injection multiplies the in-graph gradients by a NaN poison
argument, so the overflow/nonfinite `lax.cond` reflex is chaos-testable
without leaving the executable.
"""
from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from . import autograd
from . import kvstore as kvs_mod
from . import profiler
from . import random as _random
from .gluon.block import _TraceContext
from .ndarray.ndarray import NDArray
from .observability import tracer as _tracer
from .observability import registry as _obs_registry
from .observability import compilex as _compilex
from .fault import injection as _finj

__all__ = ["CachedStep", "jit_step"]

_reg = _obs_registry()
_hits = _reg.counter("cachedop_cache_hits")
_miss_counters = {}      # reason -> Counter cachedop_cache_misses{reason=}
_fallback_counters = {}  # reason -> Counter cachedop_fallbacks{reason=}

# cache-key layout; positions feed miss-reason classification
_KEY_FIELDS = ("shape_change", "param_change", "state_change", "scale_mode",
               "hyper_change", "autocast", "mesh", "sharded", "grad_reduce",
               "clip", "plan", "sparse", "tiered")


def _mesh_fingerprint(mesh):
    """Structural identity of a mesh for executable cache keys: axis
    names, axis sizes, and the exact device ids in mesh order. Two
    meshes with the same fingerprint produce equal NamedShardings, so a
    step compiled over one runs over the other — which is what lets an
    elastic shrink → grow-back round trip (fault/supervisor.py) reuse
    the pre-shrink executables instead of recompiling (an `id(mesh)`
    key — the pre-PR-18 scheme — could not, since resize always builds
    a fresh Mesh object)."""
    return (tuple(mesh.axis_names),
            tuple(int(mesh.shape[a]) for a in mesh.axis_names),
            tuple(int(d.id) for d in mesh.devices.flatten()))


def _miss(reason):
    c = _miss_counters.get(reason)
    if c is None:
        c = _miss_counters[reason] = _reg.counter("cachedop_cache_misses",
                                                  reason=reason)
    c.inc()


def _fallback(reason):
    c = _fallback_counters.get(reason)
    if c is None:
        c = _fallback_counters[reason] = _reg.counter("cachedop_fallbacks",
                                                      reason=reason)
    c.inc()


_sparse_demotions = _reg.counter("cachedop_sparse_demotions")
_demotion_warned = set()    # param names already warned about


def _warn_sparse_demotion(name):
    """A `ShardedEmbedding` table used OUTSIDE its lookup sites (tied
    output projection, a norm over the raw weights, ...) cannot take
    the sparse fast path — the hoisted-table backward would silently
    drop the non-lookup use's gradient. It trains dense instead:
    correct numerics, O(vocab) gradient, and this one-per-name warning
    so the lost memory headline is visible."""
    _sparse_demotions.inc()
    if name in _demotion_warned:
        return
    _demotion_warned.add(name)
    warnings.warn(
        f"ShardedEmbedding table {name!r} is read outside its lookup "
        f"sites (tied projection / raw-weight use); the sparse "
        f"fast path cannot carry that use's gradient, so the table "
        f"trains through the DENSE path (correct, but materialises an "
        f"O(vocab) gradient). Untie the weight or look it up through "
        f"the block to regain the sparse path.", RuntimeWarning,
        stacklevel=3)


def _note_step_failure(exc):
    """Step-failure surfacing for the recovery supervisor: a captured (or
    fallback-imperative) step that DIES mid-flight records what killed it
    — ``cachedop_step_failures{kind=<exception type>}`` plus a trace
    instant — before the exception propagates, so a crash report written
    seconds later attributes the step death even when the raising layer's
    own telemetry was lost with the wedge. Cold path: the registry's
    (name, labels) memo is the handle cache."""
    kind = type(exc).__name__
    _reg.counter("cachedop_step_failures", kind=kind).inc()
    if _tracer.ACTIVE:
        _tracer.instant("cachedop.step_failure", cat="trainer",
                        args={"kind": kind, "error": str(exc)[:200]})


# executables retained per CachedStep; a full jitted step program is heavy
# (variable-length NLP batches would otherwise accumulate one per shape
# forever), so the cache is a bounded LRU like the backward cache's
_CACHE_MAX = 8


class _CaptureUnsupported(Exception):
    """Internal: this call cannot be captured — take the imperative path."""

    def __init__(self, reason):
        self.reason = reason
        super().__init__(reason)


# one aval-signature format shared with the backward cache, so the two
# cache-key layouts cannot drift apart
from .autograd import _aval_sig as _aval  # noqa: E402


def _dev0_view(a):
    """Zero-copy single-device view of a REPLICATED mesh output: shard 0
    holds the full logical value, and a one-device array keeps every
    eager/hybridized consumer (eval forwards, monitors, checkpoints)
    working without caring that the captured step ran on a mesh."""
    try:
        return a.addressable_shards[0].data
    except Exception:
        return a


def _logical_view(a):
    """The value eager code should see for a mesh output: a replicated
    array collapses to its zero-copy device-0 shard view; a genuinely
    SHARDED array (rule-driven FSDP/TP layout) IS its own logical value —
    it stays mesh-resident so the next step pays no re-placement and
    per-device memory stays at the shard size (.asnumpy()/save still see
    the full logical array)."""
    spec = getattr(getattr(a, "sharding", None), "spec", None)
    if spec is not None and any(e is not None for e in tuple(spec)):
        return a
    return _dev0_view(a)


def jit_step(trainer, loss_fn, **kwargs):
    """Convenience for `trainer.capture(loss_fn, **kwargs)`:

        step = mx.jit_step(trainer, lambda x, y: lossf(net(x), y).mean())
        for x, y in batches:
            loss = step(x, y)
    """
    return CachedStep(trainer, loss_fn, **kwargs)


class CachedStep:
    """One captured training step (see module docstring). Calling it runs
    forward + backward + gradient reduction + guard + optimizer update as
    one dispatch and returns `loss_fn`'s output (loss first) as NDArrays.

    `grad_reduce` ('mean', the default, or 'sum') states how the in-graph
    mesh reduction composes with the loss: a batch-MEAN loss needs the
    per-replica gradients averaged over the axis to match the imperative
    whole-batch semantics; a per-sample-SUM loss needs them summed.
    """

    def __init__(self, trainer, loss_fn, sharded_update=False,
                 grad_reduce="mean"):
        if grad_reduce not in ("mean", "sum"):
            raise MXNetError(f"grad_reduce must be 'mean' or 'sum', "
                             f"got {grad_reduce!r}")
        self._trainer = trainer
        self._loss_fn = loss_fn
        self._sharded = bool(sharded_update)
        self._grad_reduce = grad_reduce
        from collections import OrderedDict
        self._cache = OrderedDict()   # LRU: key -> (jfn, meta)
        self._last_key = None
        self._warned = set()
        # mesh captures: ("d"|"n", idx) -> (device-0 view, mesh-resident
        # array); as long as the param still holds the view, the next
        # step reuses the mesh copy instead of re-broadcasting
        self._mesh_cache = {}
        self.last_fallback_reason = None

    def _mesh_resident(self, kind, idx, cur):
        c = self._mesh_cache.get((kind, idx))
        if c is not None and c[0] is cur:
            return c[1]
        return cur

    def _store(self, key, entry):
        while len(self._cache) >= _CACHE_MAX:
            self._cache.popitem(last=False)
        self._cache[key] = entry

    # ------------------------------------------------------------------
    @property
    def cache_size(self):
        return len(self._cache)

    def hlo_info(self):
        """Optimized-HLO counts of the most recently dispatched
        executable (compilex inspection: fusions, collectives, copies,
        donation aliases, module bytes) — None before the first captured
        call or when inspection was skipped by policy. What
        tools/check_fusion.py budgets."""
        entry = self._cache.get(self._last_key)
        if entry is None or entry[0] == "unsupported":
            return None
        return getattr(entry[0], "last_hlo", None)

    @property
    def last_compile_seconds(self):
        """Wall clock of the most recent executable's compiling dispatch
        (measured by compilex BEFORE any HLO-inspection recompile, so it
        is the cost a training loop actually paid) — None if the current
        entry never compiled in this process."""
        entry = self._cache.get(self._last_key)
        if entry is None or entry[0] == "unsupported":
            return None
        return getattr(entry[0], "last_compile_seconds", None)

    def __call__(self, *batch, batch_size=None):
        try:
            if _tracer.ACTIVE:
                with _tracer.span("Trainer.captured_step", cat="trainer",
                                  args={"params": len(self._trainer._params),
                                        "sharded": self._sharded,
                                        "cache_size": len(self._cache)}):
                    return self._call_impl(batch, batch_size)
            return self._call_impl(batch, batch_size)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:
            _note_step_failure(e)
            raise

    def _call_impl(self, batch, batch_size):
        from . import prefetch as _prefetch_mod
        batch_nd = []
        for b in batch:
            if isinstance(b, NDArray):
                batch_nd.append(b)
                continue
            arr = jnp.asarray(b)
            if not isinstance(b, jax.Array):
                # a HOST batch converted inside the step dispatch is a
                # synchronous critical-path transfer — the device
                # prefetcher (mxnet_tpu/prefetch.py) exists to make this
                # count zero on warm steps
                _prefetch_mod.record_sync_h2d(
                    int(arr.size) * jnp.dtype(arr.dtype).itemsize)
            batch_nd.append(NDArray(arr))
        if batch_size is None:
            if not batch_nd or batch_nd[0].ndim == 0:
                raise MXNetError("capture: pass batch_size= when the first "
                                 "batch argument has no leading batch dim")
            batch_size = int(batch_nd[0].shape[0])
        self.last_fallback_reason = None
        try:
            return self._captured(batch_nd, batch_size)
        except _CaptureUnsupported as e:
            kv = getattr(self._trainer, "_kvstore", None)
            if kv is not None and getattr(kv, "_shard_plan", None) \
                    is not None:
                # with a shard plan the params/optimizer state live
                # SHARDED between steps — the imperative path would mix
                # mesh-resident and host arrays and train garbage, so
                # the fallback is NOT transparent here (fallback matrix:
                # docs/PERFORMANCE.md "Parameter sharding")
                raise MXNetError(
                    f"captured step with a shard plan cannot fall back "
                    f"to the imperative path (reason: {e.reason}); fix "
                    f"the configuration or detach the plan "
                    f"(kvstore.set_mesh) before training imperatively"
                ) from e
            self.last_fallback_reason = e.reason
            _fallback(e.reason)
            if e.reason not in self._warned:
                self._warned.add(e.reason)
                warnings.warn(f"CachedStep: falling back to the imperative "
                              f"path ({e.reason})", RuntimeWarning,
                              stacklevel=3)
            return self._imperative(batch_nd, batch_size)

    # --------------------------------------------------- imperative twin
    def _imperative(self, batch_nd, batch_size):
        """Reference-semantics fallback: record, backward on the (AMP-
        scaled) loss, `Trainer.step`. Same return value as the captured
        path (the RAW loss, not the scaled one)."""
        from . import amp
        for p in self._trainer._params:
            # the imperative path computes dense grads for everything;
            # drop any sparse pair an earlier captured step left behind
            if getattr(p, "_sparse_grad", None) is not None:
                p._sparse_grad = None
        from .shard import moe as _smoe
        with autograd.record():
            with _smoe.capture_scope(None) as moe_tape:
                out = self._loss_fn(*batch_nd)
            leaves, treedef = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, NDArray))
            if not leaves or not isinstance(leaves[0], NDArray):
                raise MXNetError("capture: loss_fn must return an NDArray "
                                 "loss (optionally nested with extra "
                                 "outputs, loss leaf first)")
            # MoE load-balancing aux losses join the head exactly like
            # the captured path does (same loss value either way)
            for aux_l in moe_tape.losses:
                leaves[0] = leaves[0] + aux_l
            if moe_tape.losses:
                out = jax.tree_util.tree_unflatten(treedef, leaves)
            sc = amp.scaler()
            head = leaves[0] * sc.loss_scale if sc is not None else leaves[0]
        head.backward()
        self._trainer.step(batch_size)
        return out

    # ------------------------------------------------------ captured path
    def _captured(self, batch_nd, batch_size):
        tr = self._trainer
        opt = tr._optimizer
        from . import amp
        from .optimizer import multi_tensor
        if tr._update_on_kvstore:
            raise _CaptureUnsupported("update_on_kvstore")
        if not multi_tensor.supports(opt):
            raise _CaptureUnsupported("optimizer")
        kv = tr._kvstore
        spec = None
        plan = None
        if kv is not None and kv.type == "ici":
            if kv._compression is not None:
                raise _CaptureUnsupported("compression")
            plan = kv.shard_plan()
            if plan is None:
                spec = kv.capture_spec()
                if spec is None and jax.process_count() > 1:
                    raise _CaptureUnsupported("multiprocess")
        if self._sharded and plan is not None:
            raise MXNetError(
                "sharded_update=True composes with the 1-D replicated "
                "mesh only; a shard plan already shards weights and "
                "optimizer state per-rule — drop sharded_update")
        if self._sharded and spec is None:
            raise MXNetError(
                "sharded_update=True needs an 'ici' kvstore with a "
                "multi-device mesh attached (kvstore.set_mesh)")
        params = tr._params
        if any(p._deferred_init is not None for p in params):
            raise _CaptureUnsupported("deferred_init")
        diff = [(i, p) for i, p in enumerate(params)
                if p.grad_req != "null" and p._data is not None
                and p._grad is not None]
        if not diff:
            raise _CaptureUnsupported("no_grads")
        if spec is not None:
            _, _, n_rep = spec
            for b in batch_nd:
                if b.ndim == 0 or b.shape[0] % n_rep:
                    raise _CaptureUnsupported("batch_not_divisible")
        if plan is not None and jax.process_count() > 1:
            # multi-controller plan sharding would need host batches
            # placed onto non-addressable devices — refuse cleanly here
            # (the no-fallback rule turns this into an MXNetError)
            # instead of dying inside device_put
            raise _CaptureUnsupported("multiprocess")
        # NB under a plan a batch whose dim 0 the data axis does not
        # divide is NOT an error: every such leaf replicates
        # (per-leaf, in the build's batch_sh) and the global-batch loss
        # math is unchanged — a routine end-of-epoch partial batch must
        # degrade (one extra cache entry, no dp parallelism for that
        # step), never abort a run that has no imperative fallback.

        scaler = amp.scaler()
        scale_mode = ("amp" if scaler is not None
                      else "skip" if tr.skip_nonfinite else "none")

        # sparse-embedding fast-path eligibility (ISSUE 15): marked
        # `ShardedEmbedding` tables, row-sharded by their rule over one
        # mesh axis, elementwise optimizer — shard/embedding.py
        from .shard import embedding as _semb
        sparse_info = _semb.sparse_eligibility(plan, diff, opt)

        # tiered tables (ISSUE 19): a converted parameter's live data IS
        # the hot cache — it can only train through the captured sparse
        # path fed by a RowPrefetcher, never imperatively
        tiered_ks = {k: p._tiered_state for k, (i, p) in enumerate(diff)
                     if getattr(p, "_tiered_state", None) is not None}
        if tiered_ks and plan is None:
            names = sorted(diff[k][1].name for k in tiered_ks)
            raise MXNetError(
                f"tiered embedding tables {names} can only train under "
                f"an active shard plan (the live parameter is the hot "
                f"cache, not the logical table); call Trainer.shard "
                f"and capture the step")

        updater = tr._updater
        state_nds = []
        for i, p in diff:
            if i not in updater.states:
                updater.states[i] = opt.create_state_multi_precision(
                    i, p.data())
            st = updater.states[i]
            st = st if isinstance(st, tuple) else \
                ((st,) if st is not None else ())
            state_nds.append(st)

        key = (
            tuple(_aval(b._data) for b in batch_nd),
            tuple(p._struct_sig() for p in params),
            tuple(tuple(_aval(s._data) for s in sv) for sv in state_nds),
            scale_mode,
            multi_tensor._hyper_sig(opt),
            str(amp.autocast_dtype()),
            None if spec is None else (_mesh_fingerprint(spec[0]),
                                       spec[1], spec[2]),
            self._sharded,
            self._grad_reduce,
            None if opt.clip_gradient is None else float(opt.clip_gradient),
            None if plan is None else plan.signature(),
            tuple(sorted((k, v["axis"]) for k, v in sparse_info.items())),
            tuple(sorted(tiered_ks)),
        )
        entry = self._cache.get(key)
        if entry is None:
            _miss(self._miss_reason(key))
            profiler.record_jit_cache(False)
            self._last_key = key
            try:
                entry = self._build(batch_nd, diff, state_nds, scale_mode,
                                    spec, plan, sparse_info, tiered_ks)
            except _CaptureUnsupported as e:
                # negative-cache the failure: later steps with the same
                # signature skip straight to the imperative path instead
                # of re-running the abstract pre-pass every step
                self._store(key, ("unsupported", e.reason))
                raise
            self._store(key, entry)
        elif entry[0] == "unsupported":
            self._cache.move_to_end(key)
            self._last_key = key
            raise _CaptureUnsupported(entry[1])
        else:
            self._cache.move_to_end(key)
            _hits.inc()
            profiler.record_jit_cache(True)
            self._last_key = key
        jfn, meta = entry
        try:
            return self._dispatch(jfn, meta, batch_nd, diff, state_nds,
                                  batch_size, scaler, scale_mode)
        except _CaptureUnsupported as e:
            # a first-dispatch compile failure is as permanent as a build
            # failure: negative-cache it so later steps skip straight to
            # the imperative path
            self._store(key, ("unsupported", e.reason))
            raise

    def _miss_reason(self, key):
        last = self._last_key
        if last is None:
            return "first"
        for name, a, b in zip(_KEY_FIELDS, key, last):
            if a != b:
                return name
        return "other"

    # ------------------------------------------------------------ build
    def _build(self, batch_nd, diff, state_nds, scale_mode, spec,
               plan=None, sparse_info=None, tiered_ks=None):
        tr = self._trainer
        opt = tr._optimizer
        kv = tr._kvstore
        from .optimizer import multi_tensor as _mt
        from .optimizer.multi_tensor import apply_param_update
        from .jax_compat import shard_map
        from .shard import embedding as _semb
        from .shard import moe as _smoe
        from jax.sharding import PartitionSpec as P
        sparse_info = sparse_info or {}
        tiered_ks = tiered_ks or {}

        diff_ids = {id(p) for _, p in diff}
        diff_params = [p for _, p in diff]
        nondiff = [p for p in tr._params
                   if p._data is not None and id(p) not in diff_ids]
        guard = scale_mode != "none"
        unscale = scale_mode == "amp"
        clip = None if opt.clip_gradient is None else float(opt.clip_gradient)
        mp_flags = [bool(opt.multi_precision
                         and p.data()._data.dtype != np.float32)
                    for _, p in diff]
        n_diff = len(diff)
        mean = self._grad_reduce == "mean"
        mesh = axis = None
        n_rep = 1
        if spec is not None:
            mesh, axis, n_rep = spec

        # per-param sharded-update eligibility (arXiv:2004.13336);
        # irrelevant under a shard plan (rules own the layout there)
        shard_ok = []
        for (i, p), sv in zip(diff, state_nds):
            w = p.data()._data
            shard_ok.append(bool(
                self._sharded and type(opt).elementwise and w.ndim >= 1
                and w.shape[0] >= n_rep and w.shape[0] % n_rep == 0
                and all(s._data.shape == w.shape or s._data.ndim == 0
                        for s in sv)))

        # rule-resolved per-parameter specs (the GSPMD-lowered path):
        # grads are pinned to the weight's layout IN-GRAPH so they
        # materialise already reduce-scattered (kvstore.graph_constrain)
        plan_specs = None
        if plan is not None:
            plan_specs = [plan.spec_for(p.name, p.data()._data.shape)
                          for _, p in diff]

        loss_fn = self._loss_fn
        meta = {"treedef": None, "n_out": 0, "aux": [], "nondiff": nondiff}

        def traced(rng, diff_vals, nondiff_vals, batch_vals):
            """Functional run of loss_fn: every trainer parameter reads its
            traced value, layer RNG flows from `rng`, aux updates (BN
            running stats) are captured as outputs."""
            nd_list = meta["nondiff"]
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(True)
            try:
                with _TraceContext(rng) as tctx, \
                        _smoe.capture_scope(plan) as moe_tape:
                    for p, v in zip(diff_params, diff_vals):
                        p._trace_override = NDArray(v)
                    for p, v in zip(nd_list, nondiff_vals):
                        p._trace_override = NDArray(v)
                    out = loss_fn(*[NDArray(v) for v in batch_vals])
                    leaves, treedef = jax.tree_util.tree_flatten(
                        out, is_leaf=lambda x: isinstance(x, NDArray))
                    if not leaves or not all(isinstance(l, NDArray)
                                             for l in leaves):
                        raise MXNetError(
                            "capture: loss_fn must return NDArray(s), "
                            "loss leaf first")
                    # MoE aux losses (load balancing) join the loss
                    # head HERE, inside the trace — so they are part of
                    # the differentiated program and their gradient
                    # drives the router (shard/moe.py)
                    head = leaves[0]
                    for aux_l in moe_tape.losses:
                        head = head + aux_l
                    meta["treedef"] = treedef
                    meta["n_out"] = len(leaves)
                    meta["aux"] = [p for p, _ in tctx.aux_updates]
                    meta["moe_sites"] = list(moe_tape.sites)
                    return ([head._data] +
                            [l._data for l in leaves[1:]],
                            [v._data if isinstance(v, NDArray) else v
                             for _, v in tctx.aux_updates])
            finally:
                for p in diff_params:
                    p._trace_override = None
                for p in nd_list:
                    p._trace_override = None
                autograd.set_recording(prev_rec)
                autograd.set_training(prev_train)

        # abstract pre-pass: (a) surface trace errors (host syncs inside
        # loss_fn) as a clean fallback, (b) discover the aux-update set so
        # aux params NOT already program inputs become ones (else their
        # values would bake in as compile-time constants)
        rng0 = _random._next_key()
        dvals = [p.data()._data for p in diff_params]
        bvals = [b._data for b in batch_nd]

        from .gluon import parameter as _param_mod

        def _prepass():
            # the watch collects Parameters whose CONCRETE data the trace
            # reads (no override installed): non-trainer params a
            # fine-tuning loss_fn touches — left alone they would bake in
            # as compile-time constants and go stale on set_data()
            watch = set()
            prev = _param_mod._capture_watch
            _param_mod._capture_watch = watch
            try:
                nvals0 = [p._data._data for p in meta["nondiff"]]
                jax.eval_shape(traced, rng0, dvals, nvals0, bvals)
            finally:
                _param_mod._capture_watch = prev
            return watch

        try:
            for _ in range(3):   # promotion closes after one extra pass
                watch = _prepass()
                known = set(diff_ids)
                known.update(id(p) for p in meta["nondiff"])
                promote = [p for p in watch
                           if p._data is not None and id(p) not in known]
                promote += [p for p in meta["aux"]
                            if id(p) not in known
                            and all(p is not q for q in promote)]
                if not promote:
                    break
                meta["nondiff"] = meta["nondiff"] + promote
        except MXNetError:
            raise
        except _CaptureUnsupported:
            raise
        except Exception as e:
            raise _CaptureUnsupported(
                f"trace_error:{type(e).__name__}") from e
        if mesh is not None and meta["n_out"] != 1:
            # extra outputs have no canonical cross-replica layout
            raise _CaptureUnsupported("extra_outputs_mesh")
        nondiff = meta["nondiff"]
        pos_of = {id(p): j for j, p in enumerate(nondiff)}
        meta["aux_pos"] = [pos_of.get(id(p)) for p in meta["aux"]]

        # sparse-embedding site discovery (ISSUE 15): one more abstract
        # pass with the RECORD context installed tells us which eligible
        # tables the model actually looks up and with what index shapes
        # — the out_shardings pytree below needs that before tracing.
        # An eligible table with no lookup site trains dense (zero grad).
        # The pass traces to a JAXPR with the diff values as the
        # arguments: record-mode lookups never touch the table value, so
        # a table whose argument is still REFERENCED anywhere has a
        # NON-lookup use (a tied output projection, a norm over the raw
        # weights, ...). Its cotangent could not ride the sparse row
        # block — the fast path would silently drop that use's gradient
        # — so such a table DEMOTES to the dense path (correct numerics,
        # dense O(vocab) gradient), loudly.
        sparse_live = {}
        if sparse_info:
            rec = _semb.SparseLookupContext(
                "record", [id(diff_params[k]) for k in sparse_info])
            try:
                with rec:
                    nvals0 = [p._data._data for p in meta["nondiff"]]
                    closed = jax.make_jaxpr(
                        lambda dv: traced(rng0, dv, nvals0, bvals))(
                        dvals)
            except MXNetError:
                raise
            except Exception as e:
                raise _CaptureUnsupported(
                    f"trace_error:{type(e).__name__}") from e
            # every reference to a top-level arg appears in some eqn's
            # (or the output's) invars — call-style primitives receive
            # outer vars at their call site, so no recursion is needed.
            # A pass-through into a sub-jaxpr counts as a use: that can
            # only demote (dense = always-correct), never miss a use.
            referenced = set()
            for eqn in closed.jaxpr.eqns:
                referenced.update(id(v) for v in eqn.invars)
            referenced.update(id(v) for v in closed.jaxpr.outvars)
            for k, info in sparse_info.items():
                sites = rec.sites.get(id(diff_params[k]))
                if not sites:
                    continue
                if id(closed.jaxpr.invars[k]) in referenced:
                    _warn_sparse_demotion(diff_params[k].name)
                    continue
                shapes = [tuple(int(d) for d in s.shape) for s in sites]
                n_flat = sum(
                    int(np.prod(shp, dtype=np.int64)) if shp else 1
                    for shp in shapes)
                sparse_live[k] = dict(info, site_shapes=shapes,
                                      n_flat=n_flat)
        live_ks = sorted(sparse_live)
        dense_ks = [k for k in range(n_diff) if k not in sparse_live]

        # tiered hot caches (ISSUE 19) are hard-wired to the sparse fast
        # path: a tiered table that fell off it (demoted by a direct
        # table reference, tied weights, or no recorded lookup) cannot
        # train — the dense path would read the cache as if it were the
        # logical table. Loud, no fallback.
        tiered_live = sorted(tiered_ks)
        for k in tiered_live:
            if k not in sparse_live:
                raise MXNetError(
                    f"tiered embedding {diff_params[k].name!r} did not "
                    f"take the sparse fast path this step (demoted by a "
                    f"direct table reference, or the table was never "
                    f"looked up) — a tiered table trains only through "
                    f"the sparse lookup; remove direct uses of the "
                    f"weight from loss_fn")
        if tiered_live:
            meta["tiered"] = [
                (k, int(sparse_live[k]["n_flat"]),
                 2 + sum(bool(b) for b in tiered_ks[k].row_like))
                for k in tiered_live]

        def program(batch_vals, diff_vals, nondiff_vals, state_vals, rng,
                    lrs, wds, rescale, inv_scale, loss_scale, poison,
                    tiered_vals=()):
            if tiered_vals:
                # scatter the prefetcher's staged cold rows into their
                # slots FIRST — the record pass, lookup, and scatter-add
                # update below all see the filled cache. Sentinel slot
                # ids (== n_slots) drop; an all-hit step scatters an
                # all-sentinel block (pure device no-op after fusion).
                diff_vals = list(diff_vals)
                state_vals = [list(sv) for sv in state_vals]
                off = 0
                for k in tiered_live:
                    ts = tiered_ks[k]
                    ax = sparse_live[k]["axis"]
                    inc_slots = tiered_vals[off]
                    inc_rows = tiered_vals[off + 1]
                    off += 2
                    diff_vals[k] = _semb.scatter_rows(
                        diff_vals[k], inc_slots, inc_rows, plan.mesh, ax)
                    for j, rl in enumerate(ts.row_like):
                        if not rl:
                            continue
                        state_vals[k][j] = _semb.scatter_rows(
                            state_vals[k][j], inc_slots,
                            tiered_vals[off], plan.mesh, ax)
                        off += 1
            se = {}
            if sparse_live:
                # discovery pass with CONCRETE tracers: record each
                # lookup site's index value. Only the recorded index
                # extraction survives DCE — the rest of this forward is
                # dead (its outputs are unused).
                rec = _semb.SparseLookupContext(
                    "record", [id(diff_params[k]) for k in live_ks])
                with rec:
                    traced(rng, diff_vals, nondiff_vals, batch_vals)
                for k in live_ks:
                    info = sparse_live[k]
                    sites = rec.sites[id(diff_params[k])]
                    flats = [s.reshape(-1).astype(jnp.int32)
                             for s in sites]
                    flat = jnp.concatenate(flats) if len(flats) > 1 \
                        else flats[0]
                    # dedup: each distinct row crosses the interconnect
                    # once per step; the sentinel (vocab) is out of
                    # range on every shard, so scatters drop pad slots
                    uniq, inv = jnp.unique(
                        flat, size=int(flat.shape[0]),
                        fill_value=info["vocab"], return_inverse=True)
                    inv = inv.reshape(-1).astype(jnp.int32)
                    rows = _semb.gather_rows(diff_vals[k], uniq,
                                             plan.mesh, info["axis"])
                    segs, off = [], 0
                    for shp in info["site_shapes"]:
                        segs.append((off, shp))
                        off += int(np.prod(shp, dtype=np.int64)) \
                            if shp else 1
                    se[k] = [uniq, inv, rows, segs]

            def run_traced(dv_full, consume_rows=None):
                if not sparse_live:
                    return traced(rng, dv_full, nondiff_vals, batch_vals)
                cctx = _semb.SparseLookupContext(
                    "consume", [id(diff_params[k]) for k in live_ks])
                for k, r in zip(live_ks, consume_rows):
                    uniq, inv, _, segs = se[k]
                    cctx.set_rows(diff_params[k], r, inv, segs)
                with cctx:
                    return traced(rng, dv_full, nondiff_vals, batch_vals)

            if sparse_live:
                # the tables are HOISTED OUT of the vjp: the gathered
                # (U, D) row blocks are the differentiable inputs, so
                # the backward materialises a dense-of-touched block +
                # indices, never an O(vocab) gradient
                def fwd(dv_dense, rows_list):
                    full = list(diff_vals)
                    for k, v in zip(dense_ks, dv_dense):
                        full[k] = v
                    leaves, aux = run_traced(full, rows_list)
                    return leaves[0], (leaves[1:], aux)

                head, vjp_fn, (extra, aux_vals) = jax.vjp(
                    fwd, [diff_vals[k] for k in dense_ks],
                    [se[k][2] for k in live_ks], has_aux=True)
                cot = jnp.ones_like(head) * jnp.asarray(loss_scale,
                                                        head.dtype)
                g_dense, g_rows_list = vjp_fn(cot)
                grads = [None] * n_diff
                for k, g in zip(dense_ks, g_dense):
                    grads[k] = g * poison
                g_rows = {k: g * poison
                          for k, g in zip(live_ks, g_rows_list)}
            else:
                def fwd(dv):
                    leaves, aux = traced(rng, dv, nondiff_vals,
                                         batch_vals)
                    return leaves[0], (leaves[1:], aux)

                head, vjp_fn, (extra, aux_vals) = jax.vjp(
                    fwd, diff_vals, has_aux=True)
                cot = jnp.ones_like(head) * jnp.asarray(loss_scale,
                                                        head.dtype)
                grads = list(vjp_fn(cot)[0])
                # grad.nan fault point: poison is 1.0 unless the
                # injection schedule fired this step (then NaN) — same
                # reflex test as the imperative trainer's gradient
                # poisoning, in-graph
                grads = [g * poison for g in grads]
                g_rows = {}

            if plan_specs is not None:
                # rule-driven layout: no explicit psum — the loss is
                # computed over the GLOBAL batch, so the dp reduction is
                # already part of the backward; the constraint makes each
                # gradient land reduce-scattered into its weight's layout
                # (sparse-path tables have no dense gradient to constrain)
                grads = [g if g is None else kv.graph_constrain(g, ps)
                         for g, ps in zip(grads, plan_specs)]

            if mesh is not None:
                grads = [
                    kv.graph_reduce_scatter(g, axis, n_rep, mean=mean)
                    if sh else kv.graph_allreduce(g, axis, n_rep, mean=mean)
                    for g, sh in zip(grads, shard_ok)]
                head = kv.graph_allreduce(head, axis, n_rep, mean=mean)
                aux_vals = [kv.graph_allreduce(v, axis, n_rep, mean=True)
                            for v in aux_vals]

            # local (shard) views of weights; states arrive pre-sharded
            # through their in_specs
            w_locals, sv_locals = [], []
            for k in range(n_diff):
                w = diff_vals[k]
                sv = tuple(state_vals[k])
                if shard_ok[k]:
                    chunk = w.shape[0] // n_rep
                    ridx = jax.lax.axis_index(axis)
                    w = jax.lax.dynamic_slice_in_dim(w, ridx * chunk,
                                                     chunk, 0)
                w_locals.append(w)
                sv_locals.append(sv)

            flag = jnp.zeros((), jnp.int32)
            if guard:
                shard_cnt = sum(
                    (jnp.sum(~jnp.isfinite(g.astype(jnp.float32)),
                             dtype=jnp.int32)
                     for g, sh in zip(grads, shard_ok) if sh),
                    jnp.zeros((), jnp.int32))
                repl_cnt = sum(
                    (jnp.sum(~jnp.isfinite(g.astype(jnp.float32)),
                             dtype=jnp.int32)
                     for g, sh in zip(grads, shard_ok)
                     if not sh and g is not None),
                    jnp.zeros((), jnp.int32))
                # sparse rows count into the same reflex: a nonfinite
                # touched-row gradient skips the whole update
                repl_cnt = repl_cnt + sum(
                    (jnp.sum(~jnp.isfinite(g.astype(jnp.float32)),
                             dtype=jnp.int32) for g in g_rows.values()),
                    jnp.zeros((), jnp.int32))
                if mesh is not None and any(shard_ok):
                    shard_cnt = kv.graph_allreduce(shard_cnt, axis, n_rep)
                flag = ((shard_cnt + repl_cnt) > 0).astype(jnp.int32)

            def _sparse_out_g(k):
                og = g_rows[k] * inv_scale if unscale else g_rows[k]
                return (se[k][0], og)

            def do_update(_):
                nws, nss, ogs = [], [], []
                for k in range(n_diff):
                    if k in sparse_live:
                        # scatter-add arm (ISSUE 15): touched rows are
                        # gathered, staged through the exact multi-
                        # tensor numerics, and written back on the
                        # OWNING shard only — the donated table/state
                        # buffers update in place, untouched rows never
                        # move (lazy/sparse-update semantics)
                        uniq = se[k][0]

                        def stage(w_r, g_r, sv_r, _k=k):
                            nw, ns, _ = _mt.sparse_update_rows(
                                opt, w_r, g_r, sv_r, lrs[_k], wds[_k],
                                mp_flags[_k], clip, rescale,
                                inv_scale if unscale else None)
                            return nw, ns

                        nw, ns = _semb.sparse_row_update(
                            w_locals[k], sv_locals[k], uniq, g_rows[k],
                            plan.mesh, sparse_live[k]["axis"], stage)
                        nws.append(nw)
                        nss.append(ns)
                        ogs.append(_sparse_out_g(k))
                        continue
                    nw, ns, og = apply_param_update(
                        opt, w_locals[k], grads[k], sv_locals[k],
                        lrs[k], wds[k], mp_flags[k], clip, rescale,
                        inv_scale if unscale else None)
                    nws.append(nw)
                    nss.append(ns)
                    ogs.append(og if og is not None else grads[k])
                return tuple(nws), tuple(nss), tuple(ogs)

            def skip_update(_):
                # grads still end unscaled on the skip path (per-param
                # path parity: amp.unscale runs before the skip)
                ogs = tuple(
                    _sparse_out_g(k) if k in sparse_live
                    else (grads[k] * inv_scale if unscale else grads[k])
                    for k in range(n_diff))
                return (tuple(w_locals),
                        tuple(tuple(sv) for sv in sv_locals), ogs)

            if guard:
                new_ws, new_ss, out_gs = jax.lax.cond(
                    flag > 0, skip_update, do_update, None)
            else:
                new_ws, new_ss, out_gs = do_update(None)

            if mesh is not None and any(shard_ok):
                # sharded params: all-gather the new weights IN-PROGRAM;
                # states and grads stay row-sharded (out_specs P(axis))
                new_ws = tuple(
                    kv.graph_all_gather(w, axis) if sh else w
                    for w, sh in zip(new_ws, shard_ok))
            return ([head] + list(extra), list(aux_vals), list(new_ws),
                    [tuple(sv) for sv in new_ss], list(out_gs), flag)

        jit_kwargs = {}
        if mesh is None and plan is None:
            fn = program
        elif plan is not None:
            # Rule-driven GSPMD lowering: the program itself contains no
            # explicit collectives — inputs arrive committed to their
            # per-rule NamedShardings (dispatch places them once;
            # thereafter a no-op), out_shardings pin params/state/grads
            # to the SAME layouts so donation reuses the sharded buffers
            # in place, and the partitioner inserts the FSDP
            # gather-before-use / reduce-scatter-after-backward and TP
            # collectives the specs imply.
            from jax.sharding import NamedSharding
            fn = program
            pmesh = plan.mesh
            repl = NamedSharding(pmesh, P())
            n_dp = int(pmesh.shape[plan.data_axis])
            bsh = plan.batch_sharding()

            def batch_sh(b):
                if b.ndim >= 1 and b.shape[0] % n_dp == 0:
                    return bsh
                return repl

            diff_sh = [NamedSharding(pmesh, ps) for ps in plan_specs]
            nondiff_sh = [plan.sharding(p.name, p._data._data.shape)
                          for p in nondiff]
            state_sh = []
            for (i, p), sv in zip(diff, state_nds):
                w_shape = p.data()._data.shape
                state_sh.append(tuple(
                    NamedSharding(pmesh, plan.state_spec(
                        p.name, w_shape, s._data.shape)) for s in sv))
            aux_sh = [plan.sharding(p.name, p._data._data.shape)
                      for p in meta["aux"]]
            # grads: dense params land in their weight's layout; a
            # sparse-path table's "gradient" is the (unique_ids, rows)
            # pair — replicated, O(touched), never O(vocab)
            grad_sh = [(repl, repl) if k in sparse_live else diff_sh[k]
                       for k in range(len(diff_sh))]
            jit_kwargs["out_shardings"] = (
                [repl] * meta["n_out"],      # loss leaves: replicated
                aux_sh,
                diff_sh,                     # new weights keep their rule
                state_sh,                    # state stays sharded
                grad_sh,
                repl,                        # guard flag
            )
            meta["shardings"] = (
                [batch_sh(b) for b in batch_nd],
                diff_sh, nondiff_sh, state_sh, repl,
            )
            # per-spec collective accounting: gradient bytes entering the
            # cross-replica reduction, attributed to the layout that rule
            # produced (kv_collective_bytes{op=spmd_grad_reduce,spec=});
            # sparse tables account their all-to-all payloads instead —
            # per step per table: one (shards, U) int32 index exchange
            # plus one (shards, U, D) vector return
            per_spec = {}
            for k, ((i, p), ps) in enumerate(zip(diff, plan_specs)):
                if k in sparse_live:
                    continue
                g = p._grad._data
                nbytes = int(g.size) * jnp.dtype(g.dtype).itemsize
                per_spec[str(ps)] = per_spec.get(str(ps), 0) + nbytes
            meta["coll_specs"] = sorted(per_spec.items())
            embed_bytes = 0
            for k, info in sparse_live.items():
                n_sh = int(pmesh.shape[info["axis"]])
                itemsize = jnp.dtype(
                    diff[k][1].data()._data.dtype).itemsize
                embed_bytes += n_sh * info["n_flat"] * (
                    4 + info["dim"] * itemsize)
            meta["embed_bytes"] = embed_bytes
        else:
            def state_spec(k, sv):
                return tuple(
                    P(axis) if shard_ok[k] and s._data.ndim != 0 else P()
                    for s in sv)

            in_specs = (
                [P(axis)] * len(batch_nd),
                [P()] * n_diff,
                [P()] * len(nondiff),
                [state_spec(k, sv) for k, sv in enumerate(state_nds)],
                P(),
                tuple(P() for _ in range(n_diff)),
                tuple(P() for _ in range(n_diff)),
                P(), P(), P(), P(),
            )
            out_specs = (
                [P()],                                   # head (reduced)
                [P()] * len(meta["aux"]),                # aux (pmean'd)
                [P()] * n_diff,                          # new weights
                [state_spec(k, sv) for k, sv in enumerate(state_nds)],
                [P(axis) if sh else P() for sh in shard_ok],   # grads
                P(),                                     # guard flag
            )
            fn = shard_map(program, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
            # imperative arrays are committed to one device; resharding
            # onto the mesh must be explicit (jit refuses to guess).
            # device_put is a no-op once an array already carries the
            # right sharding — params/state pay it on the first step only.
            from jax.sharding import NamedSharding
            repl = NamedSharding(mesh, P())
            meta["shardings"] = (
                [NamedSharding(mesh, P(axis)) for _ in batch_nd],
                [repl] * n_diff,
                [repl] * len(nondiff),
                [tuple(NamedSharding(mesh, s)
                       for s in state_spec(k, sv))
                 for k, sv in enumerate(state_nds)],
                repl,
            )

        # MoE routing sites the trace reported (shard/moe.py tape): the
        # sharded ones carry their static a2a byte cost; a site that
        # fell back to local dispatch carries bytes=0 plus its reason —
        # loud accounting, the demotion-not-silent discipline
        moe_sites = meta.get("moe_sites") or []
        moe_live = plan is not None and any(s["sharded"]
                                            for s in moe_sites)
        meta["moe_bytes"] = sum(s.get("bytes", 0) for s in moe_sites)

        # compile observatory (observability/compilex.py): the captured
        # step's compiles/HLO structure publish under the executable name
        # check_fusion budgets — "sharded_embed_step" when the sparse
        # embedding fast path is live (its all-to-all count is pinned),
        # "moe_step" when expert-parallel MoE routing is live under a
        # plan (its all-to-all count is pinned too; a model with BOTH
        # sparse tables and MoE keeps the embed name — the sparse path
        # restructures the program, MoE only adds in-graph collectives),
        # "sharded_step" when a rule plan owns the layout,
        # "captured_step" otherwise (single-device or 1-D mesh)
        exe_name = ("sharded_embed_step" if sparse_live
                    else "moe_step" if moe_live
                    else "sharded_step" if plan is not None
                    else "captured_step")
        # autotune (ISSUE 20): the shard-plan signature versions any
        # stored compile-space winner (a winner tuned under one layout
        # is stale under another, tune_stale{reason=plan}), and the
        # training step's numerics contract is the documented fp
        # tolerance — optimisation may re-associate, not drift
        from . import tune as _tune
        _tune.note_plan(exe_name,
                        None if plan is None else str(plan.signature()))
        _tune.register_contract(exe_name, "allclose", rtol=1e-5,
                                atol=1e-7)
        jfn = _compilex.instrument(
            jax.jit(fn, donate_argnums=(1, 3), **jit_kwargs), exe_name)
        meta.update({
            "fresh": True,     # first dispatch compiles: scope the CPU
                               # donation-noop warning to that call only
            "guard": guard,
            "unscale": unscale,
            "shard_ok": shard_ok,
            "mesh": spec,
            "plan": plan is not None,
            "sparse": sorted(sparse_live),
            "coll_bytes": 0 if mesh is None else sum(
                int(p._grad._data.size)
                * jnp.dtype(p._grad._data.dtype).itemsize
                for _, p in diff),
            "coll_op": ("in_graph_reduce_scatter"
                        if any(shard_ok) else "in_graph_psum"),
        })
        return jfn, meta

    # --------------------------------------------------------- dispatch
    def _dispatch(self, jfn, meta, batch_nd, diff, state_nds, batch_size,
                  scaler, scale_mode):
        tr = self._trainer
        opt = tr._optimizer
        tr._optimizer.rescale_grad = tr._scale / batch_size
        # optimistic update-count bump (the skip branch rolls it back, so
        # lr schedules see exactly what the imperative skip leaves behind)
        snapshot = (opt.num_update,
                    {i: opt._index_update_count.get(i) for i, _ in diff})
        for i, _ in diff:
            opt._update_count(i)
        lrs = tuple(float(opt._get_lr(i)) for i, _ in diff)
        wds = tuple(float(opt._get_wd(i)) for i, _ in diff)
        rescale = float(opt.rescale_grad)
        inv_scale = 0.0 if scaler is None else 1.0 / float(scaler.loss_scale)
        loss_scale = 1.0 if scaler is None else float(scaler.loss_scale)
        poison = (float("nan")
                  if _finj.ENABLED and _finj.should_fire("grad.nan")
                  else 1.0)
        rng = _random._next_key()

        profiler.record_dispatch("captured_step")
        if meta["coll_bytes"]:
            kvs_mod._count_collective(meta["coll_op"], meta["coll_bytes"])
        for spec_str, nbytes in meta.get("coll_specs", ()):
            kvs_mod._count_collective("spmd_grad_reduce", nbytes,
                                      spec=spec_str)
        if meta.get("embed_bytes"):
            # the hot-path currency of the sharded-embedding workload:
            # bytes the bucketed index/vector all-to-alls move per step
            kvs_mod._count_collective("embed_all_to_all",
                                      meta["embed_bytes"])
        if meta.get("moe_bytes"):
            # same currency for expert parallelism: bytes the MoE
            # dispatch/combine all-to-alls move per step (forward pair,
            # shard/moe.py a2a_bytes_per_step convention)
            kvs_mod._count_collective("moe_all_to_all",
                                      meta["moe_bytes"])
        batch_vals = [b._data for b in batch_nd]
        diff_vals = [self._mesh_resident("d", i, p.data()._data)
                     for i, p in diff]
        nondiff_vals = [self._mesh_resident("n", j, p._data._data)
                        for j, p in enumerate(meta["nondiff"])]
        state_vals = [tuple(s._data for s in sv) for sv in state_nds]
        sh = meta.get("shardings")
        if sh is not None:
            from . import prefetch as _prefetch_mod
            # Batch placement: a device-prefetched batch already carries
            # the step's exact NamedSharding — use it as-is (zero-copy,
            # no critical-path H2D). Anything else pays a synchronous
            # per-step placement here (counted, so check_dispatch can
            # assert zero with the prefetcher active); a batch that is
            # device-COMMITTED but in a different layout additionally
            # records cachedop_fallbacks{reason=resharded_input} — the
            # producer staged it, just not where this step runs.
            staged = []
            for v, tgt in zip(batch_vals, sh[0]):
                if getattr(v, "sharding", None) == tgt:
                    staged.append(v)
                    continue
                if getattr(v, "committed", False):
                    _fallback("resharded_input")
                _prefetch_mod.record_sync_h2d(
                    int(v.size) * jnp.dtype(v.dtype).itemsize)
                staged.append(jax.device_put(v, tgt))
            batch_vals = staged
            # params/state/rng: no-ops once mesh-resident (first step only)
            diff_vals, nondiff_vals, state_vals, rng = jax.device_put(
                (diff_vals, nondiff_vals, state_vals, rng),
                (sh[1], sh[2], sh[3], sh[4]))
            # frozen nondiff params broadcast onto the mesh ONCE: remember
            # the mesh-resident copy so later steps skip the transfer
            for j, p in enumerate(meta["nondiff"]):
                self._mesh_cache[("n", j)] = (p._data._data,
                                              nondiff_vals[j])
        args = (batch_vals, diff_vals, nondiff_vals, state_vals,
                rng, lrs, wds, rescale, inv_scale, loss_scale, poison)
        if meta.get("tiered"):
            # consume the RowPrefetcher's staged cold-row plan for this
            # step (already committed replicated on the mesh — passing
            # it costs no placement here). The contract is strict
            # depth-1: exactly one planned batch per dispatch.
            tiered_vals = []
            for k, n_flat, n_blocks in meta["tiered"]:
                ts = diff[k][1]._tiered_state
                prod = ts.take_pending()
                if prod is None:
                    raise MXNetError(
                        f"tiered embedding {diff[k][1].name!r}: no "
                        f"staged row plan for this step — feed the "
                        f"training loop through prefetch.RowPrefetcher "
                        f"(raw index batches cannot address the hot "
                        f"cache)")
                if len(prod) != n_blocks or \
                        int(prod[0].shape[0]) != n_flat:
                    raise MXNetError(
                        f"tiered embedding {diff[k][1].name!r}: staged "
                        f"row plan shape ({len(prod)} blocks, "
                        f"{int(prod[0].shape[0])} ids) does not match "
                        f"the captured step ({n_blocks} blocks, "
                        f"{n_flat} ids) — the prefetcher must translate "
                        f"exactly this step's index batch, once")
                tiered_vals.extend(prod)
            args = args + (tuple(tiered_vals),)
        fresh = meta.pop("fresh", False)
        try:
            if fresh:
                # buffer donation is a no-op on CPU test meshes; jax warns
                # at compile time — suppress it HERE, not process-wide
                with warnings.catch_warnings():
                    warnings.filterwarnings(
                        "ignore", message="Some donated buffers were not")
                    loss_leaves, aux_vals, new_ws, new_ss, out_gs, flag = \
                        jfn(*args)
            else:
                loss_leaves, aux_vals, new_ws, new_ss, out_gs, flag = \
                    jfn(*args)
        except Exception as e:
            # no update ran: un-bump the optimistic update counts so lr
            # schedules stay aligned with what was actually applied
            num_update, counts = snapshot
            opt.num_update = num_update
            for i, c in counts.items():
                if c is None:
                    opt._index_update_count.pop(i, None)
                else:
                    opt._index_update_count[i] = c
            # donation hazard: if the program EXECUTED far enough to
            # consume its donated inputs before failing, the param/state
            # buffers are gone — falling back would read deleted arrays
            # and silently train garbage. Only a failure that left every
            # donated buffer alive (trace/compile-stage errors) may take
            # the transparent imperative fallback.
            donated_dead = any(
                getattr(a, "is_deleted", lambda: False)()
                for group in (diff_vals, state_vals)
                for leaf in group
                for a in (leaf if isinstance(leaf, tuple) else (leaf,)))
            if donated_dead:
                raise MXNetError(
                    "captured step failed AFTER its donated parameter/"
                    "state buffers were consumed — model state is lost; "
                    "restore from a checkpoint (see docs/PERFORMANCE.md "
                    f"donation rules). Cause: {type(e).__name__}: {e}"
                ) from e
            if fresh and not isinstance(e, _CaptureUnsupported):
                # first call = trace/compile of the backward+update stages
                # (the forward-only prepass cannot see those): treat like
                # any other capture failure — transparent fallback
                raise _CaptureUnsupported(
                    f"compile_error:{type(e).__name__}") from e
            raise

        # Interop rule for mesh captures: anything eager code may consume
        # (params, aux, replicated grads, the loss) is rebound to a ZERO-
        # COPY device-0 shard view of the replicated mesh output, so
        # eval/monitoring/hybridized forwards keep working on one device;
        # the mesh-resident array itself is kept in _mesh_cache so the
        # next captured step pays no re-broadcast. Row-sharded outputs
        # (optimizer state, sharded-update grads) stay mesh-resident —
        # their next-step in_specs match exactly and .asnumpy()/save see
        # the full logical value.
        if sh is not None and meta.get("plan"):
            # rule-sharded layout: params/grads/aux that a rule SHARDS
            # stay mesh-resident (the global array is the logical value
            # and per-device memory stays at the shard size); replicated
            # ones collapse to the device-0 view like the 1-D mesh path
            for (i, p), w in zip(diff, new_ws):
                v = _logical_view(w)
                p.data()._rebind(v)
                self._mesh_cache[("d", i)] = (v, w)
            for (_, p), g in zip(diff, out_gs):
                if isinstance(g, tuple):
                    # sparse fast path: the table's gradient exists ONLY
                    # as (unique_ids, touched_rows) — p.grad() keeps its
                    # previous (stale) buffer; consumers of sparse grads
                    # read this pair (docs/PERFORMANCE.md "Sharded
                    # embeddings")
                    p._sparse_grad = (NDArray(_dev0_view(g[0])),
                                      NDArray(_dev0_view(g[1])))
                    continue
                # a table that trained sparse EARLIER but dense now
                # (demotion, plan/optimizer change) must not leave a
                # stale (ids, rows) pair for consumers to read
                if getattr(p, "_sparse_grad", None) is not None:
                    p._sparse_grad = None
                p._grad._rebind(_logical_view(g))
            for p, v, j in zip(meta["aux"], aux_vals, meta["aux_pos"]):
                view = _logical_view(v)
                p._data._rebind(view)
                if j is not None:
                    self._mesh_cache[("n", j)] = (view, v)
            loss_leaves = [_dev0_view(v) for v in loss_leaves]
        elif sh is not None:
            for (i, p), w in zip(diff, new_ws):
                v = _dev0_view(w)
                p.data()._rebind(v)
                self._mesh_cache[("d", i)] = (v, w)
            for (_, p), g, sok in zip(diff, out_gs, meta["shard_ok"]):
                p._grad._rebind(g if sok else _dev0_view(g))
            for p, v, j in zip(meta["aux"], aux_vals, meta["aux_pos"]):
                view = _dev0_view(v)
                p._data._rebind(view)
                if j is not None:
                    self._mesh_cache[("n", j)] = (view, v)
            loss_leaves = [_dev0_view(v) for v in loss_leaves]
        else:
            for (_, p), w in zip(diff, new_ws):
                p.data()._rebind(w)
            for (_, p), g in zip(diff, out_gs):
                p._grad._rebind(g)
            for p, v in zip(meta["aux"], aux_vals):
                p._data._rebind(v)
        for sv_nd, sv_new in zip(state_nds, new_ss):
            for s_nd, s_val in zip(sv_nd, sv_new):
                s_nd._rebind(s_val)

        # step k is dispatched and every NDArray handle points at its
        # post-step buffer: wake the RowPrefetcher so batch k+1's row
        # plan resolves overlapped with this step's device compute (its
        # writeback np.asarray blocks until the compute lands — the
        # data-flow barrier)
        for k, _n, _b in meta.get("tiered") or ():
            diff[k][1]._tiered_state.notify_step()

        applied = True
        if meta["guard"]:
            overflow = bool(flag)   # ONE host sync — the imperative
            applied = not overflow  # nonfinite guard pays the same
            if scaler is not None:
                scaler.update_scale(overflow)
        if applied:
            tr._note_applied()
        else:
            num_update, counts = snapshot
            opt.num_update = num_update
            for i, c in counts.items():
                if c is None:
                    opt._index_update_count.pop(i, None)
                else:
                    opt._index_update_count[i] = c
            tr._note_skip("AMP overflow" if scale_mode == "amp"
                          else "nonfinite gradients")
        tr._tick_step()

        out_nd = [NDArray(v) for v in loss_leaves]
        return jax.tree_util.tree_unflatten(meta["treedef"], out_nd)
