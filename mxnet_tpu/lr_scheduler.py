"""Learning-rate schedulers (reference: python/mxnet/lr_scheduler.py)."""
from __future__ import annotations

import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler", "WarmUpScheduler"]


class LRScheduler:
    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0.0,
                 warmup_mode="linear"):
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update):
        if self.warmup_mode == "linear":
            inc = (self.warmup_final_lr - self.warmup_begin_lr) \
                * num_update / max(self.warmup_steps, 1)
            return self.warmup_begin_lr + inc
        return self.warmup_final_lr * (num_update / max(self.warmup_steps, 1)) ** 2

    def __call__(self, num_update):
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """lr *= factor every `step` updates, floored at stop_factor_lr."""

    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8, base_lr=0.01,
                 **kwargs):
        super().__init__(base_lr, **kwargs)
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        n = (num_update - self.warmup_steps) // self.step
        return max(self.base_lr * (self.factor ** n), self.stop_factor_lr)


class MultiFactorScheduler(LRScheduler):
    """lr *= factor at each step milestone."""

    def __init__(self, step, factor=1.0, base_lr=0.01, **kwargs):
        super().__init__(base_lr, **kwargs)
        self.steps = sorted(step)
        self.factor = factor

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        lr = self.base_lr
        for s in self.steps:
            if num_update >= s:
                lr *= self.factor
        return lr


class PolyScheduler(LRScheduler):
    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0.0, **kwargs):
        super().__init__(base_lr, **kwargs)
        self.max_update = max_update
        self.power = pwr
        self.final_lr = final_lr

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        span = max(self.max_update - self.warmup_steps, 1)
        t = min(num_update - self.warmup_steps, span) / span
        return self.final_lr + (self.base_lr - self.final_lr) * (1 - t) ** self.power


class CosineScheduler(LRScheduler):
    def __init__(self, max_update, base_lr=0.01, final_lr=0.0, **kwargs):
        super().__init__(base_lr, **kwargs)
        self.max_update = max_update
        self.final_lr = final_lr

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        span = max(self.max_update - self.warmup_steps, 1)
        t = min(num_update - self.warmup_steps, span) / span
        return self.final_lr + (self.base_lr - self.final_lr) \
            * (1 + math.cos(math.pi * t)) / 2


class WarmUpScheduler(LRScheduler):
    """Linear warmup wrapped around any base scheduler (reference:
    gluonnlp-style WarmUpScheduler; upstream schedulers take
    warmup_steps inline — this is the composable form): lr ramps
    0 -> base over `warmup_steps`, then delegates."""

    def __init__(self, base_scheduler, warmup_steps=0,
                 warmup_begin_lr=0.0, warmup_mode="linear", **kwargs):
        if getattr(base_scheduler, "warmup_steps", 0):
            raise ValueError(
                "WarmUpScheduler: base scheduler already has "
                "warmup_steps — composing two warmups would dip the lr "
                "right after the outer ramp ends")
        base_lr = getattr(base_scheduler, "base_lr", 0.01)
        super().__init__(base_lr=base_lr, warmup_steps=int(warmup_steps),
                         warmup_begin_lr=warmup_begin_lr,
                         warmup_mode=warmup_mode)
        self.base_scheduler = base_scheduler

    def __call__(self, num_update):
        if self.warmup_steps and num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)   # base-class ramp
        return self.base_scheduler(num_update - self.warmup_steps)
