"""Symbol attribute scoping (reference: python/mxnet/attribute.py).

`with mx.AttrScope(ctx_group="dev1"):` attaches the given attributes to
every Symbol created inside the scope (the reference uses this for context
groups and custom graph annotations; here attrs also ride `tojson`, so
sharding hints can be round-tripped with the graph).
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["AttrScope"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = [AttrScope()]
    return _tls.stack


class AttrScope:
    """Scoped user attributes applied to symbols created within."""

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise MXNetError("AttrScope values must be strings "
                                 "(reference contract)")
        self._attr = kwargs

    @classmethod
    def current(cls):
        return _stack()[-1]

    def get(self, attr=None):
        """Merge scope attrs with (and prefer) the explicitly-given ones."""
        if not self._attr:
            return attr or {}
        merged = dict(self._attr)
        merged.update(attr or {})
        return merged

    def __enter__(self):
        parent = _stack()[-1]
        merged = dict(parent._attr)
        merged.update(self._attr)
        pushed = AttrScope()
        pushed._attr = merged
        _stack().append(pushed)
        return self

    def __exit__(self, *exc):
        _stack().pop()


def current():
    """Module-level accessor for the active AttrScope (reference:
    attribute.py current())."""
    return AttrScope.current()
