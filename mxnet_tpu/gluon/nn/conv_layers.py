"""Gluon convolution & pooling layers (reference: gluon/nn/conv_layers.py).

Layout note: the reference default is channel-first (NCHW). TPU MXU prefers
channel-last (NHWC) — every layer takes `layout=` and the model zoo exposes a
channel-last fast path; XLA handles either, but NHWC avoids relayouts.
Weight layout follows the data layout: (O, I/g, *k) for NC*, (O, *k, I/g)
for N*C.
"""
from __future__ import annotations

import numpy as np

from ...ndarray.ndarray import _apply
from ...ops import nn_ops as K
from ..block import HybridBlock, is_symbolic

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D", "ZeroPad2D"]


def _tuple(x, n):
    return (x,) * n if isinstance(x, int) else tuple(x)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 ndim=2, prefix=None, params=None):
        super().__init__(prefix, params)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = _tuple(kernel_size, ndim)
        self._strides = _tuple(strides, ndim)
        self._padding = _tuple(padding, ndim)
        self._dilation = _tuple(dilation, ndim)
        self._groups = groups
        self._layout = layout
        self._ndim = ndim
        self._activation = activation
        self._channel_first = layout.index("C") == 1
        with self.name_scope():
            wshape = self._weight_shape(in_channels)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer)
            else:
                self.bias = None

    def _weight_shape(self, in_channels):
        ig = in_channels // self._groups if in_channels else 0
        if self._channel_first:
            return (self._channels, ig) + self._kernel
        return (self._channels,) + self._kernel + (ig,)

    def _infer_shapes(self, x):
        c_axis = self._layout.index("C")
        in_c = x.shape[c_axis]
        self.weight._finish_deferred_init(self._weight_shape(in_c))
        self._in_channels = in_c

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.Convolution(x, weight, bias, kernel=self._kernel,
                            stride=self._strides, pad=self._padding,
                            dilate=self._dilation, num_filter=self._channels,
                            num_group=self._groups, no_bias=bias is None,
                            layout=self._layout)
        if self._activation:
            out = F.Activation(out, act_type=self._activation)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._in_channels} -> "
                f"{self._channels}, kernel_size={self._kernel}, "
                f"stride={self._strides}, padding={self._padding})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, ndim=1, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, ndim=2, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCDHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, ndim=3, **kwargs)


class _ConvTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides, padding, output_padding,
                 dilation, groups, layout, ndim, **kwargs):
        self._output_padding = _tuple(output_padding, ndim)
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, ndim=ndim, **kwargs)

    def _weight_shape(self, in_channels):
        # transposed conv weight: (I, O/g, *k)
        return (in_channels, self._channels // self._groups) + self._kernel \
            if in_channels else (0, self._channels // self._groups) + self._kernel

    def _infer_shapes(self, x):
        c_axis = self._layout.index("C")
        in_c = x.shape[c_axis]
        self.weight._finish_deferred_init(self._weight_shape(in_c))
        self._in_channels = in_c

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.Deconvolution(x, weight, bias, kernel=self._kernel,
                              stride=self._strides, pad=self._padding,
                              adj=self._output_padding,
                              num_filter=self._channels, no_bias=bias is None,
                              layout=self._layout)
        if self._activation:
            out = F.Activation(out, act_type=self._activation)
        return out


class Conv1DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 **kwargs):
        super().__init__(channels, kernel_size, strides, padding,
                         output_padding, dilation, groups, layout, 1, **kwargs)


class Conv2DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCHW",
                 **kwargs):
        super().__init__(channels, kernel_size, strides, padding,
                         output_padding, dilation, groups, layout, 2, **kwargs)


class Conv3DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCDHW",
                 **kwargs):
        super().__init__(channels, kernel_size, strides, padding,
                         output_padding, dilation, groups, layout, 3, **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, pool_type, ndim,
                 layout=None, ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(**kwargs)
        self._kernel = _tuple(pool_size, ndim)
        self._strides = _tuple(strides if strides is not None else pool_size,
                               ndim)
        self._padding = _tuple(padding, ndim)
        self._pool_type = pool_type
        self._layout = layout or {1: "NCW", 2: "NCHW", 3: "NCDHW"}[ndim]
        self._count_include_pad = count_include_pad

    def hybrid_forward(self, F, x):
        if is_symbolic(x):
            return F.Pooling(x, kernel=self._kernel,
                             pool_type=self._pool_type,
                             stride=self._strides, pad=self._padding,
                             layout=self._layout,
                             count_include_pad=self._count_include_pad)
        return _apply(lambda a, _k=self._kernel, _pt=self._pool_type,
                      _s=self._strides, _p=self._padding, _l=self._layout,
                      _c=self._count_include_pad:
                      K.pooling(a, _k, _pt, _s, _p, _l, _c), [x])

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kernel}, "
                f"stride={self._strides}, padding={self._padding})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, "max", 1, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, "max", 2, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, "max", 3, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, "avg", 1, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, "avg", 2, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, **kwargs):
        super().__init__(pool_size, strides, padding, "avg", 3, **kwargs)


class _GlobalPool(HybridBlock):
    def __init__(self, pool_type, ndim, layout=None, keep_dims=True, **kwargs):
        super().__init__(**kwargs)
        self._pool_type = pool_type
        self._layout = layout or {1: "NCW", 2: "NCHW", 3: "NCDHW"}[ndim]
        self._keep = keep_dims

    def hybrid_forward(self, F, x):
        if is_symbolic(x):
            out = F.Pooling(x, global_pool=True,
                            pool_type=self._pool_type, layout=self._layout)
            return out if self._keep else F.flatten(out)
        out = _apply(lambda a, _pt=self._pool_type, _l=self._layout,
                     _keep=self._keep:
                     K.global_pooling(a, _pt, _l, keepdims=_keep), [x])
        return out


class GlobalMaxPool1D(_GlobalPool):
    def __init__(self, **kwargs):
        super().__init__("max", 1, **kwargs)


class GlobalMaxPool2D(_GlobalPool):
    def __init__(self, **kwargs):
        super().__init__("max", 2, **kwargs)


class GlobalMaxPool3D(_GlobalPool):
    def __init__(self, **kwargs):
        super().__init__("max", 3, **kwargs)


class GlobalAvgPool1D(_GlobalPool):
    def __init__(self, **kwargs):
        super().__init__("avg", 1, **kwargs)


class GlobalAvgPool2D(_GlobalPool):
    def __init__(self, **kwargs):
        super().__init__("avg", 2, **kwargs)


class GlobalAvgPool3D(_GlobalPool):
    def __init__(self, **kwargs):
        super().__init__("avg", 3, **kwargs)


class ZeroPad2D(HybridBlock):
    """Zero padding on H/W of NCHW input (reference: nn.ZeroPad2D).
    padding: int or (pad_h_before, pad_h_after, pad_w_before,
    pad_w_after) in the upstream 4-tuple convention."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        self._padding = (padding,) * 4 if isinstance(padding, int) \
            else tuple(padding)

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        ph0, ph1, pw0, pw1 = self._padding
        pairs = ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1))
        return _apply(lambda a, _p=pairs: jnp.pad(a, _p), [x])


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        self._padding = padding if not isinstance(padding, int) \
            else (0, 0, 0, 0, padding, padding, padding, padding)

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        p = self._padding
        pairs = tuple((p[i], p[i + 1]) for i in range(0, len(p), 2))
        return _apply(lambda a, _p=pairs: jnp.pad(a, _p, mode="reflect"), [x])
