"""Serving engine (ISSUE 6): paged KV cache, ragged paged attention,
continuous batching, request API, fault/chaos behaviour.

The load-bearing guarantees pinned here:

  * the paged decode path is BITWISE-identical to the dense-cache
    `decode_step` on equal context width (shared decode core);
  * the KV page pool NEVER leaks: `in_use` returns to 0 after every
    request completes — including chaos (decode faults, exhausted
    retries) and page-exhaustion preemption;
  * the decode executable compiles once and never retraces across slot
    occupancy / page-table changes (also gated in check_dispatch).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.fault import injection as finj
from mxnet_tpu.observability import registry
from mxnet_tpu.serve import (PageAllocError, PagePool, ServeError,
                             ServeOverloaded)
from mxnet_tpu.serve.kv_pages import NULL_PAGE


def _tiny_model(vocab=50, units=32, layers=2, heads=4, max_length=32,
                seed=11):
    from mxnet_tpu.models.transformer import TransformerNMT
    mx.random.seed(seed)
    m = TransformerNMT(vocab, units=units, hidden=2 * units,
                       num_layers=layers, num_heads=heads,
                       max_length=max_length, dropout=0.0)
    m.initialize()
    return m


def _server(model=None, **kw):
    model = model if model is not None else _tiny_model()
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_src_len", 16)
    kw.setdefault("max_new_tokens", 12)
    kw.setdefault("engine_driven", False)
    return mx.serve.Server(model, **kw)


@pytest.fixture(autouse=True)
def _clear_faults():
    finj.clear()
    yield
    finj.clear()


# ---------------------------------------------------------------- pool
def test_page_pool_alloc_free_accounting():
    pool = PagePool(num_pages=8, page_size=4)
    assert pool.capacity == 7 and pool.available() == 7
    a = pool.alloc(3)
    assert len(a) == 3 and NULL_PAGE not in a
    assert pool.in_use() == 3 and pool.available() == 4
    b = pool.alloc(4)
    assert pool.available() == 0
    pool.free(a)
    assert pool.in_use() == 4 and pool.available() == 3
    pool.free(b)
    assert pool.in_use() == 0 and pool.available() == 7
    assert pool.pages_for(1) == 1 and pool.pages_for(4) == 1
    assert pool.pages_for(5) == 2 and pool.pages_for(0) == 1


def test_page_pool_exhaustion_is_atomic_and_counted():
    reg = registry()
    fail0 = reg.counter("kv_page_alloc_failures").value
    pool = PagePool(num_pages=4, page_size=2)
    pool.alloc(2)
    with pytest.raises(PageAllocError):
        pool.alloc(2)       # only 1 free: all-or-nothing
    assert pool.available() == 1    # nothing was granted
    assert reg.counter("kv_page_alloc_failures").value == fail0 + 1


def test_page_pool_free_errors():
    pool = PagePool(num_pages=4, page_size=2)
    pages = pool.alloc(1)
    pool.free(pages)
    with pytest.raises(MXNetError):
        pool.free(pages)            # double free
    with pytest.raises(MXNetError):
        pool.free([NULL_PAGE])      # reserved null page


def test_page_pool_defrag_mapping():
    pool = PagePool(num_pages=8, page_size=2)
    a = pool.alloc(5)               # pages 1..5
    pool.free([a[0], a[2]])         # live: {2, 4, 5} (alloc order 1..5)
    live = sorted({1, 2, 3, 4, 5} - {a[0], a[2]})
    mapping = pool.defrag()
    # live pages renumbered to 1..3; only movers appear in the mapping
    assert set(mapping.keys()) <= set(live)
    assert sorted(mapping.values()) == sorted(
        n for n, o in zip(range(1, 4), live) if n != o)
    assert pool.in_use() == 3
    assert pool.available() == 4
    # post-defrag allocations hand out ids above the compacted range
    assert all(p > 3 for p in pool.alloc(2))


# ----------------------------------------------- ragged paged attention
def _paged_fixture(seed=0, S=3, H=2, dh=8, P=9, psize=8, npages=2):
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(S, H, dh).astype(np.float32))
    kp = jnp.asarray(rng.randn(P, psize, H, dh).astype(np.float32))
    vp = jnp.asarray(rng.randn(P, psize, H, dh).astype(np.float32))
    pt = jnp.asarray(np.array([[1, 2], [3, 0], [4, 5]], np.int32))
    lens = jnp.asarray(np.array([12, 5, 16], np.int32))
    return q, kp, vp, pt, lens


def test_paged_attention_lax_matches_shared_math():
    """The gather fallback must be EXACTLY the shared single-query math
    over the gathered context (that is what buys decode-path parity)."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import (
        _paged_attention_lax, single_query_cached_attention)
    q, kp, vp, pt, lens = _paged_fixture()
    out = _paged_attention_lax(q, kp, vp, pt, lens)
    S, H, dh = q.shape
    L = pt.shape[1] * kp.shape[1]
    kc = kp[pt].reshape(S, L, H, dh).transpose(0, 2, 1, 3)
    vc = vp[pt].reshape(S, L, H, dh).transpose(0, 2, 1, 3)
    mask = (jnp.arange(L)[None, :] < lens[:, None])[:, None, None, :]
    ref = single_query_cached_attention(q[:, :, None, :], kc, vc,
                                        mask)[:, :, 0]
    assert np.array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("cfg", [{}, {"rpa_block_k": 8}],
                         ids=["default", "block_k=8"])
def test_paged_attention_kernel_interpret(monkeypatch, cfg):
    """The Pallas ragged-paged kernel numerics, pinned on CPU via
    interpret mode (same harness as the flash-kernel tests) — at the
    default block config AND under the ISSUE 20 `rpa_block_k` tuning
    knob (psize=16 fixture so a sub-page tile is legal): every
    reachable block config must reproduce the lax fallback."""
    monkeypatch.setenv("MXTPU_PALLAS_INTERPRET", "1")
    from mxnet_tpu.ops.pallas_kernels import (_paged_attention_lax,
                                              ragged_paged_attention)
    from mxnet_tpu.tune import overrides
    q, kp, vp, pt, lens = (_paged_fixture() if not cfg else
                           _paged_fixture(psize=16))
    if cfg:
        lens = lens * 2              # reach into the second K block
    with overrides.scope(cfg):
        out_k = ragged_paged_attention(q, kp, vp, pt, lens)
    ref = _paged_attention_lax(q, kp, vp, pt, lens)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


# --------------------------------------------------- decode-path parity
def test_paged_decode_bitwise_parity():
    """The serve paged decode and the dense-cache `decode_step` (the
    beam-search path) share one decode core + KV layout: on identical
    memory and equal context width, executing both cores op-by-op (the
    shared functions themselves, outside jit) produces BITWISE-equal
    logits at every step. The jitted production path is additionally
    checked to pick identical tokens (whole-program XLA fusion is allowed
    its ~1-ULP reassociation, but never a different argmax here)."""
    import jax.numpy as jnp
    from mxnet_tpu.models.transformer import (decode_step, decoder_weights,
                                              encoder_weights)
    from mxnet_tpu.serve.decode import DecodeRuntime

    model = _tiny_model()
    w = decoder_weights(model)
    ew = encoder_weights(model)
    rng = np.random.RandomState(3)
    src = rng.randint(4, 50, (9,)).astype(np.int32)

    psize, npages = 4, 4            # paged context width = dense Lmax
    lmax = psize * npages
    rt = DecodeRuntime(w, ew, slots=2, num_pages=2 * npages + 1,
                       page_size=psize, max_pages_per_slot=npages,
                       max_src_len=12)
    rt.prefill(0, src)

    # dense twin fed the EXACT memory the prefill executable wrote
    n_layers = len(w["layers"])
    h = w["num_heads"]
    dh = w["embed"].shape[1] // h
    mem_kv = [(rt.mem_k[li, 0:1], rt.mem_v[li, 0:1])
              for li in range(n_layers)]
    mem_vl = rt.mem_vl[0:1]
    caches = (jnp.zeros((n_layers, 1, h, lmax, dh), w["embed"].dtype),) * 2

    page_tables = np.full((2, npages), NULL_PAGE, np.int32)
    page_tables[0] = [1, 2, 3, 4]   # slot 0 owns 4 pages
    pt_dev = jnp.asarray(page_tables)
    active = jnp.asarray(np.array([1, 0], np.int32))
    lens = np.zeros((2,), np.int32)
    tok = np.array([2, 0], np.int32)        # BOS
    # the eager core keeps its own copy of the page state (the jitted
    # runtime call donates rt.k_pages/v_pages)
    kp, vp = jnp.array(rt.k_pages), jnp.array(rt.v_pages)

    for t in range(8):
        logits_d, caches = decode_step(
            w, caches, mem_kv, mem_vl, jnp.asarray(tok[:1]), t)
        # the shared core, executed eagerly: bitwise
        kp, vp, _, logits_e = rt._decode_program(
            kp, vp, pt_dev, jnp.asarray(lens), jnp.asarray(tok), active,
            rt.mem_k, rt.mem_v, rt.mem_vl)
        assert np.array_equal(np.asarray(logits_e)[0],
                              np.asarray(logits_d)[0]), f"step {t}"
        # the jitted production path: same token choice, logits ~1 ULP
        next_paged, logits_p = rt.decode(page_tables, lens, tok, active)
        np.testing.assert_allclose(np.asarray(logits_p)[0],
                                   np.asarray(logits_d)[0],
                                   rtol=2e-6, atol=2e-6)
        nxt = int(np.argmax(np.asarray(logits_d)[0]))
        assert int(next_paged[0]) == nxt
        tok = np.array([nxt, 0], np.int32)
        lens[0] += 1


def test_serve_greedy_matches_beam1_cached():
    """End to end: the server's greedy decode equals `beam_search_cached`
    with beam_size=1 (same shared decode core, full pipeline)."""
    from mxnet_tpu.models.transformer import beam_search_cached
    model = _tiny_model()
    rng = np.random.RandomState(0)
    src = rng.randint(4, 50, (8,)).astype(np.int32)
    srv = _server(model, max_new_tokens=11)
    try:
        got = srv.submit(src).result()
    finally:
        srv.close()
    tokens, _ = beam_search_cached(model, mx.nd.array(src.reshape(1, -1)),
                                   beam_size=1, max_length=12)
    beam = tokens.asnumpy()[0, 0].tolist()   # [BOS, tok, tok, ...]
    want = beam[1:1 + len(got)]
    eos_cut = want.index(3) + 1 if 3 in want else len(want)
    assert got == want[:eos_cut] or got == want


# ----------------------------------------------- continuous batching
def test_continuous_batching_admits_midflight_and_frees_pages():
    srv = _server(max_new_tokens=12)
    sched = srv.scheduler
    rng = np.random.RandomState(1)
    long1 = srv.submit(rng.randint(4, 50, (6,)), max_new_tokens=10)
    short = srv.submit(rng.randint(4, 50, (5,)), max_new_tokens=2)
    late = srv.submit(rng.randint(4, 50, (7,)), max_new_tokens=3)
    r = sched.step()
    assert r.admitted == 2          # both slots fill, `late` queues
    assert sched.active_count() == 2
    saw_midflight = False
    for _ in range(40):
        if not sched.pending_work():
            break
        sched.step()
        states = (long1.state, late.state)
        if states == ("running", "running"):
            saw_midflight = True    # late admitted while long1 in flight
    assert saw_midflight, "continuous batching never backfilled"
    assert len(short.result()) == 2
    assert len(long1.result()) == 10
    assert len(late.result()) == 3
    assert srv.pool.in_use() == 0
    srv.close()


def test_static_batching_needs_more_steps():
    """Same mixed-length workload: static batching (admit only into an
    empty batch) must take strictly more scheduler turns than continuous
    batching — the bench's speedup, in deterministic step counts."""
    def run(static):
        model = _tiny_model(seed=13)
        srv = _server(model, slots=2, max_new_tokens=12,
                      static_batching=static)
        rng = np.random.RandomState(5)
        for budget in (12, 2, 6, 3):
            srv.submit(rng.randint(4, 50, (6,)), max_new_tokens=budget)
        steps = 0
        while srv.scheduler.pending_work():
            srv.scheduler.step()
            steps += 1
            assert steps < 200
        assert srv.pool.in_use() == 0
        srv.close()
        return steps

    s_static = run(True)
    s_cont = run(False)
    assert s_cont < s_static, (s_cont, s_static)


def test_static_batching_fills_whole_batch_per_window():
    """static_batching admits into an EMPTY batch only, but fills ALL
    free slots in that one admission turn (regression: the window used
    to close after the first admission, degenerating to batch-size-1)."""
    model = _tiny_model(seed=23)
    srv = _server(model, slots=3, max_new_tokens=4, static_batching=True)
    rng = np.random.RandomState(21)
    for _ in range(4):
        srv.submit(rng.randint(4, 50, (5,)), max_new_tokens=4)
    r = srv.scheduler.step()
    assert r.admitted == 3          # whole batch, one window
    assert srv.scheduler.active_count() == 3
    # mid-flight: no admission until the batch drains
    r = srv.scheduler.step()
    assert r.admitted == 0
    srv.scheduler.run_until_idle()
    assert srv.pool.in_use() == 0
    srv.close()


def test_close_fails_pending_requests_instead_of_stranding():
    srv = _server()
    rng = np.random.RandomState(22)
    h = srv.submit(rng.randint(4, 50, (5,)))    # queued, never stepped
    srv.close()
    assert h.state == "failed" and h.done()
    with pytest.raises(ServeError):
        h.result(timeout=1)
    assert srv.pool.in_use() == 0


def test_backpressure_bounded_queue():
    reg = registry()
    rej0 = reg.counter("serve_requests", result="rejected").value
    srv = _server(max_queue=2)
    rng = np.random.RandomState(2)
    srv.submit(rng.randint(4, 50, (4,)))
    srv.submit(rng.randint(4, 50, (4,)))
    with pytest.raises(ServeOverloaded):
        srv.submit(rng.randint(4, 50, (4,)))
    assert reg.counter("serve_requests", result="rejected").value \
        == rej0 + 1
    srv.scheduler.run_until_idle()
    assert srv.pool.in_use() == 0
    srv.close()


def test_submit_validates_source_tokens():
    srv = _server()
    with pytest.raises(MXNetError):
        srv.submit([], max_new_tokens=4)            # empty source
    with pytest.raises(MXNetError):
        srv.submit(np.arange(4, 40, dtype=np.int32))  # > max_src_len
    srv.close()


def test_submit_rejects_request_pool_can_never_serve():
    """A token budget needing more pages than the WHOLE pool holds is
    rejected at submit time (it would deterministically exhaust the pool
    mid-decode and burn retries)."""
    model = _tiny_model(seed=27)
    srv = _server(model, slots=2, page_size=2, num_pages=3,  # 2 usable
                  max_new_tokens=6)
    with pytest.raises(MXNetError):
        srv.submit(np.arange(4, 9, dtype=np.int32), max_new_tokens=6)
    h = srv.submit(np.arange(4, 9, dtype=np.int32), max_new_tokens=4)
    assert len(h.result(timeout=30)) >= 1
    assert srv.pool.in_use() == 0
    srv.close()


def test_throughput_is_per_server():
    """serve_tokens is process-global; throughput() must count per
    scheduler instance (regression: a second — even concurrent — server
    double-counted the first one's tokens)."""
    model = _tiny_model(seed=28)
    a = _server(model, max_new_tokens=4)
    b = _server(model, max_new_tokens=4)    # concurrently alive
    a.submit(np.arange(4, 10, dtype=np.int32)).result(timeout=30)
    assert b.throughput() == 0.0            # a's tokens don't leak into b
    assert a.throughput() > 0
    b.submit(np.arange(4, 10, dtype=np.int32)).result(timeout=30)
    assert b.scheduler.tokens_generated == 4
    assert a.scheduler.tokens_generated == 4
    a.close()
    b.close()


def test_construction_validates_encoder_pos_table():
    """max_src_len beyond the ENCODER position table fails at
    construction, not with an opaque shape error on every prefill."""
    model = _tiny_model(seed=29, max_length=8)
    with pytest.raises(MXNetError):
        _server(model, max_src_len=16)


def test_streaming_yields_incrementally():
    srv = _server(max_new_tokens=6)
    rng = np.random.RandomState(4)
    toks = list(srv.stream(rng.randint(4, 50, (5,)), timeout=30))
    assert 1 <= len(toks) <= 6
    assert all(isinstance(t, int) for t in toks)
    assert srv.pool.in_use() == 0
    srv.close()


def test_engine_driven_server():
    """The decode loop as dependency-engine tasks: submits from the user
    thread, decoding on engine workers, clean drain + close."""
    from mxnet_tpu import engine
    srv = _server(engine_driven=True, max_new_tokens=6)
    rng = np.random.RandomState(6)
    hs = [srv.submit(rng.randint(4, 50, (n,))) for n in (5, 8, 3)]
    res = [h.result(timeout=60) for h in hs]
    assert all(1 <= len(r) <= 6 for r in res)
    assert srv.wait(timeout=30)
    assert srv.pool.in_use() == 0
    srv.close()
    assert not any("serve" in f["site"] for f in engine.failures())


def test_page_exhaustion_preempts_not_deadlocks():
    """Two long requests on a pool that cannot hold both: the loser is
    preempted (pages freed, requeued) instead of wedging the batch, and
    everything still completes with zero leaked pages."""
    reg = registry()
    pre0 = reg.counter("serve_page_preemptions").value
    model = _tiny_model(seed=17)
    srv = _server(model, slots=2, page_size=2, num_pages=4,  # 3 usable
                  max_new_tokens=6, max_retries=5)
    rng = np.random.RandomState(7)
    h1 = srv.submit(rng.randint(4, 50, (5,)), max_new_tokens=6)
    h2 = srv.submit(rng.randint(4, 50, (6,)), max_new_tokens=6)
    srv.scheduler.run_until_idle(max_steps=500)
    assert len(h1.result()) >= 1 and len(h2.result()) >= 1
    assert reg.counter("serve_page_preemptions").value > pre0
    # preemption is queueing, not a fault: the retry budget is untouched
    assert h1.preemptions + h2.preemptions >= 1
    assert h1.retries == 0 and h2.retries == 0
    assert srv.pool.in_use() == 0
    srv.close()


def test_defrag_midflight_keeps_decoding_correctly():
    """Pool compaction between steps (device remap + table remap) must
    not change what a request generates."""
    def run(with_defrag):
        model = _tiny_model(seed=19)
        srv = _server(model, slots=2, page_size=2, max_new_tokens=8)
        rng = np.random.RandomState(8)
        h1 = srv.submit(rng.randint(4, 50, (6,)), max_new_tokens=8)
        h2 = srv.submit(rng.randint(4, 50, (4,)), max_new_tokens=2)
        sched = srv.scheduler
        for i in range(40):
            if not sched.pending_work():
                break
            sched.step()
            if with_defrag and i == 3:
                # h2 finished -> holes in the pool -> compaction moves
                # h1's live pages mid-request
                sched.defrag()
        out = (h1.result(), h2.result())
        assert srv.pool.in_use() == 0
        srv.close()
        return out

    assert run(True) == run(False)


# ------------------------------------------------------------- chaos
def test_chaos_decode_fault_retries_without_leaking():
    """A fault mid-decode kills the in-flight batch: requests are retried
    from scratch and complete; page accounting returns to baseline."""
    reg = registry()
    ret0 = reg.counter("serve_decode_retries").value
    srv = _server(max_new_tokens=6, max_retries=2)
    rng = np.random.RandomState(9)
    finj.inject("serve.decode", at=[2])      # second decode turn dies
    h1 = srv.submit(rng.randint(4, 50, (5,)))
    h2 = srv.submit(rng.randint(4, 50, (7,)))
    srv.scheduler.run_until_idle(max_steps=500)
    assert finj.fires("serve.decode") == 1
    assert len(h1.result()) >= 1 and len(h2.result()) >= 1
    assert h1.retries + h2.retries >= 1
    assert reg.counter("serve_decode_retries").value == ret0 + 1
    assert srv.pool.in_use() == 0
    # the stream restarted with the retry: no pre-fault token prefix
    # duplicated ahead of the regenerated sequence
    assert list(h1.stream(timeout=1)) == h1.result()
    assert list(h2.stream(timeout=1)) == h2.result()
    srv.close()


def test_requeue_rearms_stream_and_ttft():
    """A retried request restarts its stream (undelivered chunks of the
    aborted attempt dropped) and re-arms TTFT measurement."""
    srv = _server(max_new_tokens=6, max_retries=2)
    rng = np.random.RandomState(24)
    finj.inject("serve.decode", at=[2])      # die after one emitted token
    h = srv.submit(rng.randint(4, 50, (5,)))
    sched = srv.scheduler
    sched.step()                             # admit + first token
    assert len(h.tokens) == 1 and h.t_first_token is not None
    sched.step()                             # fault -> requeue
    assert h.state == "queued" and h.retries == 1
    assert h.t_first_token is None           # TTFT re-arms
    assert not h._chunks                     # aborted chunks dropped
    sched.run_until_idle(max_steps=200)
    assert list(h.stream(timeout=1)) == h.result()
    assert h.ttft is not None and h.ttft <= h.latency
    assert srv.pool.in_use() == 0
    srv.close()


def test_chaos_decode_fault_exhausted_retries_fails_cleanly():
    srv = _server(max_new_tokens=6, max_retries=1)
    rng = np.random.RandomState(10)
    finj.inject("serve.decode", prob=1.0)    # every decode turn dies
    h = srv.submit(rng.randint(4, 50, (5,)))
    srv.scheduler.run_until_idle(max_steps=100)
    assert h.state == "failed"
    with pytest.raises(ServeError):
        h.result(timeout=1)
    assert srv.pool.in_use() == 0            # failed != leaked
    srv.close()


def test_prefill_failure_fails_only_that_request():
    """An ordinary prefill error (donated buffers still alive — the CPU
    case) fails the admitted request only; in-flight traffic continues."""
    srv = _server(max_new_tokens=4)
    rng = np.random.RandomState(25)
    ok1 = srv.submit(rng.randint(4, 50, (5,)))
    srv.scheduler.step()                     # ok1 admitted + decoding
    orig = srv.runtime.prefill
    calls = {"n": 0}

    def flaky(slot, src, src_len=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient prefill failure")
        return orig(slot, src, src_len)

    srv.runtime.prefill = flaky
    bad = srv.submit(rng.randint(4, 50, (4,)))
    ok2 = srv.submit(rng.randint(4, 50, (6,)))
    srv.scheduler.run_until_idle(max_steps=200)
    assert bad.state == "failed"
    assert len(ok1.result()) >= 1 and len(ok2.result()) >= 1
    assert srv.pool.in_use() == 0
    srv.close()


def test_prefill_memory_loss_restarts_inflight_requests():
    """A prefill failure that consumed the donated memory buffers
    (`MemoryStateLost`) restarts EVERY in-flight request — re-admission
    re-prefills each slot — with zero leaked pages."""
    from mxnet_tpu.serve.decode import MemoryStateLost
    srv = _server(max_new_tokens=4, max_retries=2)
    rng = np.random.RandomState(26)
    inflight = srv.submit(rng.randint(4, 50, (5,)))
    srv.scheduler.step()                     # admitted + one token
    assert inflight.state == "running"
    orig = srv.runtime.prefill
    calls = {"n": 0}

    def lossy(slot, src, src_len=None):
        calls["n"] += 1
        if calls["n"] == 1:
            srv.runtime.reset_mem()          # what the real path does
            raise MemoryStateLost("prefill consumed donated buffers")
        return orig(slot, src, src_len)

    srv.runtime.prefill = lossy
    bad = srv.submit(rng.randint(4, 50, (4,)))
    srv.scheduler.run_until_idle(max_steps=200)
    assert bad.state == "failed"
    # the in-flight request was restarted from scratch and completed
    assert inflight.retries >= 1
    assert len(inflight.result()) >= 1
    assert srv.pool.in_use() == 0
    srv.close()


def test_chaos_admit_fault_rejects_one_request():
    srv = _server()
    rng = np.random.RandomState(12)
    finj.inject("serve.admit", at=[1])
    with pytest.raises(ServeError):
        srv.submit(rng.randint(4, 50, (4,)))
    h = srv.submit(rng.randint(4, 50, (4,)))  # next one sails through
    assert len(h.result()) >= 1
    assert srv.pool.in_use() == 0
    srv.close()


# ------------------------------------------------------------ metrics
def test_serve_metrics_and_percentiles():
    reg = registry()
    ttft = reg.histogram("serve_ttft_seconds")
    lat = reg.histogram("serve_request_seconds")
    t0, l0 = ttft.count, lat.count
    srv = _server(max_new_tokens=4)
    rng = np.random.RandomState(14)
    hs = [srv.submit(rng.randint(4, 50, (5,))) for _ in range(3)]
    for h in hs:
        h.result()
    srv.close()
    assert ttft.count == t0 + 3 and lat.count == l0 + 3
    snap = lat.snapshot()
    # the quantile-snapshot satellite: p50/p95/p99 all present + ordered
    assert snap["count"] >= 3
    assert snap["p50"] <= snap["p95"] <= snap["p99"]
    qs = lat.quantiles((0.5, 0.95, 0.99))
    assert qs[0.5] == snap["p50"] and qs[0.99] == snap["p99"]
    tps = srv.throughput()
    assert tps > 0
    assert reg.gauge("serve_tokens_per_s").snapshot() == tps


def test_warm_server_zero_recompiles_against_compile_counters():
    """ISSUE 11 satellite: the existing retrace pin (decode compiles
    once, ever) restated against the compile observatory — a WARM server
    performs ZERO recompiles of either executable across varying slot
    occupancy, measured on `compiles{executable=serve_decode|serve_prefill}`,
    and both executables land compile telemetry in the metrics snapshot."""
    reg = registry()
    dec_c = reg.counter("compiles", executable="serve_decode")
    pre_c = reg.counter("compiles", executable="serve_prefill")
    srv = _server(slots=3, max_new_tokens=8)
    rng = np.random.RandomState(21)
    # warm: the first request compiles prefill + decode exactly once
    srv.submit(rng.randint(4, 50, (5,)), max_new_tokens=3).result()
    base_d, base_p = dec_c.value, pre_c.value
    assert srv.runtime.decode_traces == 1
    # mixed-length traffic so occupancy and page tables vary mid-flight
    hs = [srv.submit(rng.randint(4, 50, (n,)), max_new_tokens=t)
          for n, t in ((3, 8), (7, 2), (6, 5), (8, 4), (4, 7))]
    for h in hs:
        h.result()
    assert dec_c.value == base_d, "warm decode recompiled"
    assert pre_c.value == base_p, "warm prefill recompiled"
    assert srv.runtime.decode_traces == 1
    srv.close()
    # per-executable compile telemetry (prefill vs decode) is in the
    # snapshot next to the serve_* series
    snap = reg.snapshot()
    execs = {dict(s["labels"]).get("executable")
             for s in snap.get("compile_seconds", [])}
    assert {"serve_decode", "serve_prefill"} <= execs


def test_encode_memory_matches_eager_encoder_bitwise():
    """The prefill executable's pure encoder is bitwise-equal to the
    eager `model.encode` path (they share flash_attention and the
    layer math)."""
    import jax.numpy as jnp
    from mxnet_tpu.models.transformer import encode_memory, encoder_weights
    model = _tiny_model()
    rng = np.random.RandomState(15)
    src = rng.randint(4, 50, (2, 12)).astype(np.int32)
    svl = np.array([8, 12], np.int32)
    eager, _ = model.encode(mx.nd.array(src), mx.nd.array(svl))
    pure = encode_memory(encoder_weights(model), jnp.asarray(src),
                         jnp.asarray(svl))
    assert np.array_equal(eager.asnumpy(), np.asarray(pure))


# ------------------------------------------- per-request deadlines (ISSUE 7)
def test_deadline_expired_in_queue_evicted_cleanly():
    """A queued request whose deadline elapses before admission fails
    with ServeDeadlineExceeded — not a generic ServeError — pages stay
    at baseline and serve_deadline_expired counts it."""
    from mxnet_tpu.serve import ServeDeadlineExceeded
    reg = registry()
    base = reg.counter("serve_deadline_expired").value
    srv = _server(slots=1, max_new_tokens=8)
    rng = np.random.RandomState(21)
    # a long request occupies the only slot...
    long_h = srv.submit(rng.randint(4, 50, (5,)), max_new_tokens=8)
    srv.scheduler.step()                 # admit it
    # ...so this one waits in queue past its deadline
    doomed = srv.submit(rng.randint(4, 50, (4,)), max_new_tokens=4,
                        deadline_ms=1)
    import time
    time.sleep(0.02)
    srv.scheduler.step()                 # sweep fires
    assert doomed.done()
    with pytest.raises(ServeDeadlineExceeded):
        doomed.result()
    assert reg.counter("serve_deadline_expired").value == base + 1
    srv.scheduler.run_until_idle()
    assert len(long_h.result()) >= 1     # the slot holder is unaffected
    assert srv.pool.in_use() == 0
    srv.close()


def test_deadline_expired_mid_decode_frees_pages():
    """A RUNNING request past its deadline is evicted mid-decode: pages
    return to the pool, the stream ends with ServeDeadlineExceeded, and
    other in-flight requests keep decoding."""
    from mxnet_tpu.serve import ServeDeadlineExceeded
    srv = _server(slots=2, max_new_tokens=12)
    rng = np.random.RandomState(22)
    doomed = srv.submit(rng.randint(4, 50, (5,)), max_new_tokens=12,
                        deadline_ms=30)
    other = srv.submit(rng.randint(4, 50, (4,)), max_new_tokens=3)
    sched = srv.scheduler
    sched.step()                          # admit both, decode one token
    import time
    time.sleep(0.05)                      # doomed's deadline elapses
    sched.run_until_idle(max_steps=200)
    with pytest.raises(ServeDeadlineExceeded):
        doomed.result()
    assert doomed.state == "failed"
    assert len(other.result()) >= 1       # neighbour finished normally
    assert srv.pool.in_use() == 0         # evicted pages freed
    srv.close()


def test_no_deadline_requests_unaffected():
    """deadline_ms=None (default) keeps the old behaviour bit-for-bit."""
    srv = _server(max_new_tokens=4)
    rng = np.random.RandomState(23)
    h = srv.submit(rng.randint(4, 50, (5,)))
    assert len(h.result(timeout=60)) >= 1
    assert srv.pool.in_use() == 0
    srv.close()


def test_engine_loop_survives_injected_task_fault():
    """QoS hardening (ISSUE 7): an injected engine.task fault that kills
    a serve loop task must not wedge the server — the loop re-arms on a
    fresh var (serve_loop_restarts counts it) and every request still
    completes with zero leaked pages."""
    from mxnet_tpu import engine
    reg = registry()
    base_restarts = reg.counter("serve_loop_restarts").value
    srv = _server(engine_driven=True, max_new_tokens=6)
    rng = np.random.RandomState(24)
    # warm one request through so the executables are compiled and the
    # fault hits a steady-state loop task
    srv.submit(rng.randint(4, 50, (4,))).result(timeout=120)
    # drain BEFORE arming: the warm-up loop task may still be in flight
    # (result() returns on the last token, the task disarms later) and a
    # straggler task from an earlier test could otherwise absorb the
    # at=[1] fault — it must hit the loop task the next submit kicks
    engine.wait_for_all()
    finj.inject("engine.task", at=[1])    # the NEXT engine task dies
    hs = [srv.submit(rng.randint(4, 50, (n,))) for n in (5, 6, 3)]
    res = [h.result(timeout=120) for h in hs]
    finj.clear("engine.task")
    assert all(1 <= len(r) <= 6 for r in res)
    assert srv.wait(timeout=60)
    assert srv.pool.in_use() == 0
    srv.close()
    assert reg.counter("serve_loop_restarts").value > base_restarts
    # the fault is VISIBLE (sticky failure report), not swallowed
    assert any("FaultInjected" in f["error"] for f in engine.failures())
    engine.clear_failures()


def test_engine_loop_survives_high_class_queue_limits():
    """QoS hardening (ISSUE 7): a bounded HIGH-class queue that sheds or
    rejects a serve loop task must not leave the loop armed-but-taskless
    — shed tasks re-push, rejected kicks disarm so the next kick
    retries."""
    import threading
    import time
    from mxnet_tpu import engine
    from mxnet_tpu.serve.engine_bridge import EngineLoop

    class FakeSched:
        def __init__(self, work):
            self.work = work

        def step(self):
            if self.work:
                self.work -= 1
                return True
            return False

        def pending_work(self):
            return self.work > 0

    # shed: wedge every worker, queue the loop task, shed it with a
    # second high push — the loop must re-push itself and still drain
    sched = FakeSched(3)
    loop = EngineLoop(sched)
    gate = threading.Event()
    for _ in range(engine.num_workers()):
        engine.push(gate.wait)
    time.sleep(0.05)
    prev = engine.set_queue_limit(engine.PRIORITY_HIGH, 1, "shed_oldest")
    try:
        loop.kick()                              # queued loop task
        engine.push(lambda: None, priority=engine.PRIORITY_HIGH)  # sheds it
        gate.set()
        deadline = time.monotonic() + 10
        while sched.pending_work() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not sched.pending_work()          # shed loop task re-pushed
    finally:
        engine.set_queue_limit(engine.PRIORITY_HIGH, *prev)
        gate.set()
    loop.close()
    engine.wait_for_all()

    # reject: a kick into a full high queue disarms instead of wedging;
    # once the limit lifts, the next kick decodes again
    sched2 = FakeSched(2)
    loop2 = EngineLoop(sched2)
    gate2 = threading.Event()
    blocker = engine.push(gate2.wait, priority=engine.PRIORITY_HIGH)
    time.sleep(0.05)
    prev = engine.set_queue_limit(engine.PRIORITY_HIGH, 1, "reject")
    try:
        wedge = threading.Event()
        for _ in range(engine.num_workers()):
            engine.push(wedge.wait)
        time.sleep(0.05)
        # blocker running, workers wedged: one queued high task fills the
        # limit, so the loop's kick is rejected -> must disarm cleanly
        engine.push(lambda: None, priority=engine.PRIORITY_HIGH)
        loop2.kick()
        assert sched2.pending_work()             # nothing ran yet
        wedge.set()
        gate2.set()
    finally:
        engine.set_queue_limit(engine.PRIORITY_HIGH, *prev)
        gate2.set()
        wedge.set()
    engine.wait_for_all()
    loop2.kick()                                 # retried kick proceeds
    deadline = time.monotonic() + 10
    while sched2.pending_work() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not sched2.pending_work()
    loop2.close()
    assert blocker.done()


# ------------------------------------------- serving fast path (ISSUE 12)
def test_page_pool_free_is_atomic_regression():
    """A double-free mid-list must leave the pool UNTOUCHED: before the
    fix, the earlier pages of the list were already freed and counted
    when the error fired, corrupting the leak accounting the tier-1
    gates assert on."""
    reg = registry()
    pool = PagePool(num_pages=8, page_size=4)
    a = pool.alloc(3)
    pool.free([a[0]])
    frees0 = reg.counter("kv_page_frees").value
    with pytest.raises(MXNetError):
        pool.free([a[1], a[0], a[2]])    # a[0] already free, mid-list
    # NOTHING moved: a[1]/a[2] still live, free counter flat
    assert pool.in_use() == 2
    assert pool.ref_count(a[1]) == 1 and pool.ref_count(a[2]) == 1
    assert reg.counter("kv_page_frees").value == frees0
    # over-release via duplicates within ONE list is caught up front too
    with pytest.raises(MXNetError):
        pool.free([a[1], a[1]])
    assert pool.in_use() == 2
    pool.free([a[1], a[2]])
    assert pool.in_use() == 0


def test_page_pool_refcount_sharing():
    reg = registry()
    pool = PagePool(num_pages=8, page_size=4)
    pages = pool.alloc(2)
    pool.share(pages)                    # second owner
    assert pool.ref_count(pages[0]) == 2
    assert pool.total_refs() == 4
    assert reg.gauge("kv_page_refs").value == 4
    pool.free(pages)                     # first owner releases
    assert pool.in_use() == 2            # still live (one owner left)
    assert pool.available() == 5
    # duplicate releases within one list are legal up to the refcount
    pool.share([pages[0]])
    pool.free([pages[0], pages[0]])
    assert pool.ref_count(pages[0]) == 0
    pool.free([pages[1]])
    assert pool.in_use() == 0 and pool.total_refs() == 0
    with pytest.raises(MXNetError):
        pool.share([pages[0]])           # free page: nothing to share


def test_prefix_cache_radix_unit():
    from mxnet_tpu.serve.prefix_cache import PrefixCache, content_key
    pool = PagePool(num_pages=16, page_size=4)
    cache = PrefixCache(pool)
    k1 = content_key([7, 8, 9])
    k2 = content_key([7, 8])             # different source: no sharing
    seq = [2, 10, 11, 12, 13, 14, 15, 16, 17]    # [BOS] + 8 prompt
    pages = pool.alloc(2)
    assert cache.insert(k1, seq, pages) == 2
    assert pool.ref_count(pages[0]) == 2         # cache's own reference
    # full match, partial match, foreign-source and diverging lookups
    assert cache.lookup(k1, seq, 2) == pages
    assert cache.lookup(k1, seq, 1) == [pages[0]]
    assert cache.lookup(k2, seq, 2) == []
    div = list(seq)
    div[6] = 99                                   # diverges in chunk 2
    assert cache.lookup(k1, div, 2) == [pages[0]]
    # owner releases; cache keeps the pages alive at refcount 1
    pool.free(pages)
    assert pool.in_use() == 2
    # LRU eviction: only LEAF nodes with no in-flight adopters go, least
    # recently used first — and an adopted page is skipped
    pool.share([pages[1]])                        # simulate an adopter
    assert cache.evict(2) == 0                    # leaf pinned, parent has
    pool.free([pages[1]])                         # a child: nothing to do
    assert cache.evict(1) == 1                    # leaf (chunk 2) goes
    assert cache.lookup(k1, seq, 2) == [pages[0]]
    assert cache.evict(1) == 1                    # now the root chunk
    assert cache.pages_held() == 0 and pool.in_use() == 0
    # remap keeps the index coherent with a real defrag
    anchors = pool.alloc(2)                       # occupy the low ids
    p2 = pool.alloc(1)
    cache.insert(k1, seq, p2)
    pool.free(p2)                                 # cache is the only owner
    pool.free(anchors)                            # low ids free: p2 moves
    mapping = pool.defrag()
    cache.remap(mapping)
    new_id = mapping[p2[0]]
    assert cache.lookup(k1, seq, 1) == [new_id]
    assert cache.clear() == 1
    assert pool.in_use() == 0


def _drain(srv, *submits, max_steps=500):
    handles = [srv.submit(s, max_new_tokens=m, prompt_tokens=p)
               for s, m, p in submits]
    srv.scheduler.run_until_idle(max_steps=max_steps)
    return [h.result(timeout=60) for h in handles]


def test_prompted_greedy_bitwise_contract():
    """THE fast-path contract: for one (source, prompt, budget) request
    the committed token sequence is IDENTICAL across every serving
    configuration — prefix cache cold, warm, disabled; speculative k=2
    and k=3 — and page refcounts return to the cache-held baseline
    after every request, to zero after close()."""
    from mxnet_tpu import profiler
    model = _tiny_model(max_length=48)
    rng = np.random.RandomState(5)
    src = rng.randint(4, 50, (7,)).astype(np.int32)
    prompt = rng.randint(4, 50, (9,)).astype(np.int32)

    def run(srv):
        t0 = srv.scheduler.decode_turns
        out = _drain(srv, (src, 8, prompt))[0]
        return out, srv.scheduler.decode_turns - t0

    srv = _server(model, max_new_tokens=8, max_prompt_len=12,
                  num_pages=16)
    cold, cold_turns = run(srv)
    warm, warm_turns = run(srv)
    assert warm == cold
    assert warm_turns < cold_turns          # adopted pages skip prefill
    cache = srv.prefix_cache
    assert cache.hits == 1 and cache.misses == 1
    assert cache.tokens_saved == 8          # two full 4-token pages
    # drained: only the cache holds pages, each at refcount exactly 1
    assert srv.pool.in_use() == cache.pages_held() == 2
    assert srv.pool.total_refs() == 2
    srv.close()
    assert srv.pool.in_use() == 0 and srv.pool.total_refs() == 0

    srv = _server(model, max_new_tokens=8, max_prompt_len=12,
                  num_pages=16, prefix_cache=False)
    nocache, _ = run(srv)
    assert srv.prefix_cache is None
    srv.close()
    assert nocache == cold

    for k in (2, 3):
        srv = _server(model, max_new_tokens=8, max_prompt_len=12,
                      num_pages=16, speculative_k=k)
        spec, _ = run(srv)
        assert spec == cold, f"speculative k={k} changed greedy output"
        assert srv.runtime.verify_traces == 1
        srv.close()
        assert srv.pool.in_use() == 0


def test_speculative_accepts_and_reduces_turns():
    """On self-repetitive greedy output the n-gram proposer earns its
    keep: drafted tokens get accepted, a solo request finishes in fewer
    decode turns than tokens, and the acceptance histogram records the
    distribution profiler.dumps() surfaces."""
    reg = registry()
    hist0 = reg.histogram("serve_spec_accepted_tokens").count
    model = _tiny_model(max_length=48)
    rng = np.random.RandomState(5)
    src = rng.randint(4, 50, (7,)).astype(np.int32)
    srv = _server(model, max_new_tokens=12, max_prompt_len=12,
                  num_pages=16, speculative_k=3)
    out = _drain(srv, (src, 12, None))[0]
    sched = srv.scheduler
    assert sched.spec_accepted > 0
    assert sched.decode_turns < len(out)    # strictly fewer turns/token
    assert reg.histogram("serve_spec_accepted_tokens").count > hist0
    srv.close()


def test_prefix_eviction_under_pressure():
    """When the pool is dry, admission evicts LRU cache-only pages
    instead of failing or preempting — cached prefixes only cost
    capacity while it is spare."""
    reg = registry()
    ev0 = reg.counter("serve_prefix_evictions").value
    model = _tiny_model(max_length=48)
    rng = np.random.RandomState(6)
    src = rng.randint(4, 50, (5,)).astype(np.int32)
    pa = rng.randint(4, 50, (9,)).astype(np.int32)
    pb = rng.randint(4, 50, (9,)).astype(np.int32)
    # capacity 5: a request's working set is 4 pages (prompt 9 + 6 new),
    # so after A leaves its 2 cached pages behind, B's growth hits a dry
    # pool and must reclaim from the cache
    srv = _server(model, slots=1, max_new_tokens=6, max_prompt_len=12,
                  num_pages=6)
    a = _drain(srv, (src, 6, pa))[0]
    assert srv.prefix_cache.pages_held() == 2
    b = _drain(srv, (src, 6, pb))[0]
    assert len(a) >= 1 and len(b) >= 1
    assert reg.counter("serve_prefix_evictions").value > ev0
    # evicted pages left the cache index too — nothing dangling
    assert srv.pool.in_use() == srv.prefix_cache.pages_held()
    srv.close()
    assert srv.pool.in_use() == 0


def test_chaos_prefix_and_speculate_faults_degrade_identically():
    """Injected cache-lookup/insert and draft faults DEGRADE (cold path /
    unspeculated turn) with bitwise-identical request output, zero
    leaked pages and zero stuck refcounts."""
    model = _tiny_model(max_length=48)
    rng = np.random.RandomState(8)
    reqs = [(rng.randint(4, 50, (6,)).astype(np.int32),
             5, rng.randint(4, 50, (9,)).astype(np.int32))
            for _ in range(3)]
    reqs.append(reqs[0])                    # a warm repeat in the mix

    def run(faulty):
        srv = _server(model, slots=2, max_new_tokens=6, max_prompt_len=12,
                      num_pages=24, speculative_k=2)
        fired = 0
        if faulty:
            finj.inject("serve.prefix", prob=0.5, seed=13)
            finj.inject("serve.speculate", prob=0.5, seed=14)
        try:
            outs = _drain(srv, *reqs)
            if faulty:
                fired = (finj.fires("serve.prefix")
                         + finj.fires("serve.speculate"))
        finally:
            finj.clear()
        held = srv.prefix_cache.pages_held()
        assert srv.pool.in_use() == held    # requests fully released
        assert srv.pool.total_refs() == held
        srv.close()
        assert srv.pool.in_use() == 0
        return outs, fired

    clean, _ = run(faulty=False)
    chaos, fired = run(faulty=True)
    assert fired > 0
    assert chaos == clean


def test_spec_preemption_with_prompt_no_leak():
    """Page-pressure preemption under speculation + prompts: requests
    restart (re-adopting any cached prefix), complete, and the pool
    returns to the cache-held baseline."""
    reg = registry()
    pre0 = reg.counter("serve_page_preemptions").value
    model = _tiny_model(max_length=48)
    rng = np.random.RandomState(11)
    src = rng.randint(4, 50, (5,)).astype(np.int32)
    prompts = [rng.randint(4, 50, (6,)).astype(np.int32)
               for _ in range(2)]
    srv = _server(model, slots=2, max_new_tokens=8, max_prompt_len=8,
                  num_pages=7, speculative_k=2)   # capacity 6: contended
    outs = _drain(srv, (src, 8, prompts[0]), (src, 8, prompts[1]),
                  max_steps=2000)
    assert all(len(o) >= 1 for o in outs)
    assert reg.counter("serve_page_preemptions").value > pre0
    assert srv.pool.in_use() == srv.prefix_cache.pages_held()
    srv.close()
    assert srv.pool.in_use() == 0


def test_submit_prompt_validation():
    srv = _server(max_new_tokens=8, max_prompt_len=8)
    with pytest.raises(MXNetError):
        # prompt + max_new over the per-slot page budget
        srv.submit([5, 6, 7], max_new_tokens=8,
                   prompt_tokens=list(range(4, 20)))
    srv.close()


def test_paged_attention_multi_rowwise_matches_single():
    """The widened lax fallback runs the SAME shared math per query row:
    row i equals the single-query path over `lengths + i` visible keys
    to reduction-order tolerance (XLA batches the W-row contraction; the
    TOKEN-level identity the speculative commits rely on is pinned end
    to end by test_prompted_greedy_bitwise_contract)."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import (_paged_attention_lax,
                                              _paged_attention_lax_multi)
    q1, kp, vp, pt, lens = _paged_fixture()
    S, H, dh = q1.shape
    W = 3
    rng = np.random.RandomState(21)
    q = jnp.asarray(rng.randn(S, W, H, dh).astype(np.float32))
    out = _paged_attention_lax_multi(q, kp, vp, pt, lens)
    for i in range(W):
        ref = _paged_attention_lax(q[:, i], kp, vp, pt, lens + i)
        np.testing.assert_allclose(np.asarray(out[:, i]),
                                   np.asarray(ref),
                                   rtol=2e-6, atol=2e-6, err_msg=str(i))


@pytest.mark.parametrize("cfg", [{}, {"rpa_sublanes": 16},
                                 {"rpa_block_k": 8}],
                         ids=["default", "sublanes=16", "block_k=8"])
def test_paged_attention_multi_kernel_interpret(monkeypatch, cfg):
    """The widened Pallas kernel numerics, pinned on CPU via interpret
    mode against the lax fallback (same harness as the 1-wide test) —
    at the default config AND under the ISSUE 20 tuning knobs (padded
    query-sublane count, sub-page K tile)."""
    monkeypatch.setenv("MXTPU_PALLAS_INTERPRET", "1")
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import (_paged_attention_lax_multi,
                                              ragged_paged_attention)
    from mxnet_tpu.tune import overrides
    q1, kp, vp, pt, lens = (_paged_fixture() if "rpa_block_k" not in cfg
                            else _paged_fixture(psize=16))
    S, H, dh = q1.shape
    rng = np.random.RandomState(22)
    q = jnp.asarray(rng.randn(S, 4, H, dh).astype(np.float32))
    with overrides.scope(cfg):
        out_k = ragged_paged_attention(q, kp, vp, pt, lens)
    ref = _paged_attention_lax_multi(q, kp, vp, pt, lens)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


def test_cache_aware_admission_prefers_warm_prefix_under_pressure():
    """When pages are tight, admission reorders the queue toward the
    request with the longest warm cached prefix (smaller fresh-page
    cost) instead of blind FIFO — counted by
    `serve_prefix_admit_preferred`."""
    reg = registry()
    pref0 = reg.counter("serve_prefix_admit_preferred").value
    model = _tiny_model(max_length=48)
    rng = np.random.RandomState(14)
    src = rng.randint(4, 50, (5,)).astype(np.int32)
    pa = rng.randint(4, 50, (9,)).astype(np.int32)
    pc = rng.randint(4, 50, (9,)).astype(np.int32)
    srv = _server(model, slots=1, max_new_tokens=6, max_prompt_len=12,
                  num_pages=6)                    # capacity 5: tight
    _drain(srv, (src, 6, pa))                     # cache pa's 2 pages
    blocker = srv.submit(src, max_new_tokens=6)   # occupies the slot
    srv.scheduler.step()
    assert blocker.state == "running"
    cold = srv.submit(src, max_new_tokens=6, prompt_tokens=pc)
    warm = srv.submit(src, max_new_tokens=6, prompt_tokens=pa)
    srv.scheduler.run_until_idle(max_steps=1000)
    assert len(cold.result()) >= 1 and len(warm.result()) >= 1
    assert reg.counter("serve_prefix_admit_preferred").value > pref0
    assert warm.prompt_cached_tokens == 8         # adopted, not rebuilt
    assert warm.t_done < cold.t_done              # warm jumped the queue
    srv.close()
    assert srv.pool.in_use() == 0


def test_warm_preference_cannot_starve_cold_head():
    """The warm-prefix admission preference is BOUNDED: a cold queue
    head bypassed `MAX_ADMIT_BYPASS` times is admitted regardless, so
    sustained warm traffic cannot starve it."""
    from mxnet_tpu.serve.scheduler import Scheduler
    model = _tiny_model(max_length=48)
    rng = np.random.RandomState(15)
    src = rng.randint(4, 50, (5,)).astype(np.int32)
    pa = rng.randint(4, 50, (9,)).astype(np.int32)
    pc = rng.randint(4, 50, (9,)).astype(np.int32)
    srv = _server(model, slots=1, max_new_tokens=6, max_prompt_len=12,
                  num_pages=6, max_queue=16)     # capacity 5: tight
    _drain(srv, (src, 6, pa))                    # warm pa's prefix
    blocker = srv.submit(src, max_new_tokens=6)
    srv.scheduler.step()
    cold = srv.submit(src, max_new_tokens=6, prompt_tokens=pc)
    warms = [srv.submit(src, max_new_tokens=6, prompt_tokens=pa)
             for _ in range(Scheduler.MAX_ADMIT_BYPASS + 2)]
    srv.scheduler.run_until_idle(max_steps=4000)
    assert len(cold.result()) >= 1
    # the bound bit: cold was bypassed at most MAX_ADMIT_BYPASS times,
    # so it finished before the LAST warm request
    assert cold._admit_bypassed <= Scheduler.MAX_ADMIT_BYPASS
    assert cold.t_done < warms[-1].t_done
    assert len(blocker.result()) >= 1
    srv.close()
    assert srv.pool.in_use() == 0


# ------------------------------------------- low precision (ISSUE 14)
def _int8_model():
    # smaller than _tiny_model: the low-precision suite compiles several
    # extra executables, and the tier-1 window is tight
    return _tiny_model(vocab=40, units=16, layers=1, heads=2,
                       max_length=48, seed=13)


def _match_rate(ref, out):
    matched = sum(sum(1 for x, y in zip(a, b) if x == y)
                  for a, b in zip(ref, out))
    total = sum(max(len(a), len(b)) for a, b in zip(ref, out))
    return matched / max(total, 1)


def _lp_requests(n=5, seed=3):
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        src = rng.randint(4, 40, (int(rng.randint(3, 10)),)).astype(
            np.int32)
        prompt = rng.randint(4, 40, (8,)).astype(np.int32) if i % 2 \
            else None
        reqs.append((src, int(rng.choice([4, 6, 8])), prompt))
    # repeat a prompted request so the prefix-warm path runs too
    return reqs + [r for r in reqs if r[2] is not None][:1]


def test_int8_kv_token_match_cold_warm_and_speculative():
    """The accuracy contract: int8-KV greedy output matches fp32 at
    >= 0.99 token-match rate across prefix-cache cold/warm traffic and
    speculative k in {2, 3} — and the pool accounting stays exact (no
    stuck references beyond the cache, zero after close)."""
    model = _int8_model()
    reqs = _lp_requests()
    fp = _server(model, max_prompt_len=8)
    ref = _drain(fp, *reqs)
    fp.close()
    for k in (0, 2, 3):
        srv = _server(model, max_prompt_len=8, kv_dtype="int8",
                      speculative_k=k)
        out = _drain(srv, *reqs)
        assert srv.pool.in_use() == srv.prefix_cache.pages_held()
        rate = _match_rate(ref, out)
        srv.close()
        assert srv.pool.in_use() == 0
        assert rate >= 0.99, (k, rate)


def test_int8_pages_carry_scales_through_radix_cache():
    """Shared int8 pages carry their scales: scales are indexed by page
    id in the pool-parallel scale arrays, so a warm request adopting
    cached prompt pages sees the cold request's exact quantised content
    AND grid — cold vs warm output is BITWISE identical."""
    model = _int8_model()
    rng = np.random.RandomState(7)
    src = rng.randint(4, 40, (6,)).astype(np.int32)
    prompt = rng.randint(4, 40, (8,)).astype(np.int32)
    srv = _server(model, max_prompt_len=8, kv_dtype="int8")
    cold = _drain(srv, (src, 8, prompt))[0]
    cache = srv.prefix_cache
    pages = [n.page for n in cache._nodes]
    assert pages, "prompt pages were not cached"
    ks = np.asarray(srv.runtime.k_scales)      # (L, P, H)
    vs = np.asarray(srv.runtime.v_scales)
    assert np.all(ks[:, pages, :] > 0) and np.all(vs[:, pages, :] > 0)
    hits0 = cache.hits
    warm = _drain(srv, (src, 8, prompt))[0]
    assert cache.hits == hits0 + 1
    assert warm == cold            # adopted pages + scales, bit for bit
    traces = srv.runtime.decode_traces
    srv.close()
    assert traces == 1 and srv.pool.in_use() == 0


def test_int8_kv_fixed_budget_capacity():
    """The capacity pin: a fixed HBM byte budget holds >= 1.9x the
    TOKENS of the fp32 pool (scale arrays included in the arithmetic),
    and `Server(kv_hbm_bytes=)` sizes its pool to exactly that
    accounting."""
    from mxnet_tpu.serve.quant import kv_page_bytes, token_capacity
    geo = dict(n_layers=1, page_size=4, num_heads=2, head_dim=8)
    budget = 32 * kv_page_bytes(kv_dtype="float32", **geo)
    cap_fp = token_capacity(budget, kv_dtype="float32", **geo)
    cap_q = token_capacity(budget, kv_dtype="int8", **geo)
    assert cap_q / cap_fp >= 1.9
    model = _int8_model()
    srv = _server(model, kv_dtype="int8", kv_hbm_bytes=budget,
                  max_new_tokens=8)
    assert srv.pool.capacity * srv.pool.page_size == cap_q
    assert srv.runtime.kv_bytes_per_page() == kv_page_bytes(
        kv_dtype="int8", **geo)
    srv.close()
    with pytest.raises(MXNetError):
        _server(model, kv_dtype="int8", kv_hbm_bytes=budget, num_pages=8)


def test_chaos_quant_fault_degrades_to_full_precision():
    """serve.quant chaos (the PR 12 fault-discipline mold): an injected
    quantization fault degrades THAT request to the full-precision path
    with output identical to an fp32 server's, zero leaked pages and
    zero stuck refcounts; the next request runs the quantized path
    normally."""
    from mxnet_tpu.observability import registry as _registry
    model = _int8_model()
    rng = np.random.RandomState(9)
    src = rng.randint(4, 40, (6,)).astype(np.int32)
    prompt = rng.randint(4, 40, (8,)).astype(np.int32)
    fp = _server(model, max_prompt_len=8)
    ref = _drain(fp, (src, 8, prompt))[0]
    fp.close()
    srv = _server(model, max_prompt_len=8, kv_dtype="int8",
                  weight_dtype="int8")
    deg0 = _registry().counter("serve_quant_degraded").value
    finj.inject("serve.quant", times=1)
    degraded = _drain(srv, (src, 8, prompt))[0]
    assert degraded == ref
    assert _registry().counter("serve_quant_degraded").value == deg0 + 1
    # the degraded request never touched the quantized pool: nothing
    # held beyond (possibly) cache pages, and no refcount above 1
    assert srv.pool.in_use() == srv.prefix_cache.pages_held()
    # fault exhausted: the next request runs quantized (counter flat,
    # decode executable actually dispatched)
    out2 = _drain(srv, (src, 8, prompt))[0]
    assert len(out2) == len(ref)
    assert _registry().counter("serve_quant_degraded").value == deg0 + 1
    assert srv.runtime.decode_traces == 1
    bad = [p for p in range(1, srv.pool.num_pages)
           if srv.pool.ref_count(p) > 1]
    assert not bad
    srv.close()
    assert srv.pool.in_use() == 0


def test_weight_int8_serve_matches_fp32():
    """Per-channel int8 weights: the serve snapshot quantises (decoder
    Dense leaves become (int8, bias, per-output-channel scale); the
    embed carries per-row scales), the MODEL's master weights stay full
    precision, and greedy output matches fp32 at >= 0.99."""
    import jax.numpy as jnp
    model = _int8_model()
    reqs = _lp_requests(n=4, seed=5)
    fp = _server(model, max_prompt_len=8)
    ref = _drain(fp, *reqs)
    fp.close()
    srv = _server(model, max_prompt_len=8, weight_dtype="int8")
    w = srv.runtime._w
    assert w["embed"].dtype == jnp.int8 and "embed_scale" in w
    wq, b, s = w["layers"][0]["qkv"]
    assert wq.dtype == jnp.int8 and s.shape == (wq.shape[0],)
    # master weights untouched
    assert model.embed.weight.data()._data.dtype == jnp.float32
    out = _drain(srv, *reqs)
    rate = _match_rate(ref, out)
    srv.close()
    assert rate >= 0.99, rate
    assert srv.pool.in_use() == 0


def test_paged_attention_quant_kernel_interpret(monkeypatch):
    """The quantised Pallas kernels' numerics (scales via bitcast
    scalar prefetch, dequant in VMEM), pinned on CPU via interpret mode
    against the lax gathered-dequant fallback — 1-wide and widened."""
    monkeypatch.setenv("MXTPU_PALLAS_INTERPRET", "1")
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import (
        _paged_attention_lax, _paged_attention_lax_multi,
        ragged_paged_attention)
    rng = np.random.RandomState(0)
    S, H, dh, P, psize = 3, 2, 8, 9, 8
    q = jnp.asarray(rng.randn(S, H, dh).astype(np.float32))
    kp = jnp.asarray(rng.randint(-127, 128, (P, psize, H, dh))
                     .astype(np.int8))
    vp = jnp.asarray(rng.randint(-127, 128, (P, psize, H, dh))
                     .astype(np.int8))
    ks = jnp.asarray((rng.rand(P, H) * 0.05 + 1e-3).astype(np.float32))
    vs = jnp.asarray((rng.rand(P, H) * 0.05 + 1e-3).astype(np.float32))
    pt = jnp.asarray(np.array([[1, 2], [3, 0], [4, 5]], np.int32))
    lens = jnp.asarray(np.array([12, 5, 16], np.int32))
    out = ragged_paged_attention(q, kp, vp, pt, lens,
                                 k_scales=ks, v_scales=vs)
    ref = _paged_attention_lax(q, kp, vp, pt, lens,
                               k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)
    qm = jnp.asarray(rng.randn(S, 3, H, dh).astype(np.float32))
    outm = ragged_paged_attention(qm, kp, vp, pt, lens,
                                  k_scales=ks, v_scales=vs)
    refm = _paged_attention_lax_multi(qm, kp, vp, pt, lens,
                                      k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(outm), np.asarray(refm),
                               rtol=2e-6, atol=2e-6)


def test_quant_degrade_honours_deadline():
    """A deadline_ms request hit by a serve.quant fault gets no deadline
    amnesty: the remaining budget rides into the full-precision
    fallback, and an already/soon-expired request surfaces the same
    `ServeDeadlineExceeded` the normal path raises (counted into
    `serve_deadline_expired`), with nothing leaked."""
    from mxnet_tpu.observability import registry as _registry
    from mxnet_tpu.serve.scheduler import ServeDeadlineExceeded
    model = _int8_model()
    rng = np.random.RandomState(4)
    src = rng.randint(4, 40, (6,)).astype(np.int32)
    srv = _server(model, kv_dtype="int8")
    exp0 = _registry().counter("serve_deadline_expired").value
    finj.inject("serve.quant", times=1)
    h = srv.submit(src, max_new_tokens=8, deadline_ms=0.5)
    with pytest.raises(ServeDeadlineExceeded):
        h.result(timeout=60)
    assert _registry().counter("serve_deadline_expired").value > exp0
    # fault exhausted + no deadline: the quantized path serves normally
    out = _drain(srv, (src, 4, None))[0]
    assert len(out) >= 1
    srv.close()
    assert srv.pool.in_use() == 0
