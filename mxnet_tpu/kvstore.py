"""KVStore (reference: python/mxnet/kvstore.py + src/kvstore/*).

Backends:
  * 'local' / 'device' — single-process aggregation (reference comm tree /
    device comm); values pushed for a key are summed, pulls broadcast.
  * 'ici' — the TPU-native distributed backend replacing the reference's
    'nccl' / 'dist_sync' (BASELINE.json north star). Aggregation is a
    `jax.lax.psum` over the 'dp' axis of a `jax.sharding.Mesh`, executed via
    `shard_map`, so gradients ride the ICI interconnect and never touch the
    host. Imperative push/pull on sharded NDArrays lower to one fused XLA
    collective; inside a pjit-compiled train step the same `allreduce_`
    helper is traced straight into the step's StableHLO module.

Optimizer offload (`set_optimizer`) runs updates at pull time like the
reference's server-side update path (update_on_kvstore=True).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError, _as_list
from .ndarray.ndarray import NDArray

__all__ = ["KVStore", "create"]


def create(name="local"):
    """Create a KVStore. Supported: local, device, ici (+ dist aliases)."""
    if isinstance(name, KVStore):
        return name
    name = name.lower()
    if name in ("local", "local_allreduce_cpu", "local_allreduce_device"):
        return KVStore("local")
    if name in ("device", "nccl"):
        return KVStore("device")
    if name in ("ici", "dist", "dist_sync", "dist_device_sync", "dist_async",
                "horovod"):
        return KVStore("ici")
    raise MXNetError(f"unknown kvstore type {name!r}")


class KVStore:
    def __init__(self, kind):
        self._kind = kind
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._mesh = None

    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return jax.process_index() if self._kind == "ici" else 0

    @property
    def num_workers(self):
        return jax.process_count() if self._kind == "ici" else 1

    def set_mesh(self, mesh):
        """Attach a jax.sharding.Mesh (ici backend) for psum lowering."""
        self._mesh = mesh
        return self

    # ------------------------------------------------------------------
    def init(self, key, value):
        keys = _as_list(key)
        values = _as_list(value)
        for k, v in zip(keys, values):
            self._store[str(k)] = NDArray(v._data)

    def push(self, key, value, priority=0):
        """Aggregate values into the store (sum across devices/workers)."""
        keys = _as_list(key)
        if len(keys) == 1 and not isinstance(value, (list, tuple)) or \
                (isinstance(value, (list, tuple))
                 and not isinstance(value[0], (list, tuple))
                 and len(keys) == 1):
            values = [_as_list(value)]
        else:
            values = [_as_list(v) for v in value]
        for k, vals in zip(keys, values):
            agg = self.allreduce_([v._data for v in vals])
            k = str(k)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"key {k} not initialised")
                self._updater(k, NDArray(agg), self._store[k])
            else:
                self._store[k] = NDArray(agg)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = _as_list(key)
        outs = []
        for k in keys:
            k = str(k)
            if k not in self._store:
                raise MXNetError(f"key {k} not initialised")
            val = self._store[k]
            outs.append(val)
        if out is not None:
            flat_out = _as_list(out)
            if len(keys) == 1:
                for o in flat_out:
                    if isinstance(o, (list, tuple)):
                        for oo in o:
                            oo._assign_value(outs[0]._data)
                    else:
                        o._assign_value(outs[0]._data)
            else:
                for o, v in zip(flat_out, outs):
                    if isinstance(o, (list, tuple)):
                        for oo in o:
                            oo._assign_value(v._data)
                    else:
                        o._assign_value(v._data)
            return
        return outs[0] if len(outs) == 1 else outs

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out=out if out is not None else value, priority=priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        raise MXNetError("sparse storage is not supported on TPU "
                         "(SURVEY.md §2 #49); use dense pull")

    # ------------------------------------------------------------------
    def allreduce_(self, arrays):
        """Sum a list of jax arrays; on 'ici' with multiple devices this is
        a psum over the mesh 'dp' axis via shard_map."""
        if len(arrays) == 1:
            a = arrays[0]
            if self._kind == "ici" and self._mesh is not None and \
                    np.prod([self._mesh.shape[ax] for ax in self._mesh.axis_names]) > 1:
                return self._psum_sharded(a)
            return a
        out = arrays[0]
        for a in arrays[1:]:
            out = out + a
        return out

    def _psum_sharded(self, a):
        from jax.sharding import PartitionSpec as P
        from jax import shard_map
        mesh = self._mesh
        axis = mesh.axis_names[0]
        f = shard_map(lambda x: jax.lax.psum(x, axis), mesh=mesh,
                      in_specs=P(axis), out_specs=P(axis))
        return f(a)

    # ------------------------------------------------------------------
    def set_optimizer(self, optimizer):
        from .optimizer import get_updater, create as opt_create
        self._optimizer = opt_create(optimizer) if not hasattr(
            optimizer, "update") else optimizer
        self._updater = _KVUpdater(self._optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def save_optimizer_states(self, fname, dump_optimizer=False):
        import pickle
        states = {}
        if self._updater is not None:
            states = {k: jax.tree_util.tree_map(np.asarray, v)
                      for k, v in getattr(self._updater, "states", {}).items()}
        with open(fname, "wb") as f:
            pickle.dump(states, f)

    def load_optimizer_states(self, fname):
        import pickle
        with open(fname, "rb") as f:
            pickle.load(f)

    def barrier(self):
        from .ndarray.ndarray import waitall
        waitall()


class _KVUpdater:
    """Server-side updater: applies optimizer at push time."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, key, grad, weight):
        if key not in self.states:
            self.states[key] = \
                self.optimizer.create_state_multi_precision(key, weight)
        self.optimizer.update_multi_precision(key, weight, grad,
                                              self.states[key])
