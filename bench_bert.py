"""BERT-base MLM pretraining throughput, tokens/sec/chip (BASELINE.json's
second headline metric).

One jitted bf16 train step: BERT-base (12x768x12, vocab 30522) MLM at
seq_len 512, Pallas flash attention, 76 masked positions/sequence (15%),
AdamW-free SGD-momentum update (same optimizer as the ResNet bench so the
two headline numbers are comparable), donated buffers.

Baseline denominator: no published per-chip MXNet/GluonNLP A100 number
exists in BASELINE.json ("published": {}), so the reference class is derived
the same way SURVEY.md §6 derives the ResNet one — A100 fp16-class sustained
transformer throughput. BERT-base training costs ~0.72 GFLOP/token at
seq 512 (6*110e6 params-matmul + 12 layers * 12*S*d attention / 3 passes);
NVIDIA's tuned BERT runs at ~35% MFU on A100 (312 TFLOPs peak) ->
0.35*312e12/0.72e9 ~= 150k tokens/s/chip. We use 150000.

Run directly, or via `python bench.py` which merges this metric into its
single JSON line. Prints ONE JSON line when run standalone.
"""
from __future__ import annotations

import json
import os
import sys
import time

BASELINE_TOK_S = 150_000.0
SEQ, MASKED = 512, 76


def build_step(batch, seq, masked):
    """Build the jitted BERT MLM train step. Returns (step, params, mom,
    data) — shared by measure() and tools/profile_bert.py."""
    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx  # noqa: F401  (registers dtypes/ops)
    from mxnet_tpu.gluon.block import extract_pure_fn
    from mxnet_tpu.models.bert import BERTForPretraining, bert_base

    model = BERTForPretraining(bert_base(max_length=seq, dropout=0.0))
    model.initialize()
    model.cast("bfloat16")

    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    tok = mx.nd.NDArray(jax.random.randint(k1, (batch, seq), 0, 30522))
    seg = mx.nd.NDArray(jnp.zeros((batch, seq), jnp.int32))
    vl = mx.nd.NDArray(jnp.full((batch,), seq, jnp.int32))
    pos = mx.nd.NDArray(jax.random.randint(k2, (batch, masked), 0, seq))
    model(tok, seg, vl, pos)  # materialise params
    fwd, params = extract_pure_fn(model, tok, seg, vl, pos, training=True)
    aux_idx = list(fwd.aux_indices)

    mlm_labels = jax.random.randint(k3, (batch, masked), 0, 30522)
    nsp_labels = jax.random.randint(k4, (batch,), 0, 2)

    def loss_fn(p, t, s, v, mp, ml, nl):
        (mlm, nsp), aux = fwd(p, t, s, v, mp)
        mlm = mlm.astype(jnp.float32)
        nsp = nsp.astype(jnp.float32)
        lp = jax.nn.log_softmax(mlm, axis=-1)
        l_mlm = -jnp.mean(jnp.take_along_axis(lp, ml[..., None], -1))
        lp2 = jax.nn.log_softmax(nsp, axis=-1)
        l_nsp = -jnp.mean(jnp.take_along_axis(lp2, nl[:, None], -1))
        return l_mlm + l_nsp, aux

    lr, mu = 1e-3, 0.9
    # same lever as bench.py's BENCH_UNROLL: k steps per dispatch.
    # Measured 2026-07-31: 1 -> 165.8k, 4 -> 174.7k, 8 -> 175.8k tok/s;
    # default 4 (8's +0.6% is not worth the extra compile inside the
    # shared 900s worker budget).
    on_tpu = jax.default_backend() == "tpu"
    unroll = max(1, int(os.environ.get("BENCH_BERT_UNROLL",
                                       "4" if on_tpu else "1")))
    from bench_util import make_sgd_step
    step = make_sgd_step(loss_fn, aux_idx, lr, mu, unroll)
    mom = [jnp.zeros_like(p) for p in params]
    data = (tok._data, seg._data, vl._data, pos._data, mlm_labels, nsp_labels)
    return step, params, mom, data, unroll


def _measure_one(batch, steps, seq, masked):
    # unroll comes back from build_step so the tok/s numerator can never
    # disagree with what was actually compiled
    step, params, mom, data, unroll = build_step(batch, seq, masked)
    from bench_util import timed_measure
    return timed_measure(step, params, mom, data, steps,
                         batch * seq * unroll,
                         tag=f"bench_bert b{batch}")


def measure(batch=None, steps=None, on_result=None):
    """`on_result(result_dict)` fires whenever the best-so-far improves —
    bench.py uses it to checkpoint its merged JSON line so a wedged
    later candidate can't lose this metric."""
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if batch is None:
        # round-2 on-chip sweep: 16→167.1k, 24→166.0k, 32→166.0k tok/s
        # (docs/PERF.md) — 16 is the optimum, so measure it alone by
        # default; BENCH_BERT_BATCH=a[,b] re-opens the sweep
        candidates = [16] if on_tpu else [2]
    else:
        candidates = list(batch) if isinstance(batch, (list, tuple)) \
            else [batch]
    if steps is None:
        steps = 20 if on_tpu else 2
    seq = SEQ if on_tpu else 64
    masked = MASKED if on_tpu else 8
    print(f"[bench_bert] backend={jax.default_backend()} "
          f"candidates={candidates} seq={seq} steps={steps}",
          file=sys.stderr)

    from bench_util import sweep
    SWEEP_BUDGET_S = 150

    def run_one(b):
        return _measure_one(b, steps, seq, masked)

    best, _ = sweep(candidates, SWEEP_BUDGET_S, run_one,
                    on_best=None if on_result is None
                    else (lambda tok_s: on_result(_result(tok_s))),
                    tag="bench_bert")
    return _result(best)


def _result(tok_s):
    return {
        "metric": "bert_base_mlm_train_throughput",
        "value": round(tok_s, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tok_s / BASELINE_TOK_S, 4),
    }


def main():
    # honor JAX_PLATFORMS=cpu despite the axon sitecustomize (same dance
    # as bench.py — jax.config wins if set before backend init)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    batch = os.environ.get("BENCH_BERT_BATCH")
    steps = os.environ.get("BENCH_BERT_STEPS")
    res = measure([int(b) for b in batch.split(",")] if batch else None,
                  int(steps) if steps else None)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
