"""Classic op-name surface (SURVEY.md §2 rows 3/7/24 adjuncts; reference:
elemwise_binary_op_basic.cc, regression_output-inl.h, optimizer_op.cc,
nn/im2col.cc). Numerics vs numpy/torch closed forms."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_aliases_and_small_math():
    a = nd.array([[1.0, -2.0], [3.0, 4.0]])
    onp.testing.assert_allclose(nd.elemwise_add(a, a).asnumpy(),
                                2 * a.asnumpy())
    onp.testing.assert_allclose(nd.elemwise_div(a, a).asnumpy(),
                                onp.ones((2, 2)))
    onp.testing.assert_allclose(nd.identity(a).asnumpy(), a.asnumpy())
    onp.testing.assert_allclose(
        nd.softsign(a).asnumpy(),
        a.asnumpy() / (1 + onp.abs(a.asnumpy())), rtol=1e-6)
    onp.testing.assert_allclose(nd.degrees(nd.array([onp.pi])).asnumpy(),
                                [180.0], rtol=1e-5)
    assert nd.isnan(nd.array([onp.nan, 1.0])).asnumpy().tolist() == [1, 0]
    onp.testing.assert_allclose(nd.trace(a).asnumpy(), 5.0)
    onp.testing.assert_allclose(nd.tril(a).asnumpy(), onp.tril(a.asnumpy()))
    onp.testing.assert_allclose(
        nd.logical_and(nd.array([1, 0]), nd.array([1, 1])).asnumpy(),
        [1, 0])
    onp.testing.assert_allclose(
        nd.SwapAxis(nd.ones((2, 3)), 0, 1).shape, (3, 2))
    onp.testing.assert_allclose(
        nd.broadcast_axes(nd.ones((1, 3)), axis=0, size=4).shape, (4, 3))
    # crop is the deprecated alias of slice, not the Crop op
    onp.testing.assert_allclose(
        nd.crop(a, begin=(0, 1), end=(2, 2)).asnumpy(),
        a.asnumpy()[0:2, 1:2])
    x = nd.array([2.0, -1.5, 0.2])
    onp.testing.assert_allclose(nd.argmax_channel(
        nd.array([[1, 3, 2], [9, 0, 1]])).asnumpy(), [1, 0])
    counts, edges = nd.histogram(x, bins=3, range=(-2, 2))
    assert int(counts.asnumpy().sum()) == 3 and edges.shape == (4,)
    bc = nd.bincount(nd.array([0, 1, 1, 3], dtype="int32"))
    assert bc.asnumpy().tolist() == [1, 2, 0, 1]


def test_softmax_activation():
    x = onp.random.RandomState(0).randn(2, 4).astype(onp.float32)
    out = nd.SoftmaxActivation(nd.array(x))
    onp.testing.assert_allclose(out.asnumpy().sum(-1), onp.ones(2),
                                rtol=1e-5)
    xc = onp.random.RandomState(1).randn(2, 3, 4).astype(onp.float32)
    outc = nd.SoftmaxActivation(nd.array(xc), mode="channel")
    onp.testing.assert_allclose(outc.asnumpy().sum(1), onp.ones((2, 4)),
                                rtol=1e-5)


def test_regression_heads_forward_and_grad():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    y = nd.array([[0.0, 0.0], [0.0, 0.0]])
    x.attach_grad()
    with autograd.record():
        out = nd.LinearRegressionOutput(x, y)
    out.backward()
    # grad = (pred - label) / num_output, reference scaling
    onp.testing.assert_allclose(x.grad.asnumpy(), x.asnumpy() / 2,
                                rtol=1e-6)
    onp.testing.assert_allclose(out.asnumpy(), x.asnumpy())

    x2 = nd.array([[0.0], [2.0]])
    x2.attach_grad()
    with autograd.record():
        o2 = nd.LogisticRegressionOutput(x2, nd.array([[1.0], [0.0]]))
    o2.backward()
    sig = 1 / (1 + onp.exp(-x2.asnumpy()))
    onp.testing.assert_allclose(o2.asnumpy(), sig, rtol=1e-5)
    onp.testing.assert_allclose(x2.grad.asnumpy(),
                                sig - [[1.0], [0.0]], rtol=1e-5)

    x3 = nd.array([[1.0, -1.0]])
    x3.attach_grad()
    with autograd.record():
        o3 = nd.MAERegressionOutput(x3, nd.array([[0.0, 0.0]]))
    o3.backward()
    onp.testing.assert_allclose(x3.grad.asnumpy(), [[0.5, -0.5]])


def test_svm_output_grad_zero_when_margin_satisfied():
    # true class already beyond margin for every class pair -> zero grad
    x = nd.array([[5.0, -5.0]])
    x.attach_grad()
    with autograd.record():
        out = nd.SVMOutput(x, nd.array([0.0]), margin=1.0)
    out.backward()
    onp.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    onp.testing.assert_allclose(x.grad.asnumpy(), [[0.0, 0.0]])
    # violated margin -> pushes true class up, off class down
    x2 = nd.array([[0.0, 0.0]])
    x2.attach_grad()
    with autograd.record():
        o2 = nd.SVMOutput(x2, nd.array([0.0]), margin=1.0, use_linear=True)
    o2.backward()
    g = x2.grad.asnumpy()
    assert g[0, 0] < 0 < g[0, 1]


def test_im2col_col2im_roundtrip():
    torch = pytest.importorskip("torch")
    x = onp.random.RandomState(2).randn(2, 3, 8, 8).astype(onp.float32)
    cols = nd.im2col(nd.array(x), kernel=(3, 3), stride=(2, 2), pad=(1, 1))
    ref = torch.nn.functional.unfold(torch.from_numpy(x), (3, 3),
                                     padding=1, stride=2).numpy()
    onp.testing.assert_allclose(cols.asnumpy(), ref, rtol=1e-5, atol=1e-5)
    back = nd.col2im(cols, output_size=(8, 8), kernel=(3, 3),
                     stride=(2, 2), pad=(1, 1))
    fold = torch.nn.functional.fold(torch.from_numpy(ref), (8, 8), (3, 3),
                                    padding=1, stride=2).numpy()
    onp.testing.assert_allclose(back.asnumpy(), fold, rtol=1e-5, atol=1e-5)


def test_nd_rnn_matches_gluon_layer():
    from mxnet_tpu.gluon import rnn as grnn
    layer = grnn.LSTM(5, num_layers=1)
    layer.initialize()
    x = nd.random.uniform(shape=(7, 2, 4))   # TNC
    out = layer(x)
    params = layer.collect_params()
    pnames, pvals = [], []
    for name, p in params.items():
        pnames.append(name.split("lstm0_")[-1] if "lstm0_" in name
                      else name)
        pvals.append(p.data())
    # imperative fused op with the same weights
    res = nd.RNN(x, *pvals, mode="lstm", num_layers=1, num_dir=1,
                 hidden_size=5, pnames=tuple(pnames))
    onp.testing.assert_allclose(res.asnumpy(), out.asnumpy(), rtol=1e-5,
                                atol=1e-5)


# ----------------------------------------------------- optimizer update ops
def test_sgd_update_matches_formula():
    w = nd.array([1.0, 2.0])
    g = nd.array([0.5, -0.5])
    out = nd.sgd_update(w, g, lr=0.1, wd=0.0)
    onp.testing.assert_allclose(out.asnumpy(), [0.95, 2.05], rtol=1e-6)
    assert out is w                       # in-place contract


def test_sgd_mom_update_state_carries():
    w, g = nd.array([1.0]), nd.array([1.0])
    m = nd.zeros((1,))
    nd.sgd_mom_update(w, g, m, lr=0.1, momentum=0.9)
    onp.testing.assert_allclose(m.asnumpy(), [-0.1], rtol=1e-6)
    onp.testing.assert_allclose(w.asnumpy(), [0.9], rtol=1e-6)
    nd.sgd_mom_update(w, g, m, lr=0.1, momentum=0.9)
    onp.testing.assert_allclose(m.asnumpy(), [-0.19], rtol=1e-5)


def test_adam_update_matches_torch():
    torch = pytest.importorskip("torch")
    w0 = onp.array([1.0, -2.0, 3.0], onp.float32)
    g0 = onp.array([0.1, 0.2, -0.3], onp.float32)
    w, g = nd.array(w0), nd.array(g0)
    mean, var = nd.zeros((3,)), nd.zeros((3,))
    tw = torch.tensor(w0, requires_grad=True)
    opt = torch.optim.Adam([tw], lr=0.01, betas=(0.9, 0.999), eps=1e-8)
    for step in range(3):
        nd.adam_update(w, g, mean, var, lr=0.01)
        tw.grad = torch.tensor(g0)
        opt.step()
    # mx adam_update applies NO bias correction (reference semantics);
    # torch does — compare against the uncorrected closed form instead
    m = onp.zeros(3)
    v = onp.zeros(3)
    wref = w0.copy()
    for _ in range(3):
        m = 0.9 * m + 0.1 * g0
        v = 0.999 * v + 0.001 * g0 * g0
        wref -= 0.01 * m / (onp.sqrt(v) + 1e-8)
    onp.testing.assert_allclose(w.asnumpy(), wref, rtol=1e-5)


def test_signsgd_rmsprop_ftrl_nag_smoke():
    w, g = nd.array([1.0, -1.0]), nd.array([0.3, -0.3])
    nd.signsgd_update(w, g, lr=0.1)
    onp.testing.assert_allclose(w.asnumpy(), [0.9, -0.9], rtol=1e-6)

    w2, n2 = nd.array([1.0]), nd.zeros((1,))
    nd.rmsprop_update(w2, nd.array([1.0]), n2, lr=0.1, gamma1=0.9)
    assert float(n2.asnumpy()[0]) == pytest.approx(0.1, rel=1e-5)

    w3, z3, n3 = nd.array([1.0]), nd.zeros((1,)), nd.zeros((1,))
    nd.ftrl_update(w3, nd.array([1.0]), z3, n3, lr=0.1, lamda1=0.01)
    assert float(n3.asnumpy()[0]) == pytest.approx(1.0)
    assert float(w3.asnumpy()[0]) != 1.0

    w4, m4 = nd.array([1.0]), nd.zeros((1,))
    nd.nag_mom_update(w4, nd.array([1.0]), m4, lr=0.1, momentum=0.9)
    onp.testing.assert_allclose(m4.asnumpy(), [1.0], rtol=1e-6)


def test_mp_sgd_update_keeps_master_precision():
    w16 = nd.array([1.0, 2.0]).astype("bfloat16")
    w32 = nd.array([1.0, 2.0])
    g16 = nd.array([1e-3, 1e-3]).astype("bfloat16")
    for _ in range(10):
        nd.mp_sgd_update(w16, g16, w32, lr=0.1)
    # fp32 master accumulated 10 tiny steps bf16 alone would lose
    onp.testing.assert_allclose(w32.asnumpy(), [0.999, 1.999], rtol=1e-4)
    assert w16.dtype == onp.dtype("bfloat16") or str(w16.dtype) == "bfloat16"


def test_multi_sum_sq_and_lamb():
    arrs = [nd.array([3.0, 4.0]), nd.array([1.0])]
    ss = nd.multi_sum_sq(*arrs)
    onp.testing.assert_allclose(ss.asnumpy(), [25.0, 1.0])

    w = nd.array([0.5, 0.5])
    g = nd.array([0.1, -0.1])
    mean, var = nd.zeros((2,)), nd.zeros((2,))
    gp = nd.lamb_update_phase1(w, g, mean, var, t=1, wd=0.01)
    assert gp.shape == (2,)
    r1 = nd.norm(w)
    r2 = nd.norm(gp)
    new_w = nd.lamb_update_phase2(w, gp, r1, r2, lr=0.01)
    assert new_w is w and not onp.allclose(w.asnumpy(), [0.5, 0.5])


def test_random_op_aliases():
    assert nd.random_uniform(shape=(3,)).shape == (3,)
    assert nd.sample_poisson(lam=2.0, shape=(4,)).shape == (4,)
    assert nd.random_gamma(shape=(2,)).shape == (2,)


def test_sym_slice_and_fromjson():
    from mxnet_tpu import sym
    data = sym.Variable("data")
    s = sym.slice(data, begin=(0, 1), end=(2, 3))
    e = s.bind(mx.cpu(), {"data": nd.array(onp.arange(12.).reshape(3, 4))})
    out = e.forward()[0]
    onp.testing.assert_allclose(out.asnumpy(),
                                onp.arange(12.).reshape(3, 4)[0:2, 1:3])
    sa = sym.slice_axis(data, axis=1, begin=1, end=3)
    e2 = sa.bind(mx.cpu(), {"data": nd.array(onp.arange(12.).reshape(3, 4))})
    onp.testing.assert_allclose(e2.forward()[0].asnumpy(),
                                onp.arange(12.).reshape(3, 4)[:, 1:3])
    # JSON round-trip through the registered kernels
    s2 = mx.sym.fromjson(s.tojson())
    e3 = s2.bind(mx.cpu(), {"data": nd.array(onp.arange(12.).reshape(3, 4))})
    onp.testing.assert_allclose(e3.forward()[0].asnumpy(),
                                out.asnumpy())
