"""Sequence ops: SequenceMask / SequenceLast / SequenceReverse + small
pointwise ops (smooth_l1, softmin, hard_sigmoid).

Reference parity: src/operator/sequence_mask.cc, sequence_last.cc,
sequence_reverse.cc, src/operator/tensor/elemwise_unary_op (smooth_l1,
hard_sigmoid), softmin (softmax.cc). The reference implements the sequence
ops as per-batch CUDA loops over the time axis; here each one is a single
vectorised XLA op (a select or one gather), static-shape and
jit/vmap/grad-compatible, so they fuse into surrounding RNN/attention
programs instead of breaking them into host-synchronised steps.

Conventions (same as the reference): `data` is (T, N, ...) for axis=0 or
(N, T, ...) for axis=1; `sequence_length` is (N,) counting valid steps;
`use_sequence_length=False` means the op degenerates (mask: identity,
last: data[-1], reverse: full flip).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray, _apply

__all__ = ["SequenceMask", "SequenceLast", "SequenceReverse",
           "smooth_l1", "softmin", "hard_sigmoid",
           "sequence_mask_k", "sequence_last_k", "sequence_reverse_k",
           "smooth_l1_k", "softmin_k", "hard_sigmoid_k"]


# --------------------------------------------------------------- raw kernels
def _valid_mask(T, lengths, axis, ndim):
    """Boolean mask of valid positions, broadcastable to the data rank:
    (T, N, 1, ...) for axis=0 or (N, T, 1, ...) for axis=1."""
    t = jnp.arange(T, dtype=jnp.int32)
    ln = lengths.astype(jnp.int32)
    m = t[:, None] < ln[None, :] if axis == 0 else t[None, :] < ln[:, None]
    return m.reshape(m.shape + (1,) * (ndim - 2))


def sequence_mask_k(data, lengths=None, value=0.0, axis=0):
    if lengths is None:
        return data
    mask = _valid_mask(data.shape[axis], lengths, axis, data.ndim)
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


def sequence_last_k(data, lengths=None, axis=0):
    T = data.shape[axis]
    if lengths is None:
        return jnp.take(data, T - 1, axis=axis)
    idx = jnp.clip(lengths.astype(jnp.int32) - 1, 0, T - 1)  # (N,)
    if axis == 0:
        idx = idx.reshape((1, -1) + (1,) * (data.ndim - 2))
    else:
        idx = idx.reshape((-1, 1) + (1,) * (data.ndim - 2))
    # one XLA gather along time, per batch element
    return jnp.take_along_axis(data, idx, axis=axis).squeeze(axis)


def sequence_reverse_k(data, lengths=None, axis=0):
    """Reverse the valid prefix along time; padding stays in place.
    out[t, n] = data[len[n]-1-t, n] for t < len[n], else data[t, n]."""
    if axis != 0:
        raise ValueError("SequenceReverse supports axis=0 only (reference: "
                         "src/operator/sequence_reverse.cc)")
    if lengths is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    t = jnp.arange(T, dtype=jnp.int32)[:, None]          # (T, 1)
    ln = lengths.astype(jnp.int32)[None, :]              # (1, N)
    src = jnp.where(t < ln, ln - 1 - t, t)               # (T, N)
    src = src.reshape(src.shape + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, src, axis=0)


def smooth_l1_k(data, scalar=1.0):
    """f(x) = 0.5*(sigma*x)^2 for |x| < 1/sigma^2, else |x| - 0.5/sigma^2
    (reference: smooth_l1 in src/operator/tensor, sigma passed as `scalar`)."""
    sigma2 = scalar * scalar
    ax = jnp.abs(data)
    return jnp.where(ax < 1.0 / sigma2,
                     0.5 * sigma2 * data * data,
                     ax - 0.5 / sigma2)


def softmin_k(data, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


def hard_sigmoid_k(data, alpha=0.2, beta=0.5):
    """MXNet definition: clip(alpha*x + beta, 0, 1) — note alpha defaults to
    0.2, NOT jax.nn.hard_sigmoid's 1/6 slope."""
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


# ------------------------------------------------- imperative NDArray surface
def _seq_args(data, sequence_length, use_sequence_length):
    if use_sequence_length:
        if sequence_length is None:
            raise ValueError("use_sequence_length=True requires "
                             "sequence_length")
        return [data, sequence_length]
    return [data]


def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0, **kwargs):
    ins = _seq_args(data, sequence_length, use_sequence_length)
    return _apply(lambda *a: sequence_mask_k(
        a[0], a[1] if len(a) > 1 else None, value=value, axis=axis), ins)


def SequenceLast(data, sequence_length=None, use_sequence_length=False,
                 axis=0, **kwargs):
    ins = _seq_args(data, sequence_length, use_sequence_length)
    return _apply(lambda *a: sequence_last_k(
        a[0], a[1] if len(a) > 1 else None, axis=axis), ins)


def SequenceReverse(data, sequence_length=None, use_sequence_length=False,
                    axis=0, **kwargs):
    ins = _seq_args(data, sequence_length, use_sequence_length)
    return _apply(lambda *a: sequence_reverse_k(
        a[0], a[1] if len(a) > 1 else None, axis=axis), ins)


def smooth_l1(data, scalar=1.0, **kwargs):
    return _apply(lambda x: smooth_l1_k(x, scalar=scalar), [data])


def softmin(data, axis=-1, **kwargs):
    return _apply(lambda x: softmin_k(x, axis=axis), [data])


def hard_sigmoid(data, alpha=0.2, beta=0.5, **kwargs):
    return _apply(lambda x: hard_sigmoid_k(x, alpha=alpha, beta=beta), [data])
