"""Framework-aware AST lint over ``mxnet_tpu/`` itself (ISSUE 13).

Six rules, each distilled from a bug class that recurred across landing
passes (the CHANGES.md incident that motivated each is catalogued in
docs/STATIC_ANALYSIS.md):

  MXTPU-E01  raw ``int()``/``float()`` of an ``os.environ``/``getenv``
             read — must route through `mxnet_tpu._env` (the PR 7
             MXTPU_ENGINE_AGING_MS cpp/python parity drift, re-fixed in
             PR 10 for the retry knobs).
  MXTPU-E02  host-sync calls (``.asnumpy()``/``.item()``/``.tolist()``/
             host-numpy ``asarray``/``jax.device_get``) inside an
             engine-task body or a traced function — a silent
             host/device round-trip in the exact scopes where one
             dispatch per step is the contract.
  MXTPU-E03  a ``Counter``/``Gauge``/``Histogram`` instantiated directly
             instead of through the ``metrics_registry`` memo (PR 10
             dropped three hand-kept counter-memo dicts; a direct
             instance forks the series from its registry twin).
  MXTPU-E04  a bare ``except:`` / ``except BaseException`` in
             engine/serve code whose body never re-raises — it swallows
             cancellation/preemption (the PR 7 parity helpers exist to
             re-raise these).
  MXTPU-E05  a fault point fired (``_finj.check("x.y")``) with no
             degradation path in sight — no enclosing ``try`` and no
             evidence the enclosing function runs under a retry/deadline
             wrapper (every PR 3/6/10 fault point ships one).
  MXTPU-E06  wall-clock / RNG nondeterminism (``time.time()``, module
             ``random``, ``np.random``) inside traced code — it bakes
             one trace-time value into the executable and breaks
             bitwise replay (the PR 10 rollback contract).

Every rule supports inline suppression::

    risky_line()   # mxtpu: disable=E05 degradation is at the call site

and a checked-in baseline (tools/static_baseline.json) so pre-existing
ACCEPTED findings don't block the `check_static` gate while new ones do.
A finding's baseline fingerprint is (rule, path, scope, stripped source
line) — stable across unrelated line drift.

Pure stdlib; `lint_source` works on any source string so the gate's
seeded-violation controls and the tests feed it fixtures directly.
"""
from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

__all__ = ["Finding", "RULES", "lint_source", "lint_file", "lint_tree",
           "lint_package", "load_baseline", "apply_baseline",
           "package_root"]

RULES = {
    "MXTPU-E01": "raw numeric os.environ/getenv parse (use mxnet_tpu._env)",
    "MXTPU-E02": "host sync inside an engine-task or traced function",
    "MXTPU-E03": "metric instantiated outside the metrics_registry memo",
    "MXTPU-E04": "except swallows BaseException (cancellation) without "
                 "re-raise",
    "MXTPU-E05": "fault point fired with no visible degradation/retry "
                 "path",
    "MXTPU-E06": "wall-clock/RNG nondeterminism inside traced code",
}

# host-sync attribute calls (E02); zero-arg device->host pulls
_HOST_SYNC_ATTRS = ("asnumpy", "item", "tolist")
# numpy-module aliases whose .asarray/.array on a device value is a sync
_NUMPY_NAMES = ("numpy", "np", "onp", "_np")
# modules whose import binds a "random source" name (E06)
_TIME_FNS = ("time", "time_ns", "monotonic", "perf_counter",
             "monotonic_ns", "perf_counter_ns")
_DATETIME_FNS = ("now", "utcnow", "today")
# retry/degradation wrappers (E05): a function whose NAME is referenced
# inside any argument of a call to one of these has a degradation path
_RETRY_WRAPPERS = ("call", "retry_call", "_deadline_call", "wrap")
# engine/serve modules where E04 applies wholesale (elsewhere it applies
# only inside engine-task scopes)
_E04_MODULES = ("engine.py", "_engine_common.py")
_E04_DIRS = ("serve",)


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative posix path
    line: int
    col: int
    scope: str           # dotted enclosing class/function qualname
    message: str
    snippet: str         # stripped source line (fingerprint component)
    suppressed: bool = False
    baselined: bool = False

    @property
    def fingerprint(self):
        return (self.rule, self.path, self.scope, self.snippet)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "scope": self.scope, "message": self.message,
                "snippet": self.snippet}

    def __str__(self):
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope or '<module>'}] {self.message}")


# ------------------------------------------------------------ suppression
def _suppressed_rules(lines, lineno):
    """Rule ids disabled on `lineno` (1-based): an inline
    ``# mxtpu: disable=E01,E05 ...`` on the line itself or on a
    comment-only line directly above."""
    out = set()
    for cand in (lineno, lineno - 1):
        if not 1 <= cand <= len(lines):
            continue
        text = lines[cand - 1]
        if cand != lineno and not text.lstrip().startswith("#"):
            continue
        marker = "mxtpu: disable="
        idx = text.find(marker)
        if idx < 0 or "#" not in text[:idx]:
            continue
        spec = text[idx + len(marker):].split()[0] if \
            text[idx + len(marker):].split() else ""
        for tok in spec.split(","):
            tok = tok.strip().upper()
            if not tok:
                continue
            if not tok.startswith("MXTPU-"):
                tok = "MXTPU-" + tok
            out.add(tok)
    return out


# ---------------------------------------------------------------- helpers
def _dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _contains(node, pred):
    return any(pred(n) for n in ast.walk(node))


class _Scope:
    __slots__ = ("node", "name", "defs", "hot")

    def __init__(self, node, name):
        self.node = node
        self.name = name          # qualname component ("" for module)
        self.defs = {}            # local name -> FunctionDef/Lambda node
        self.hot = None           # "traced" | "engine_task" | None


class _Linter(ast.NodeVisitor):
    """One pass to build scopes + collect deferred facts, then a second
    resolution pass marks hot scopes and emits findings."""

    def __init__(self, src, path, relpath):
        self.lines = src.splitlines()
        self.path = relpath
        self.base = os.path.basename(path)
        self.in_serve = any(d in relpath.replace("\\", "/").split("/")
                            for d in _E04_DIRS)
        self.findings = []
        self.tree = ast.parse(src)
        # module-level import aliases
        self.os_names = set()          # names bound to the os module
        self.environ_names = set()     # names bound to os.environ
        self.getenv_names = set()      # names bound to os.getenv
        self.time_names = set()        # names bound to the time module
        self.random_names = set()      # names bound to the random module
        self.np_names = set(_NUMPY_NAMES)
        self.datetime_names = set()    # datetime module or class
        self.jax_names = set()
        self.registry_classes = set()  # Counter/... imported from
                                       # metrics_registry
        self.registry_mods = set()     # aliases of the metrics_registry
                                       # module itself
        self.is_registry_module = self.base == "metrics_registry.py"
        self.is_env_module = relpath.replace("\\", "/").endswith(
            "mxnet_tpu/_env.py")
        # deferred hot-scope requests: (scopes tuple, fn name, kind)
        self._hot_requests = []
        # names referenced inside retry-wrapper call args (E05 evidence)
        self.retried_names = set()
        # per-scope env-assigned local names: {scope node: {name}}
        self._env_locals = {}
        self._scopes = []              # stack of _Scope
        self._all_scopes = []
        self._node_scope = {}          # id(node) -> tuple of _Scope stack

    # ------------------------------------------------------ import walk
    def _collect_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    bind = a.asname or a.name.split(".")[0]
                    if a.name == "os":
                        self.os_names.add(bind)
                    elif a.name == "time":
                        self.time_names.add(bind)
                    elif a.name == "random":
                        self.random_names.add(bind)
                    elif a.name == "numpy":
                        self.np_names.add(a.asname or "numpy")
                    elif a.name == "datetime":
                        self.datetime_names.add(bind)
                    elif a.name == "jax":
                        self.jax_names.add(bind)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    bind = a.asname or a.name
                    if mod == "os":
                        if a.name == "environ":
                            self.environ_names.add(bind)
                        elif a.name == "getenv":
                            self.getenv_names.add(bind)
                    elif mod == "datetime" and a.name == "datetime":
                        self.datetime_names.add(bind)
                    elif mod.endswith("metrics_registry") \
                            or mod == "observability":
                        if a.name in ("Counter", "Gauge", "Histogram"):
                            self.registry_classes.add(bind)
                    if a.name == "metrics_registry":
                        self.registry_mods.add(bind)

    # ------------------------------------------------------------- emit
    def _emit(self, rule, node, message):
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = self.lines[lineno - 1].strip() if \
            1 <= lineno <= len(self.lines) else ""
        scope = ".".join(s.name for s in self._node_scope.get(
            id(node), ()) if s.name)
        f = Finding(rule, self.path, lineno, col, scope, message, snippet)
        if rule in _suppressed_rules(self.lines, lineno):
            f.suppressed = True
        self.findings.append(f)

    # --------------------------------------------------------- the walk
    def run(self):
        self._collect_imports()
        self._scopes = [_Scope(self.tree, "")]
        self._all_scopes = [self._scopes[0]]
        self._walk(self.tree, parents=())
        self._resolve_hot()
        self._second_pass()
        return self.findings

    def _walk(self, node, parents):
        """Scope-tracking walk: records each node's scope stack, local
        defs, jit/push/retry call facts, and env-assigned locals."""
        for child in ast.iter_child_nodes(node):
            self._node_scope[id(child)] = tuple(self._scopes)
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scopes[-1].defs[child.name] = child
                sc = _Scope(child, child.name)
                if self._decorated_jit(child):
                    sc.hot = "traced"
                if child.name == "hybrid_forward":
                    sc.hot = "traced"     # _TraceContext traces these
                self._scopes.append(sc)
                self._all_scopes.append(sc)
                self._walk(child, parents + (node,))
                self._scopes.pop()
            elif isinstance(child, ast.ClassDef):
                sc = _Scope(child, child.name)
                self._scopes.append(sc)
                self._all_scopes.append(sc)
                self._walk(child, parents + (node,))
                self._scopes.pop()
            elif isinstance(child, ast.Lambda):
                sc = _Scope(child, "<lambda>")
                self._scopes.append(sc)
                self._all_scopes.append(sc)
                self._walk(child, parents + (node,))
                self._scopes.pop()
            else:
                if isinstance(child, ast.Call):
                    self._note_call(child)
                if isinstance(child, ast.Assign):
                    self._note_assign(child)
                self._walk(child, parents + (node,))

    def _decorated_jit(self, fn):
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            d = _dotted(target)
            if d in ("jit", "jax.jit") or (d == "partial" and isinstance(
                    dec, ast.Call) and any(
                    _dotted(a) in ("jit", "jax.jit") for a in dec.args)):
                return True
            # functools.partial(jax.jit, ...) used as decorator factory
            if d and d.endswith(".partial") and isinstance(dec, ast.Call) \
                    and any(_dotted(a) in ("jit", "jax.jit")
                            for a in dec.args):
                return True
        return False

    def _note_call(self, call):
        d = _dotted(call.func)
        # jax.jit(fn, ...) / jit(fn, ...): first positional arg is traced
        if d in ("jit", "jax.jit") or (
                d and d.split(".")[-1] == "jit"
                and d.split(".")[0] in self.jax_names):
            self._mark_arg_hot(call, "traced")
        # <x>.push(fn, ...) / push(fn, ...): fn becomes an engine task
        if d and d.split(".")[-1] == "push" or (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "push"):
            self._mark_arg_hot(call, "engine_task")
        # retry/degradation wrappers: any name referenced inside the
        # args has a degradation path (E05 evidence)
        fn_name = (call.func.attr if isinstance(call.func, ast.Attribute)
                   else call.func.id if isinstance(call.func, ast.Name)
                   else None)
        if fn_name in _RETRY_WRAPPERS:
            for arg in list(call.args) + [kw.value for kw in
                                          call.keywords]:
                for n in ast.walk(arg):
                    if isinstance(n, ast.Name):
                        self.retried_names.add(n.id)
                    elif isinstance(n, ast.Attribute):
                        self.retried_names.add(n.attr)

    def _mark_arg_hot(self, call, kind):
        if not call.args:
            return
        arg = call.args[0]
        if isinstance(arg, ast.Lambda):
            # the lambda's scope gets created when we descend into it;
            # defer by node identity
            self._hot_requests.append((tuple(self._scopes), arg, kind))
        elif isinstance(arg, ast.Name):
            self._hot_requests.append((tuple(self._scopes), arg.id, kind))
        elif isinstance(arg, ast.Attribute):
            self._hot_requests.append((tuple(self._scopes), arg.attr,
                                       kind))

    def _note_assign(self, assign):
        """name = <env read> inside the current scope (E01 dataflow)."""
        if not _contains(assign.value, self._is_env_read):
            return
        scope_node = self._scopes[-1].node
        names = self._env_locals.setdefault(id(scope_node), set())
        for tgt in assign.targets:
            if isinstance(tgt, ast.Name):
                names.add(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for e in tgt.elts:
                    if isinstance(e, ast.Name):
                        names.add(e.id)

    # -------------------------------------------------- hot resolution
    def _resolve_hot(self):
        by_node = {id(s.node): s for s in self._all_scopes}
        for scopes, target, kind in self._hot_requests:
            if isinstance(target, ast.AST):        # a lambda literal
                sc = by_node.get(id(target))
                if sc is not None and sc.hot is None:
                    sc.hot = kind
                continue
            # look the name up innermost-first in the recorded stack
            for s in reversed(scopes):
                fn = s.defs.get(target)
                if fn is not None:
                    sc = by_node.get(id(fn))
                    if sc is not None and sc.hot is None:
                        sc.hot = kind
                    break

    def _hot_kind(self, node):
        """The hot kind of `node`'s scope chain (innermost wins;
        nested defs inherit)."""
        for s in reversed(self._node_scope.get(id(node), ())):
            if s.hot:
                return s.hot
        return None

    def _enclosing_function(self, node):
        for s in reversed(self._node_scope.get(id(node), ())):
            if isinstance(s.node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                return s
        return None

    # --------------------------------------------------- second pass
    def _second_pass(self):
        in_try = []      # stack depth bookkeeping done via parent map
        parents = {}
        for n in ast.walk(self.tree):
            for c in ast.iter_child_nodes(n):
                parents[id(c)] = n
        self._parents = parents

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                self._check_E01(node)
                self._check_E02(node)
                self._check_E03(node)
                self._check_E05(node)
                self._check_E06(node)
            elif isinstance(node, ast.ExceptHandler):
                self._check_E04(node)

    def _ancestors(self, node):
        n = self._parents.get(id(node))
        while n is not None:
            yield n
            n = self._parents.get(id(n))

    # ---------------------------------------------------------- E01
    def _is_env_read(self, n):
        if isinstance(n, ast.Subscript):
            d = _dotted(n.value)
            return d is not None and (
                d.split(".")[-1] == "environ"
                and (len(d.split(".")) == 1 and d in self.environ_names
                     or d.split(".")[0] in self.os_names
                     or d.endswith(".environ")))
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            if d is None:
                return False
            parts = d.split(".")
            if parts[-1] == "getenv":
                return (len(parts) == 1 and d in self.getenv_names) \
                    or parts[0] in self.os_names or len(parts) > 1
            if parts[-1] == "get" and len(parts) >= 2 \
                    and parts[-2] == "environ":
                return True
            if parts[-1] == "get" and parts[0] in self.environ_names \
                    and len(parts) == 2:
                return True
        if isinstance(n, ast.Name):
            return n.id in self.environ_names
        return False

    def _check_E01(self, call):
        if self.is_env_module:
            return
        if not isinstance(call.func, ast.Name) \
                or call.func.id not in ("int", "float"):
            return
        direct = any(_contains(a, self._is_env_read) for a in call.args)
        viaflow = False
        if not direct:
            # local dataflow: int(x) where x was assigned from an env
            # read in the same scope (or the module scope)
            candidates = set()
            for s in self._node_scope.get(id(call), ()):
                candidates |= self._env_locals.get(id(s.node), set())
            viaflow = any(isinstance(a, ast.Name) and a.id in candidates
                          for a in call.args)
        if direct or viaflow:
            self._emit("MXTPU-E01", call,
                       "numeric env parse bypasses mxnet_tpu._env "
                       "(strtol parity + one-warning fallback)")

    # ---------------------------------------------------------- E02
    def _check_E02(self, call):
        kind = self._hot_kind(call)
        if kind is None:
            return
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr in _HOST_SYNC_ATTRS and not call.args:
                self._emit("MXTPU-E02", call,
                           f".{f.attr}() host sync inside "
                           f"{'an engine task' if kind == 'engine_task' else 'traced code'}")
                return
            d = _dotted(f)
            if d and f.attr in ("asarray", "array"):
                head = d.split(".")[0]
                leaf_mod = d.split(".")[-2] if len(d.split(".")) > 1 \
                    else head
                if head in self.np_names or leaf_mod in _NUMPY_NAMES:
                    self._emit("MXTPU-E02", call,
                               f"host-numpy {d}() materialises a device "
                               f"value inside "
                               f"{'an engine task' if kind == 'engine_task' else 'traced code'}")
                    return
            if d and d.split(".")[-1] == "device_get" \
                    and d.split(".")[0] in (self.jax_names or {"jax"}):
                self._emit("MXTPU-E02", call,
                           "jax.device_get host sync in hot path")

    # ---------------------------------------------------------- E03
    def _check_E03(self, call):
        if self.is_registry_module:
            return
        f = call.func
        if isinstance(f, ast.Name) and f.id in self.registry_classes:
            self._emit("MXTPU-E03", call,
                       f"{f.id}(...) bypasses the metrics_registry memo "
                       f"(forks the series from its registry twin)")
        elif isinstance(f, ast.Attribute) \
                and f.attr in ("Counter", "Gauge", "Histogram"):
            d = _dotted(f)
            if d and (d.split(".")[0] in self.registry_mods
                      or ".metrics_registry." in "." + d + "."):
                self._emit("MXTPU-E03", call,
                           f"{d}(...) bypasses the metrics_registry memo")

    # ---------------------------------------------------------- E04
    def _check_E04(self, handler):
        applies = (self.base in _E04_MODULES or self.in_serve
                   or self._hot_kind(handler) == "engine_task")
        if not applies:
            return
        t = handler.type
        catches_base = t is None or (
            isinstance(t, ast.Name) and t.id == "BaseException") or (
            isinstance(t, ast.Tuple) and any(
                isinstance(e, ast.Name) and e.id == "BaseException"
                for e in t.elts))
        if not catches_base:
            return
        for n in ast.walk(ast.Module(body=handler.body,
                                     type_ignores=[])):
            if isinstance(n, ast.Raise):
                return
            if isinstance(n, ast.Call):
                d = _dotted(n.func)
                leaf = d.split(".")[-1] if d else ""
                if "reraise" in leaf:
                    return        # the PR 7 parity helper re-raises
                if leaf in ("set_exception", "_set_exc"):
                    return        # stored into a future — the waiter
                                  # re-raises it; nothing is swallowed
        # an EARLIER sibling handler that re-raises KeyboardInterrupt/
        # SystemExit already lets cancellation escape this try
        parent = self._parents.get(id(handler))
        if isinstance(parent, ast.Try):
            for sib in parent.handlers:
                if sib is handler:
                    break
                names = {e.id for e in ast.walk(sib.type or ast.Pass())
                         if isinstance(e, ast.Name)}
                if names & {"KeyboardInterrupt", "SystemExit"} and any(
                        isinstance(n, ast.Raise) for b in sib.body
                        for n in ast.walk(b)):
                    return
        self._emit("MXTPU-E04", handler,
                   "handler catches BaseException (cancellation/"
                   "preemption) and never re-raises")

    # ---------------------------------------------------------- E05
    def _check_E05(self, call):
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "check"):
            return
        if not (call.args and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)
                and "." in call.args[0].value):
            return
        point = call.args[0].value
        # lexically inside a try with handlers?
        for anc in self._ancestors(call):
            if isinstance(anc, ast.Try) and anc.handlers:
                return
        # enclosing function referenced in a retry/deadline wrapper?
        fn = self._enclosing_function(call)
        if fn is not None and fn.name in self.retried_names:
            return
        self._emit("MXTPU-E05", call,
                   f"fault point {point!r} fired with no enclosing try "
                   f"and no retry/deadline wrapper in sight — a fault "
                   f"here has no degradation path")

    # ---------------------------------------------------------- E06
    def _check_E06(self, call):
        if self._hot_kind(call) != "traced":
            return
        d = _dotted(call.func)
        if d is None:
            return
        parts = d.split(".")
        head, leaf = parts[0], parts[-1]
        bad = None
        if head in self.time_names and leaf in _TIME_FNS:
            bad = f"{d}() wall clock"
        elif head in self.datetime_names and leaf in _DATETIME_FNS:
            bad = f"{d}() wall clock"
        elif head in self.random_names and len(parts) == 2:
            bad = f"module-RNG {d}()"
        elif len(parts) >= 3 and head in self.np_names \
                and parts[1] == "random":
            bad = f"global-np-RNG {d}()"
        if bad:
            self._emit("MXTPU-E06", call,
                       f"{bad} inside traced code bakes a trace-time "
                       f"value into the executable (breaks bitwise "
                       f"replay)")


# -------------------------------------------------------------- front end
def package_root():
    """The mxnet_tpu package directory this module ships in."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _relpath(path, root):
    root_parent = os.path.dirname(os.path.abspath(root))
    return os.path.relpath(os.path.abspath(path),
                           root_parent).replace(os.sep, "/")


def lint_source(src, path="<string>", relpath=None):
    """Lint one source string; returns ALL findings (including
    suppressed ones, marked ``suppressed=True``)."""
    return _Linter(src, path, relpath or path).run()


def lint_file(path, root=None):
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    rel = _relpath(path, root) if root else os.path.basename(path)
    return lint_source(src, path, rel)


def lint_tree(root):
    """Lint every ``*.py`` under `root` (skipping __pycache__);
    returns (findings, files_scanned)."""
    findings, scanned = [], 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            scanned += 1
            findings.extend(lint_file(os.path.join(dirpath, fn),
                                      root=root))
    return findings, scanned


def lint_package():
    """Lint the installed mxnet_tpu package itself."""
    return lint_tree(package_root())


# ---------------------------------------------------------------- baseline
def load_baseline(path):
    """The checked-in baseline: {"ast": [entry...], "graph": [entry...]}.
    A missing file is an empty baseline."""
    if not path or not os.path.exists(path):
        return {"ast": [], "graph": []}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    data.setdefault("ast", [])
    data.setdefault("graph", [])
    return data


def apply_baseline(findings, baseline_entries):
    """Split live findings against the baseline. An entry
    {rule, path, scope, snippet, why} suppresses every finding with the
    same fingerprint (marked ``baselined=True``). Returns
    (new_findings, baselined_findings, stale_entries) — stale entries
    matched nothing and should be pruned."""
    index = {}
    for e in baseline_entries:
        index[(e["rule"], e["path"], e.get("scope", ""),
               e.get("snippet", ""))] = e
    used = set()
    new, matched = [], []
    for f in findings:
        if f.suppressed:
            continue
        e = index.get(f.fingerprint)
        if e is not None:
            f.baselined = True
            used.add(id(e))
            matched.append(f)
        else:
            new.append(f)
    stale = [e for e in baseline_entries if id(e) not in used]
    return new, matched, stale
