"""Dependency-engine tests (SURVEY.md §2 #9, §5 race detection): the native
C++ engine and the Python fallback must order ops identically — writes
serialise, reads run concurrently, errors poison dependents."""
import time

import pytest

from mxnet_tpu import engine
from mxnet_tpu.engine import Var, _PyEngine


def _engines():
    out = [_PyEngine(4)]
    try:
        from mxnet_tpu._native import NativeEngine
        out.append(NativeEngine(4))
    except Exception:
        pass
    return out


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_write_read_ordering(eng):
    order = []
    a, b = Var(), Var()

    def op(tag, t):
        def f():
            time.sleep(t)
            order.append(tag)
            return tag
        return f

    eng.push(op("w1", 0.05), write_vars=[a])
    eng.push(op("r1", 0.01), read_vars=[a])
    eng.push(op("r2", 0.01), read_vars=[a])
    eng.push(op("w2", 0.01), write_vars=[a], read_vars=[b])
    eng.wait_for_var(a)
    assert order[0] == "w1" and order[-1] == "w2"
    assert set(order) == {"w1", "r1", "r2", "w2"}


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_error_poisons_dependents(eng):
    v = Var()

    def boom():
        raise RuntimeError("boom")

    fe = eng.push(boom, write_vars=[v])
    fr = eng.push(lambda: 1, read_vars=[v])
    fw = eng.push(lambda: 2, write_vars=[v])
    try:
        eng.wait_for_all()
    except RuntimeError:
        pass  # wait may rethrow the poisoned error (ThreadedEngine::WaitForAll)
    assert fe.exception() is not None
    assert fr.exception() is not None
    assert fw.exception() is not None


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_wait_for_var_reraises_poisoned(eng):
    """WaitForVar rethrows a stored exception (ThreadedEngine parity) even
    when the caller never retained the op's future."""
    v = Var()

    def boom():
        raise RuntimeError("boom")

    eng.push(boom, write_vars=[v])
    with pytest.raises(RuntimeError, match="boom"):
        eng.wait_for_var(v)


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_duplicate_vars_no_deadlock(eng):
    """A repeated write (or read) var in one push must not self-deadlock."""
    v, r = Var(), Var()
    fut = eng.push(lambda: 42, read_vars=[r, r], write_vars=[v, v])
    assert fut.result(timeout=5) == 42
    f2 = eng.push(lambda: 7, write_vars=[v])
    assert f2.result(timeout=5) == 7
    eng.wait_for_all()


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_throughput_many_ops(eng):
    vs = [Var() for _ in range(50)]
    futs = [eng.push(lambda i=i: i, write_vars=[vs[i % 50]])
            for i in range(1000)]
    eng.wait_for_all()
    assert sum(f.result() for f in futs) == sum(range(1000))


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_concurrent_reads_overlap(eng):
    """Two readers of the same var must run concurrently (wall-clock)."""
    v = Var()
    eng.push(lambda: time.sleep(0.01), write_vars=[v])
    t0 = time.time()
    f1 = eng.push(lambda: time.sleep(0.2), read_vars=[v])
    f2 = eng.push(lambda: time.sleep(0.2), read_vars=[v])
    eng.wait_for_all()
    elapsed = time.time() - t0
    assert elapsed < 0.38, elapsed  # serial would be >= 0.4


def test_facade_push_wait():
    v = Var()
    fut = engine.push(lambda: 42, write_vars=[v])
    engine.wait_for_var(v)
    assert fut.result() == 42
    engine.wait_for_all()


def test_native_engine_loads():
    """The native engine must actually build+load in this environment."""
    assert engine.native_engine_loaded()


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_wait_for_var_raises_failed_reader(eng):
    """A failed READER's error also surfaces from wait_for_var — both
    engines share the per-var future bookkeeping."""
    v = Var()
    eng.push(lambda: 1, write_vars=[v])

    def boom():
        raise RuntimeError("reader-boom")

    eng.push(boom, read_vars=[v])
    with pytest.raises(RuntimeError, match="reader-boom"):
        eng.wait_for_var(v)


# ---------------------- debug mode: race / deadlock detection (§5) ----------
def _native():
    try:
        from mxnet_tpu._native import NativeEngine
        return NativeEngine(4)
    except Exception as e:  # no g++ / build failure: degrade like _engines()
        pytest.skip(f"native engine unavailable: {e!r}")


def test_debug_write_write_hazard_detected():
    """A bypass-push (simulated scheduler bug) makes two writers run on
    one var concurrently; the detector must name the hazard."""
    eng = _native()
    eng.set_debug(True)
    v = Var()
    import threading
    gate = threading.Event()
    eng.push(gate.wait, write_vars=[v])          # legit writer, running
    time.sleep(0.05)
    # buggy 2nd writer, held running on the same gate so both writers are
    # demonstrably concurrent when the detector scans
    eng._debug_bypass_push(gate.wait, write_vars=[v])
    time.sleep(0.05)
    assert eng.debug_check() == 1
    assert "write-write hazard" in eng.last_error()
    gate.set()
    eng.wait_for_all()
    eng.clear_error()


def test_debug_read_write_hazard_detected():
    eng = _native()
    eng.set_debug(True)
    v = Var()
    import threading
    gate = threading.Event()
    eng.push(gate.wait, read_vars=[v])           # legit reader, running
    time.sleep(0.05)
    eng._debug_bypass_push(gate.wait, write_vars=[v])  # buggy writer, held
    time.sleep(0.05)
    assert eng.debug_check() == 1
    assert "read-write hazard" in eng.last_error()
    gate.set()
    eng.wait_for_all()


def test_debug_self_dependency_deadlock_detected():
    """An op whose reads and writes overlap is a self-cycle: debug mode
    reports the deadlock and drops the read dep so the op still runs
    (the Python binding dedups, so push raw through the C ABI)."""
    eng = _native()
    eng.set_debug(True)
    v = Var()
    ran = []
    fut = eng._debug_push_raw(lambda: ran.append(1),
                              read_vars=[v], write_vars=[v])
    fut.result(timeout=5)          # stays live because the dep was dropped
    assert ran == [1]
    assert "deadlock" in eng.last_error()
    assert "self-dependency" in eng.last_error()


def test_debug_stall_watchdog():
    """wait_for_all_timeout reports instead of hanging when an op wedges."""
    eng = _native()
    eng.set_debug(True)
    import threading
    gate = threading.Event()
    eng.push(gate.wait, write_vars=[Var()])
    assert eng.wait_for_all_timeout(150) == 1
    assert "stall" in eng.last_error()
    gate.set()
    eng.wait_for_all()
    assert eng.wait_for_all_timeout(1000) == 0


def test_debug_clean_run_no_hazard():
    """Normal dependency-respecting traffic must NOT trip the detector."""
    eng = _native()
    eng.set_debug(True)
    vs = [Var() for _ in range(4)]
    for i in range(50):
        eng.push(lambda: None, read_vars=[vs[i % 4]],
                 write_vars=[vs[(i + 1) % 4]])
    eng.wait_for_all()
    assert eng.debug_check() == 0, eng.last_error()
    assert eng.last_error() == ""


def test_debug_facade_and_env(monkeypatch):
    """The engine.py facade exposes the detector; _PyEngine honors
    MXTPU_ENGINE_DEBUG and detects self-deps too."""
    monkeypatch.setenv("MXTPU_ENGINE_DEBUG", "1")
    eng = _PyEngine(2)
    assert eng.debug_enabled()
    v = Var()
    eng.push(lambda: None, read_vars=[v], write_vars=[v]).result()
    assert eng.debug_check() == 1
    assert "deadlock" in eng.last_error()
    eng.clear_error()
    assert eng.debug_check() == 0


def test_debug_detector_clean_under_concurrent_load():
    """Satellite (ISSUE 3): dependency-respecting traffic pushed from
    MANY threads at once must not trip the race detector — false
    positives under concurrency would make debug mode useless on real
    pipelines."""
    eng = _native()
    eng.set_debug(True)
    import threading
    vs = [Var() for _ in range(8)]
    stop = threading.Barrier(4)

    def pusher(tid):
        stop.wait()
        for i in range(100):
            eng.push(lambda: None,
                     read_vars=[vs[(tid + i) % 8]],
                     write_vars=[vs[(tid + i + 1) % 8]])

    threads = [threading.Thread(target=pusher, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.wait_for_all()
    assert eng.debug_check() == 0, eng.last_error()
    assert eng.last_error() == ""


def test_debug_detector_finds_hazard_amid_concurrent_load():
    """The detector must still catch a real hazard while legitimate
    concurrent traffic is in flight (no lost signal under load)."""
    eng = _native()
    eng.set_debug(True)
    import threading
    vs = [Var() for _ in range(4)]
    v_bug = Var()
    gate = threading.Event()
    done = threading.Event()

    def legit():
        for i in range(50):
            eng.push(lambda: None, read_vars=[vs[i % 4]],
                     write_vars=[vs[(i + 1) % 4]])
        done.set()

    t = threading.Thread(target=legit)
    t.start()
    eng.push(gate.wait, write_vars=[v_bug])          # legit writer, held
    time.sleep(0.05)
    eng._debug_bypass_push(gate.wait, write_vars=[v_bug])  # buggy writer
    time.sleep(0.05)
    assert eng.debug_check() == 1
    assert "write-write hazard" in eng.last_error()
    gate.set()
    done.wait(5)
    t.join()
    eng.wait_for_all()
    eng.clear_error()


def test_file_vars_order_save_load_and_recordio(tmp_path):
    """NDArray save/load and recordio writes route through per-file engine
    vars: async write then read is race-free."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, recordio
    f = str(tmp_path / "t.npz")
    a = nd.array(np.arange(6, dtype=np.float32))
    nd.save(f, [a])                  # async write
    out = nd.load(f)                 # waits on the file var
    np.testing.assert_allclose(out[0].asnumpy(), a.asnumpy())

    rec = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(rec, "w")
    payloads = [bytes([i]) * (7 * i + 1) for i in range(20)]
    offsets = []
    for p in payloads:
        offsets.append(w.tell())     # logical offset, sync with framing
        w.write(p)                   # async append
    w.close()                        # drains the file var
    r = recordio.MXRecordIO(rec, "r")
    got = []
    while True:
        item = r.read()
        if item is None:
            break
        got.append(item)
    assert got == payloads
    # offsets must match the real framing (idx sidecar correctness)
    import struct as st
    blob = open(rec, "rb").read()
    for off, p in zip(offsets, payloads):
        magic, lrec = st.unpack("<II", blob[off:off + 8])
        assert magic == 0xced7230a and (lrec & ((1 << 29) - 1)) == len(p)


# ---------------------- QoS: priorities / groups / queues (ISSUE 7) --------
def _engine_kinds():
    kinds = ["py"]
    try:
        from mxnet_tpu._native import NativeEngine  # noqa: F401
        kinds.append("native")
    except Exception:
        pass
    return kinds


def _make_one_worker_engine(kind, aging_ms=100):
    """Fresh 1-worker engine per TEST (not per collection): shared
    engines leak worker threads for the session and let one test's
    wedged tasks poison the next (order-dependent flakes)."""
    if kind == "py":
        return _PyEngine(1, aging_ms=aging_ms)
    from mxnet_tpu._native import NativeEngine
    eng = NativeEngine(1)
    eng.set_aging_ms(aging_ms)
    return eng


@pytest.mark.parametrize("kind", _engine_kinds())
def test_priority_preempts_queued_background(kind):
    """A high-priority push dispatches before ALL queued background work,
    even when pushed last (1 worker -> fully deterministic order)."""
    import threading
    eng = _make_one_worker_engine(kind)
    try:
        order = []
        gate = threading.Event()
        eng.push(gate.wait)                   # hold the only worker
        time.sleep(0.02)                      # let it start
        for i in range(6):
            eng.push(lambda i=i: order.append(("bg", i)), priority=2)
        eng.push(lambda: order.append(("hi", 0)), priority=0)
        gate.set()
        eng.wait_for_all()
        assert order[0] == ("hi", 0), order
        # background work still ran, FIFO within its class
        assert [x for x in order if x[0] == "bg"] == [("bg", i)
                                                      for i in range(6)]
    finally:
        eng.close()


@pytest.mark.parametrize("kind", _engine_kinds())
def test_aging_prevents_starvation(kind):
    """A background task that has waited past the aging ladder beats
    FRESH normal-class work (promotion), while the native high class
    still wins its ties — aged background cannot add latency to a
    decode turn, only to same-or-lower classes."""
    import threading
    eng = _make_one_worker_engine(kind, aging_ms=40)
    try:
        order = []
        gate = threading.Event()
        eng.push(gate.wait, priority=1)       # hold the only worker
        time.sleep(0.02)
        eng.push(lambda: order.append("bg-aged"), priority=2)
        time.sleep(0.25)                      # ages past class distance
        eng.push(lambda: order.append("norm"), priority=1)
        eng.push(lambda: order.append("hi"), priority=0)
        gate.set()
        eng.wait_for_all()
        # high first (native class wins ties), then the aged background
        # beats the fresh normal task
        assert order == ["hi", "bg-aged", "norm"], order
    finally:
        eng.close()


def test_task_group_cancel_skips_queued_poisons_nothing():
    """TaskGroup.cancel: queued-not-started members never run, their
    futures resolve to engine.CANCELLED in dependency order, the var
    stays usable (nothing poisoned), and nothing lands in any failure
    report or trips the race detector."""
    import threading
    engine.set_debug(True)
    engine.clear_error()
    base_failures = len(engine.failures())
    v = Var()
    gate = threading.Event()
    started = threading.Event()
    ran = []
    g = engine.TaskGroup("test.cancel")

    def inflight_fn():
        started.set()
        gate.wait(5)
        ran.append("inflight")

    inflight = g.push(inflight_fn, write_vars=[v])
    assert started.wait(5)                    # genuinely in flight
    queued = [g.push(lambda i=i: ran.append(i), write_vars=[v])
              for i in range(4)]
    n = g.cancel()
    assert n == 4
    gate.set()
    assert g.drain(timeout=10)
    assert inflight.result(timeout=5) is not None or True  # completed
    for f in queued:
        assert engine.skipped(f.result(timeout=5))
        assert f.result(timeout=5) is engine.CANCELLED
    assert ran == ["inflight"]                # in-flight drained, rest skipped
    # var NOT poisoned: a later writer runs fine
    assert engine.push(lambda: 7, write_vars=[v]).result(timeout=5) == 7
    # no failures recorded, race detector quiet, group fully drained
    assert len(engine.failures()) == base_failures
    assert engine.debug_check() == 0, engine.last_error()
    assert g.live() == 0
    engine.set_debug(False)


def test_task_group_leak_free_gauge():
    """active_groups() returns to zero once a group's tasks settle."""
    g = engine.TaskGroup("test.leak")
    assert engine.active_groups() == 0 or g.live() == 0
    f = g.push(lambda: 1)
    f.result(timeout=5)
    assert g.drain(timeout=5)
    assert g.live() == 0
    assert engine.active_groups() == 0


def test_bounded_queue_reject_policy():
    """Over-limit background pushes raise EngineQueueFull and count into
    engine_queue_rejections{class=background}; high-water gauge moves."""
    import threading
    from mxnet_tpu.observability import registry
    rej = registry().counter("engine_queue_rejections",
                             **{"class": "background"})
    base = rej.value
    gate = threading.Event()
    v = Var()
    # the gate task runs immediately (leaves the queue); the dep-blocked
    # tasks below are the deterministic queued-not-started population
    engine.push(gate.wait, write_vars=[v])
    time.sleep(0.02)
    prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 2, "reject")
    try:
        f1 = engine.push(lambda: 1, read_vars=[v],
                         priority=engine.PRIORITY_BACKGROUND)
        f2 = engine.push(lambda: 2, read_vars=[v],
                         priority=engine.PRIORITY_BACKGROUND)
        with pytest.raises(engine.EngineQueueFull):
            engine.push(lambda: 3, read_vars=[v],
                        priority=engine.PRIORITY_BACKGROUND)
        assert rej.value == base + 1
    finally:
        engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
        gate.set()
    assert f1.result(timeout=5) == 1 and f2.result(timeout=5) == 2
    engine.wait_for_all()
    hw = registry().gauge("engine_queue_high_water",
                          **{"class": "background"})
    assert (hw.value or 0) >= 2


def test_bounded_queue_shed_oldest_policy():
    """shed_oldest: the class's oldest queued task is cancelled to make
    room — its future resolves to engine.CANCELLED, the newcomer runs."""
    import threading
    gate = threading.Event()
    v = Var()
    engine.push(gate.wait, write_vars=[v])
    time.sleep(0.02)
    prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 2,
                                  "shed_oldest")
    try:
        oldest = engine.push(lambda: "a", read_vars=[v],
                             priority=engine.PRIORITY_BACKGROUND)
        f2 = engine.push(lambda: "b", read_vars=[v],
                         priority=engine.PRIORITY_BACKGROUND)
        f3 = engine.push(lambda: "c", read_vars=[v],
                         priority=engine.PRIORITY_BACKGROUND)
    finally:
        engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
        gate.set()
    assert oldest.result(timeout=5) is engine.CANCELLED
    assert f2.result(timeout=5) == "b"
    assert f3.result(timeout=5) == "c"
    engine.wait_for_all()


def test_bounded_queue_block_policy():
    """block: an over-limit push waits for the class to drain, then
    proceeds (no rejection, no shed)."""
    import threading
    gate = threading.Event()
    v = Var()
    engine.push(gate.wait, write_vars=[v])
    time.sleep(0.02)
    prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 1, "block")
    done = []
    try:
        engine.push(lambda: done.append(1), read_vars=[v],
                    priority=engine.PRIORITY_BACKGROUND)

        def over_limit():
            f = engine.push(lambda: done.append(2), read_vars=[v],
                            priority=engine.PRIORITY_BACKGROUND)
            f.result(timeout=10)

        t = threading.Thread(target=over_limit)
        t.start()
        time.sleep(0.1)
        assert not done and t.is_alive()      # blocked at admission
        gate.set()
        t.join(timeout=10)
        assert not t.is_alive()
    finally:
        engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
        gate.set()
    engine.wait_for_all()
    assert sorted(done) == [1, 2]


def test_deadline_expires_queued_task_without_poisoning():
    """A task whose deadline elapses before it starts is skipped: future
    resolves to engine.EXPIRED, the var stays clean, counter moves."""
    import threading
    from mxnet_tpu.observability import registry
    exp = registry().counter("engine_deadline_expired")
    base = exp.value
    gate = threading.Event()
    v = Var()
    engine.push(gate.wait, write_vars=[v])
    fut = engine.push(lambda: "ran", write_vars=[v], deadline_ms=30)
    time.sleep(0.12)
    gate.set()
    assert fut.result(timeout=5) is engine.EXPIRED
    assert exp.value == base + 1
    assert engine.push(lambda: 9, write_vars=[v]).result(timeout=5) == 9
    engine.wait_for_all()


def test_inline_future_records_failure_like_an_engine_task():
    """Regression (ISSUE 7 review): the reject-policy inline fallback
    must not lose the sticky failure report — a fire-and-forget caller
    (async save whose future nobody waits) still sees the error in
    engine.failures() / engine_task_failures."""
    from mxnet_tpu.observability import registry
    cnt = registry().counter("engine_task_failures")
    base_n, base_c = len(engine.failures()), cnt.value

    f = engine.inline_future(lambda: 1 / 0, site="test.inline_save")
    assert isinstance(f.exception(), ZeroDivisionError)
    rep = engine.failures()
    assert len(rep) == base_n + 1 and rep[-1]["site"] == "test.inline_save"
    assert cnt.value == base_c + 1
    # the success path records nothing
    assert engine.inline_future(lambda: 7).result() == 7
    assert len(engine.failures()) == base_n + 1
    engine.clear_failures()


def test_group_cancel_racing_push_keeps_admission_accounting():
    """Regression: group.cancel() racing push() must not corrupt the
    bounded-queue accounting. A record joins its group only AFTER
    admission, so a concurrent cancel can never decrement a queued count
    that was never incremented (which used to drive the count negative —
    or over-admit — under a full reject-policy class)."""
    import threading
    pri = engine.PRIORITY_BACKGROUND
    prev = engine.set_queue_limit(pri, 2, "reject")
    g = engine.TaskGroup("race")
    stop = threading.Event()

    def pusher():
        while not stop.is_set():
            try:
                g.push(lambda: None, priority=pri)
            except engine.EngineQueueFull:
                pass

    threads = [threading.Thread(target=pusher) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        for _ in range(200):
            g.cancel()
        time.sleep(0.05)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        g.cancel_and_drain(timeout=10)
        engine.set_queue_limit(pri, *prev)
    engine.wait_for_all()
    assert engine._queued_count[pri] == 0
    # the class must still admit normally (no phantom occupants either)
    assert engine.push(lambda: 7, priority=pri).result(timeout=5) == 7
    assert engine.active_groups() == 0


def test_shed_bookkeeping_stays_bounded_behind_pinned_head():
    """Regression: under shed_oldest, a head record pinned queued by a
    slow dependency must not let settled records behind it accumulate in
    the shed deque without bound — compaction keeps it O(limit)."""
    import threading
    pri = engine.PRIORITY_BACKGROUND
    gate = threading.Event()
    v = Var()
    engine.push(gate.wait, write_vars=[v])
    time.sleep(0.02)
    limit = 8
    prev = engine.set_queue_limit(pri, limit, "shed_oldest")
    try:
        head = engine.push(lambda: "head", read_vars=[v], priority=pri)
        for _ in range(200):   # each settles while the head stays queued
            engine.push(lambda: None, priority=pri).result(timeout=5)
        assert len(engine._queued_records[pri]) <= 4 * limit + 16
    finally:
        engine.set_queue_limit(pri, *prev)
        gate.set()
    assert head.result(timeout=5) == "head"
    engine.wait_for_all()


@pytest.mark.parametrize("eng", _engines(), ids=lambda e: type(e).__name__)
def test_instance_failures_parity(eng):
    """Satellite (ISSUE 7): both engine implementations keep the same
    sticky per-instance failure report — root causes only, dependency
    re-raises excluded."""
    eng.clear_failures()
    v = Var()

    def boom():
        raise RuntimeError("qos-boom")

    eng.push(boom, write_vars=[v])
    dep = eng.push(lambda: 1, read_vars=[v])   # poisoned dependent
    try:
        eng.wait_for_all()
    except RuntimeError:
        pass
    assert dep.exception() is not None
    fails = eng.failures()
    assert len(fails) == 1, fails              # root cause ONLY
    assert "qos-boom" in fails[0]["error"]
    assert fails[0]["site"]
    eng.clear_failures()
    assert eng.failures() == []


def test_priority_inversion_postmortem_and_aging_resolution(tmp_path):
    """Satellite (ISSUE 7): wait_for_all_timeout under a priority-inverted
    queue (background work wedging the workers ahead of queued high-
    priority tasks) -> the watchdog post-mortem names the inversion via
    pending_report (class + overdue), and once the wedge releases, aging/
    priority dispatch runs the high task BEFORE the queued background
    backlog — the regression this test pins."""
    import json
    import threading
    from mxnet_tpu.fault.watchdog import StepWatchdog
    order = []
    gate = threading.Event()
    nw = engine.num_workers()
    wedge_group = engine.TaskGroup("test.wedge")
    for _ in range(nw):                        # wedge EVERY worker
        wedge_group.push(gate.wait, priority=engine.PRIORITY_BACKGROUND)
    time.sleep(0.05)
    for i in range(6):                         # queued background backlog
        wedge_group.push(lambda i=i: order.append(("bg", i)),
                         priority=engine.PRIORITY_BACKGROUND)
    hi = engine.push(lambda: order.append(("hi", 0)),
                     priority=engine.PRIORITY_HIGH, deadline_ms=60_000)
    # the queue is inverted NOW: high work queued behind a background wedge
    assert engine.wait_for_all_timeout(150) == 1
    wd = StepWatchdog(timeout_ms=100, snapshot_dir=str(tmp_path))
    path = wd.dump_snapshot(step=7, reason="priority-inverted queue")
    snap = json.load(open(path))
    pend = snap["engine_pending"]
    assert any(p["class"] == "high" and p["state"] == "queued"
               for p in pend), pend
    assert any(p["class"] == "background" and p["state"] == "running"
               for p in pend), pend
    # release the wedge: the high task completes and the engine drains
    # (with several workers the exact interleave is concurrent, so the
    # ORDER pin runs on a 1-worker engine below)
    gate.set()
    hi.result(timeout=10)
    engine.wait_for_all()
    engine.clear_error()
    assert ("hi", 0) in order and len(order) == 7
    assert wedge_group.drain(timeout=10)

    # deterministic resolution pin (1 worker): after the same wedge
    # shape, priority dispatch runs the queued high task FIRST no matter
    # how stale the background backlog (promotion floors at the high
    # class), while the aged background still jumps fresh normal work —
    # "aging resolves the inversion without unbounding decode latency"
    eng = _PyEngine(1, aging_ms=100)
    try:
        order2 = []
        gate2 = threading.Event()
        eng.push(gate2.wait)
        time.sleep(0.02)
        eng.push(lambda: order2.append("bg-aged"), priority=2)
        time.sleep(0.35)                       # ages past 3 intervals
        eng.push(lambda: order2.append("norm"), priority=1)
        eng.push(lambda: order2.append("hi"), priority=0)
        gate2.set()
        eng.wait_for_all()
        assert order2 == ["hi", "bg-aged", "norm"], order2
    finally:
        eng.close()


def test_malformed_aging_env_keeps_default_on_both_engines(monkeypatch):
    """A malformed MXTPU_ENGINE_AGING_MS keeps the 100ms default on BOTH
    engines instead of silently disabling aging (the native parser used
    atoi, which maps "fast" to 0 = aging off); an explicit "0" still
    disables it."""
    def make_engines():
        out = [_PyEngine(1)]
        try:
            from mxnet_tpu._native import NativeEngine
            out.append(NativeEngine(1))
        except Exception:
            pass
        return out

    # int()-accepted forms the native strtol+endptr parse REJECTS must
    # fall back on the Python side too, or the parity pair runs with
    # different starvation bounds; strtol-accepted leading whitespace
    # must parse on both.
    cases = [("fast", 100), ("0", 0), ("250", 250),
             ("250 ", 100), ("1_0", 100), (" 250", 250),
             (str(2**31), 100)]
    for raw, want in cases:
        monkeypatch.setenv("MXTPU_ENGINE_AGING_MS", raw)
        for eng_i in make_engines():
            try:
                assert eng_i.get_aging_ms() == want, \
                    (raw, type(eng_i).__name__)
            finally:
                eng_i.close()


def test_native_use_after_close_raises_not_segfaults():
    """close() nulls the handle; any later call must raise MXNetError
    instead of handing nullptr to C (a use-after-close used to SIGSEGV)."""
    try:
        from mxnet_tpu._native import NativeEngine
    except Exception:
        pytest.skip("native engine unavailable")
    from mxnet_tpu.base import MXNetError
    eng = NativeEngine(1)
    assert eng.push(lambda: 1).result(timeout=5) == 1
    eng.close()
    with pytest.raises(MXNetError):
        eng.push(lambda: 2)
    with pytest.raises(MXNetError):
        eng.get_aging_ms()
    # close is idempotent and wait_for_all on a closed engine stays a no-op
    eng.close()
    eng.wait_for_all()


def test_inline_future_write_vars_serializes_degraded_writers():
    """Two degraded pushers of the same var (reject-policy fallback) must
    serialize: inline_future(write_vars=) takes the write slot atomically
    BEFORE waiting, so both cannot pass a wait-then-run window and
    interleave (the torn-checkpoint hazard in save_sharded's fallback)."""
    import threading

    v = Var()
    inflight = [0]
    peak = [0]
    lock = threading.Lock()

    def tracked(i):
        with lock:
            inflight[0] += 1
            peak[0] = max(peak[0], inflight[0])
        try:
            time.sleep(0.1)
            return i
        finally:
            with lock:
                inflight[0] -= 1

    futs = [None, None]

    def degraded(i):
        futs[i] = engine.inline_future(lambda: tracked(i), write_vars=[v])

    ts = [threading.Thread(target=degraded, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert sorted(f.result() for f in futs) == [0, 1]
    assert peak[0] == 1, f"degraded writers overlapped (peak={peak[0]})"
    # the var's write slot now holds the last inline future: a queued
    # dependent (or wait_for_var) orders after it and sees no poison
    engine.wait_for_var(v)


def test_push_failure_after_admission_rolls_back_qos_state():
    """An inner-engine push that raises (bad var object) AFTER the facade
    admitted the record must roll the admission back: bounded-queue slots
    are released, the group drains to zero, and pending_report carries no
    phantom queued entry."""
    prev_limit, prev_policy = engine.set_queue_limit(
        engine.PRIORITY_BACKGROUND, 1, "reject")
    g = engine.TaskGroup("test.rollback")
    try:
        for _ in range(3):   # > limit: leaked slots would reject the 2nd
            with pytest.raises(Exception):
                engine.push(lambda: None, read_vars=["not-a-var"],
                            priority=engine.PRIORITY_BACKGROUND, group=g)
        f = engine.push(lambda: 7, priority=engine.PRIORITY_BACKGROUND,
                        group=g)
        assert f.result(timeout=10) == 7
        assert g.drain(timeout=10)
        assert engine.active_groups() == 0
        assert not [p for p in engine.pending_report()
                    if p.get("group") == "test.rollback"]
    finally:
        engine.set_queue_limit(engine.PRIORITY_BACKGROUND,
                               prev_limit, prev_policy)
        engine.wait_for_all()


def test_py_engine_push_after_close_raises():
    """Parity with NativeEngine's use-after-close guard: pushing onto a
    closed _PyEngine must raise, not enqueue onto worker-less ready
    queues where the future silently never settles (a hang)."""
    from mxnet_tpu.base import MXNetError
    eng = _PyEngine(1)
    assert eng.push(lambda: 1).result(timeout=5) == 1
    eng.wait_for_all()
    eng.close()
    with pytest.raises(MXNetError):
        eng.push(lambda: 2)
    eng.close()            # idempotent
    eng.wait_for_all()     # no-op on a drained closed engine


def test_default_engines_never_single_worker():
    """Regression (ISSUE 10): engine tasks frequently BLOCK (gate waits,
    checkpoint IO, prefetch stages) — a default-sized engine on a 1-CPU
    machine must still have enough workers that one blocking task cannot
    wedge every other push. Floor: the _PyEngine default (4)."""
    from mxnet_tpu._native import NativeEngine
    assert engine.num_workers() >= 2
    py = _PyEngine()
    try:
        assert py.workers >= 4
    finally:
        py.close()
    if engine.native_engine_loaded():
        native = NativeEngine()
        try:
            assert native.workers >= 4
        finally:
            native.close()
