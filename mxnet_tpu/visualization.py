"""Network visualization (reference: python/mxnet/visualization.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol_or_block, shape=None, **kwargs):
    """Print a layer table for a Symbol or Gluon Block."""
    from .gluon.block import Block
    if isinstance(symbol_or_block, Block):
        return symbol_or_block.summary()
    sym = symbol_or_block
    nodes = sym._topo()
    shape_of = {}
    if shape:
        arg_shapes, _, aux_shapes = sym.infer_shape(**shape)
        if arg_shapes is not None:
            shape_of = dict(zip(sym.list_arguments(), arg_shapes))
            shape_of.update(zip(sym.list_auxiliary_states(), aux_shapes))
    lines = [f"{'Name':<36}{'Op':<24}{'Shape':<18}{'Inputs':<40}",
             "-" * 118]
    for n in nodes:
        ins = ",".join(i.name for i in n._inputs)
        s = str(shape_of.get(n.name, "")) if n._op is None else ""
        lines.append(f"{n.name:<36}{n._op or 'Variable':<24}{s:<18}{ins:<40}")
    out = "\n".join(lines)
    print(out)
    return out


_NODE_STYLE = {
    None: ("oval", "#8dd3c7"),            # Variable
    "FullyConnected": ("box", "#fb8072"),
    "Convolution": ("box", "#fb8072"),
    "StemConvS2D": ("box", "#fb8072"),
    "BatchNorm": ("box", "#bebada"),
    "LayerNorm": ("box", "#bebada"),
    "Activation": ("box", "#ffffb3"),
    "Pooling": ("box", "#80b1d3"),
    "SoftmaxOutput": ("box", "#fccde5"),
}


class Digraph:
    """Minimal graphviz.Digraph stand-in: accumulates nodes/edges and
    renders DOT source (`.source`, `.save`). The reference returns a
    graphviz Digraph; the package is not available offline, so this carries
    the same DOT output contract (paste into any graphviz renderer)."""

    def __init__(self, title="plot"):
        self.title = title
        self._lines = []

    def node(self, name, label=None, shape="box", fillcolor="white"):
        self._lines.append(
            f'  "{name}" [label="{label or name}", shape={shape}, '
            f'style=filled, fillcolor="{fillcolor}"];')

    def edge(self, src, dst, label=None):
        lab = f' [label="{label}"]' if label else ""
        self._lines.append(f'  "{src}" -> "{dst}"{lab};')

    @property
    def source(self):
        body = "\n".join(self._lines)
        return f'digraph "{self.title}" {{\nrankdir=BT;\n{body}\n}}'

    def save(self, filename):
        with open(filename, "w") as f:
            f.write(self.source)
        return filename

    def __str__(self):
        return self.source


def plot_network(symbol, title="plot", shape=None, node_attrs=None,
                 hide_weights=True, **kwargs):
    """DOT-format DAG of a Symbol (reference: plot_network returns a
    graphviz Digraph; this returns a Digraph stand-in whose `.source` is
    valid DOT). `hide_weights` folds parameter Variables into their
    consumer node, like the reference."""
    nodes = symbol._topo()
    g = Digraph(title)
    hidden = set()
    if hide_weights:
        for n in nodes:
            if n._op is None and (n.name.endswith(("_weight", "_bias",
                                                   "_gamma", "_beta",
                                                   "_moving_mean",
                                                   "_moving_var"))):
                hidden.add(id(n))
    for n in nodes:
        if id(n) in hidden:
            continue
        shape_style, color = _NODE_STYLE.get(n._op, ("box", "#d9d9d9"))
        label = n.name if n._op is None else f"{n._op}\\n{n.name}"
        g.node(n.name, label=label, shape=shape_style, fillcolor=color)
    for n in nodes:
        if id(n) in hidden:
            continue
        for i in n._inputs:
            base, _ = i._resolve_head()
            if id(base) in hidden:
                continue
            g.edge(base.name, n.name)
    return g
