#!/usr/bin/env python
"""Multi-process / multi-host launcher (reference parity: tools/launch.py
+ dmlc_tracker — VERDICT r3 item 6).

Spawns N copies of a training command with the coordinator/rank
environment wired for `mxnet_tpu.kvstore.init_distributed`, streams each
worker's output with a rank prefix, and propagates failures (first
non-zero exit kills the rest).

Usage:
    python tools/launch.py -n 2 python examples/train_mnist.py \
        --kv-store dist --smoke
    python tools/launch.py -n 4 -H hostfile --launcher ssh python train.py

Exported env (both spellings, so either bootstrap path works):
    MXTPU_COORDINATOR=host:port   MXTPU_NUM_WORKERS=N   MXTPU_WORKER_ID=i
    DMLC_PS_ROOT_URI=host  DMLC_PS_ROOT_PORT=port
    DMLC_NUM_WORKER=N      DMLC_WORKER_ID=i   DMLC_ROLE=worker
    MXTPU_RESTART_COUNT=k          (incarnation; bumped by --max-restarts)

``--max-restarts N`` makes the launcher elastic: a crashed worker is
respawned in place (same rank, incarnation incremented) instead of
tearing the job down, until its per-rank budget runs out — the process
half of the fleet recovery drill (tools/fleet_drill.py).

TPU-first design note: upstream's launcher starts a ps-lite tracker plus
scheduler/server/worker roles. Here there are only WORKERS — the XLA
distributed runtime does rendezvous at MXTPU_COORDINATOR (rank 0 binds
it) and the gradient reductions are XLA collectives over ICI/DCN, so no
tracker process exists to launch.
"""
from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
import threading


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(base, coord_host, coord_port, n, rank):
    env = dict(base)
    env.update({
        "MXTPU_COORDINATOR": f"{coord_host}:{coord_port}",
        "MXTPU_NUM_WORKERS": str(n),
        "MXTPU_WORKER_ID": str(rank),
        "DMLC_PS_ROOT_URI": coord_host,
        "DMLC_PS_ROOT_PORT": str(coord_port),
        "DMLC_NUM_WORKER": str(n),
        "DMLC_WORKER_ID": str(rank),
        "DMLC_ROLE": "worker",
    })
    return env


def _stream(prefix, pipe, out):
    for line in iter(pipe.readline, b""):
        out.write(f"{prefix}{line.decode(errors='replace')}")
        out.flush()
    pipe.close()


def _read_hostfile(path, n):
    with open(path) as f:
        hosts = [ln.strip().split()[0] for ln in f
                 if ln.strip() and not ln.startswith("#")]
    if not hosts:
        raise SystemExit(f"hostfile {path} is empty")
    # round-robin over hosts, upstream-style
    return [hosts[i % len(hosts)] for i in range(n)]


def launch(n, command, launcher="local", hostfile=None, env=None,
           max_restarts=0):
    """Spawn the workers; returns the first non-zero exit code (0 if all
    succeed). Importable for tests.

    ``max_restarts`` makes the launcher ELASTIC: a worker that dies with
    a non-zero exit (including a SIGKILL) is respawned in place — same
    command, same rank/coordinator env, ``MXTPU_RESTART_COUNT``
    incremented so the reborn process knows its incarnation (the fleet
    supervisor reads it — fault/fleet.py). Only a worker that exhausts
    its per-rank restart budget propagates failure and tears the job
    down; the surviving workers meanwhile keep running, detect the
    dead peer by heartbeat staleness, and agree on a rollback step, so
    the respawned incarnation rejoins at the agreed checkpoint instead
    of the whole gang restarting (docs/RELIABILITY.md "Fleet
    recovery")."""
    base_env = dict(os.environ if env is None else env)
    port = _free_port()
    hosts = _read_hostfile(hostfile, n) if hostfile else ["127.0.0.1"] * n
    coord_host = hosts[0] if launcher == "ssh" else "127.0.0.1"

    procs = [None] * n
    threads = []
    restarts = [0] * n

    def _spawn(rank):
        wenv = _worker_env(base_env, coord_host, port, n, rank)
        wenv["MXTPU_RESTART_COUNT"] = str(restarts[rank])
        if launcher == "ssh" and hosts[rank] not in ("127.0.0.1",
                                                     "localhost"):
            exports = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in wenv.items()
                if k.startswith(("MXTPU_", "DMLC_", "JAX_", "XLA_",
                                 "PYTHONPATH")))
            remote = f"cd {shlex.quote(os.getcwd())} && {exports} " \
                + " ".join(shlex.quote(c) for c in command)
            p = subprocess.Popen(["ssh", "-o", "BatchMode=yes",
                                  hosts[rank], remote],
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
        else:
            p = subprocess.Popen(command, env=wenv,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
        procs[rank] = p
        t = threading.Thread(target=_stream, args=(f"[worker {rank}] ",
                                                   p.stdout, sys.stdout),
                             daemon=True)
        t.start()
        threads.append(t)

    for rank in range(n):
        _spawn(rank)

    rc = 0
    try:
        # poll until every worker exits cleanly; a non-zero exit is
        # respawned while its restart budget lasts, and propagates
        # (killing the rest) once it is exhausted
        import time
        pending = set(range(n))
        while pending:
            for i in list(pending):
                r = procs[i].poll()
                if r is None:
                    continue
                if r != 0 and restarts[i] < max_restarts:
                    restarts[i] += 1
                    print(f"[launch] worker {i} exited rc={r}; "
                          f"respawning (restart {restarts[i]}/"
                          f"{max_restarts})", file=sys.stderr)
                    _spawn(i)
                    continue
                pending.discard(i)
                if r != 0 and rc == 0:
                    rc = r
                    print(f"[launch] worker {i} exited rc={r}; "
                          "terminating the rest", file=sys.stderr)
                    for j in pending:
                        procs[j].terminate()
            time.sleep(0.2)
    finally:
        for p in procs:
            if p is not None and p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=5)
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job",
        usage="launch.py -n N [-H hostfile] [--launcher local|ssh] "
              "command ...")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("--launcher", choices=("local", "ssh"), default="local")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="respawn a crashed worker in place up to N times "
                         "(MXTPU_RESTART_COUNT incremented) before its "
                         "failure propagates")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    if args.launcher == "ssh" and not args.hostfile:
        ap.error("--launcher ssh needs -H hostfile")
    return launch(args.num_workers, args.command, launcher=args.launcher,
                  hostfile=args.hostfile, max_restarts=args.max_restarts)


if __name__ == "__main__":
    sys.exit(main())
