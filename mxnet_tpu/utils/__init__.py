"""Utility subpackage (reference: python/mxnet/util.py + src/storage/*)."""
from . import memory
from .memory import memory_info, memory_stats

__all__ = ["memory", "memory_info", "memory_stats"]
