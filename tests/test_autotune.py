"""Compile-space autotuner (ISSUE 20): winner store round-trip and
staleness, shape-class keying, the measured search with its guard
stack, and winner application at lowering time.

The load-bearing guarantees pinned here:

  * the winner store survives a process round-trip and REJECTS entries
    recorded under a different jax/jaxlib or shard-plan signature —
    loudly (`tune_stale{reason=}`); a corrupt store degrades to empty
    with `tune_store_corrupt`, never an exception;
  * the search winner is never slower than the measured baseline
    beyond the structural tie band, a seeded HLO-regressing flag and a
    numerics-breaking flag are both rejected by the guards (not by the
    allowlist), and the winner's HLO honours the fusion-gate budget;
  * `mx.set_autotune` applies a persisted winner on first dispatch
    (`tune_applied` counts it), warm dispatches hit the memo without
    recompiling, and outputs match the executable's contract — also
    from a COLD process via `MXTPU_AUTOTUNE` (the fleet path).
"""
import json
import os
import subprocess
import sys
import textwrap
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, tune
from mxnet_tpu.observability import compilex, registry


def _counter(name, **labels):
    return registry().counter(name, **labels).value


# ----------------------------------------------------------- the store
def _entry(executable="toy_exe", platform="cpu", shape_class="abc123",
           **over):
    e = {"executable": executable, "platform": platform,
         "shape_class": shape_class, "plan": None,
         "pallas": {}, "flags": {"xla_cpu_enable_fast_min_max": True},
         "score_ms": 1.0, "baseline_ms": 2.0, "trials": 3}
    e.update(over)
    return e


def test_store_round_trip(tmp_path):
    st = tune.TuneStore(tmp_path)
    key = st.record(_entry())
    assert key == "toy_exe|cpu|abc123"
    st.save()
    assert os.path.exists(os.path.join(tmp_path, "autotune_winners.json"))

    fresh = tune.TuneStore(tmp_path)           # cold read
    got = fresh.lookup("toy_exe", "cpu", "abc123")
    assert got is not None
    assert got["flags"] == {"xla_cpu_enable_fast_min_max": True}
    import jax
    assert got["jax"] == jax.__version__       # stamped on record
    assert fresh.lookup("toy_exe", "cpu", "other") is None
    assert fresh.lookup("toy_exe", "tpu", "abc123") is None


def test_store_stale_jax_version_and_plan_rejected(tmp_path):
    st = tune.TuneStore(tmp_path)
    st.record(_entry(shape_class="aa"))
    st.record(_entry(shape_class="bb", plan="plan-A"))
    st.save()
    # doctor one entry's toolchain stamp the way an upgrade would
    p = os.path.join(tmp_path, "autotune_winners.json")
    data = json.load(open(p))
    data["entries"]["toy_exe|cpu|aa"]["jax"] = "0.0.0"
    json.dump(data, open(p, "w"))

    fresh = tune.TuneStore(tmp_path)
    s0 = _counter("tune_stale", reason="jax_version")
    with pytest.warns(RuntimeWarning, match="stale"):
        assert fresh.lookup("toy_exe", "cpu", "aa") is None
    assert _counter("tune_stale", reason="jax_version") == s0 + 1
    # the warning fires once per key; the counter keeps counting
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert fresh.lookup("toy_exe", "cpu", "aa") is None
    assert _counter("tune_stale", reason="jax_version") == s0 + 2

    p0 = _counter("tune_stale", reason="plan")
    with pytest.warns(RuntimeWarning, match="stale"):
        assert fresh.lookup("toy_exe", "cpu", "bb", plan="plan-B") is None
    assert _counter("tune_stale", reason="plan") == p0 + 1
    # matching plan signature: the entry is served
    assert fresh.lookup("toy_exe", "cpu", "bb", plan="plan-A") is not None


def test_store_corrupt_degrades_loudly(tmp_path):
    p = os.path.join(tmp_path, "autotune_winners.json")
    open(p, "w").write("{ not json")
    c0 = _counter("tune_store_corrupt")
    with pytest.warns(RuntimeWarning, match="unreadable"):
        assert tune.TuneStore(tmp_path).entries() == {}
    assert _counter("tune_store_corrupt") == c0 + 1
    # a future-format store is equally unreadable from this build
    json.dump({"format": 99, "entries": {}}, open(p, "w"))
    with pytest.warns(RuntimeWarning, match="unreadable"):
        assert tune.TuneStore(tmp_path).entries() == {}
    assert _counter("tune_store_corrupt") == c0 + 2


def test_shape_class_keys_on_skeleton_not_values():
    import jax.numpy as jnp
    a = jnp.zeros((4, 8), jnp.float32)
    b = jnp.ones((4, 8), jnp.float32)
    # same skeleton, different values / python scalar values: one class
    # (a decayed lr must NOT fork a new store key)
    assert tune.shape_class((a, 0.1), {}) == tune.shape_class((b, 0.01), {})
    # different shape, dtype, or tree structure: different classes
    assert tune.shape_class((a,), {}) != \
        tune.shape_class((a.reshape(8, 4),), {})
    assert tune.shape_class((a,), {}) != \
        tune.shape_class((a.astype(jnp.bfloat16),), {})
    assert tune.shape_class((a,), {}) != tune.shape_class((a,), {"k": a})


# ---------------------------------------------------------- the search
# the check_fusion captured_step budget row (tools/ is not importable
# from the suite; tests/test_check_fusion.py pins this copy against the
# tool's table)
_CAPTURED_BUDGET = {"fusions": (10, 40), "collective_total": 0,
                    "aliased_inputs": 8}


def _captured_workload():
    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(16, 32).astype(np.float32))
    y = nd.array(rng.randint(0, 8, 16).astype(np.float32))
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    mx.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    net(X)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    step = tr.capture(lambda a, b: lossf(net(a), b).mean())
    step(X, y)
    with tune.capture_workload("captured_step") as caught:
        step(X, y)
    wl = caught["captured_step"]
    wl._anchor = (net, tr, step)
    return wl


def test_search_winner_guards_and_budget(tmp_path):
    """Bounded 3-candidate search on the real captured step: the winner
    is >= baseline within the tie band, the seeded copy-inflating flag
    is rejected by the HLO-regression guard (the allowlist contains it
    — the GUARD keeps it honest), and the winner's HLO holds the
    fusion-gate budget."""
    wl = _captured_workload()
    cands = [
        tune.Candidate("flag:copy_region",
                       flags={"xla_cpu_copy_insertion_use_region_analysis":
                              True}),
        # seeded bad candidate: measured to inflate copies 5 -> 7 on
        # this executable with the pinned toolchain
        tune.Candidate("flag:eigen_off",
                       flags={"xla_cpu_multi_thread_eigen": False}),
    ]
    res = tune.search(wl, candidates=cands, trials=2,
                      budget=_CAPTURED_BUDGET)
    assert res.baseline.rejected is None
    from mxnet_tpu.tune.search import TIE_BAND
    assert res.winner.score_ms <= res.baseline.score_ms * (1.0 + TIE_BAND)
    by_name = {r.candidate.name: r for r in res.candidates}
    assert by_name["flag:eigen_off"].rejected is not None
    assert by_name["flag:eigen_off"].rejected.startswith("hlo_regression")
    # guard 1 held on the winner — the fusion gate would accept it
    assert tune.check_budget(res.winner.hlo, _CAPTURED_BUDGET) == []
    # a persisted winner round-trips through the store
    entry = res.winner_entry()
    if entry is not None:
        st = tune.TuneStore(tmp_path)
        st.record(entry)
        st.save()
        assert tune.TuneStore(tmp_path).lookup(
            "captured_step", res.platform, res.shape_class) is not None


def test_search_rejects_numerics_break_under_bitwise_contract():
    """A flag that changes output bits is rejected when the executable's
    contract is bitwise — regardless of how fast it is."""
    import jax
    import jax.numpy as jnp

    ij = compilex.instrument(
        jax.jit(lambda x, w: jax.nn.log_softmax(jnp.tanh(x @ w))),
        "tune_toy_bitwise")
    rng = np.random.RandomState(3)
    xv = rng.randn(32, 64).astype(np.float32)
    wv = rng.randn(64, 64).astype(np.float32)

    def make_args():
        return (jnp.asarray(xv), jnp.asarray(wv)), {}

    wl = tune.Workload(ij, make_args, contract=("bitwise",))
    res = tune.search(wl, candidates=[
        tune.Candidate("flag:opt0",
                       flags={"xla_backend_optimization_level": 0}),
    ], trials=1)
    by_name = {r.candidate.name: r for r in res.candidates}
    assert by_name["flag:opt0"].rejected is not None
    assert by_name["flag:opt0"].rejected.startswith("numerics[bitwise]")
    assert res.winner.candidate.is_baseline


def test_search_rejects_dead_pallas_override():
    """A Pallas candidate whose override the kernel picker IGNORED is
    measuring the default config under a wrong label: rejected."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import pallas_kernels as pk

    prev = os.environ.get("MXTPU_PALLAS_INTERPRET")
    os.environ["MXTPU_PALLAS_INTERPRET"] = "1"
    try:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(2, 2, 8).astype(np.float32))
        kp = jnp.asarray(rng.randn(5, 16, 2, 8).astype(np.float32))
        vp = jnp.asarray(rng.randn(5, 16, 2, 8).astype(np.float32))
        pt = jnp.asarray(np.array([[1, 2], [3, 0]], np.int32))
        ln = jnp.asarray(np.array([20, 7], np.int32))

        ij = compilex.instrument(
            jax.jit(lambda *a: pk.ragged_paged_attention(*a)),
            "tune_toy_rpa")
        wl = tune.Workload(ij, lambda: ((q, kp, vp, pt, ln), {}),
                           contract=("allclose", 2e-6, 2e-6))
        res = tune.search(wl, candidates=[
            # 12 does not divide psize=16 and is not a multiple of 8:
            # the picker falls back to the default and says so
            tune.Candidate("pallas:dead", pallas={"rpa_block_k": 12}),
            tune.Candidate("pallas:bk8", pallas={"rpa_block_k": 8}),
        ], trials=1)
        by_name = {r.candidate.name: r for r in res.candidates}
        assert by_name["pallas:dead"].rejected == "dead_pallas_override"
        # the VALID block config compiled and was honestly measured
        assert by_name["pallas:bk8"].rejected in (None,) or \
            by_name["pallas:bk8"].rejected.startswith("numerics")
    finally:
        if prev is None:
            os.environ.pop("MXTPU_PALLAS_INTERPRET", None)
        else:
            os.environ["MXTPU_PALLAS_INTERPRET"] = prev


# ----------------------------------------------------------- the apply
def test_set_autotune_applies_winner_without_retrace(tmp_path):
    """A persisted winner is applied on first dispatch (tune_applied),
    warm dispatches hit the per-signature memo (no further compiles),
    outputs match the untuned path bitwise, and disabling restores the
    plain jit route."""
    import jax
    import jax.numpy as jnp

    traces = [0]

    def f(x, w):
        traces[0] += 1
        return jnp.tanh(x @ w)

    ij = compilex.instrument(jax.jit(f), "tune_toy_apply")
    rng = np.random.RandomState(7)
    xv = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    wv = jnp.asarray(rng.randn(16, 16).astype(np.float32))
    want = np.asarray(ij(xv, wv))
    compiles0 = ij._compiles.value

    st = tune.TuneStore(tmp_path)
    st.record(_entry(executable="tune_toy_apply", platform="cpu",
                     shape_class=tune.shape_class((xv, wv), {})))
    st.save()

    a0 = tune.applied_count()
    assert tune.set_autotune(tmp_path) == str(tmp_path)
    try:
        traces[0] = 0
        out = ij(xv, wv)                   # first dispatch: AOT compile
        assert np.array_equal(np.asarray(out), want)
        assert tune.applied_count() == a0 + 1
        assert _counter("tune_applied", executable="tune_toy_apply") == 1
        # a flags-only winner shares the jit's cached trace — the AOT
        # route costs AT MOST one extra trace, here zero
        assert traces[0] <= 1
        compiles1 = ij._compiles.value
        assert compiles1 == compiles0 + 1
        for _ in range(3):                 # warm: memo hit, no retrace
            ij(xv, wv)
        assert traces[0] <= 1
        assert ij._compiles.value == compiles1
        assert tune.applied_count() == a0 + 1
    finally:
        tune.set_autotune(enabled=False)
    assert tune.autotune_dir() is None
    assert np.array_equal(np.asarray(ij(xv, wv)), want)


def test_apply_miss_and_empty_entry_fall_back(tmp_path):
    """No entry for the signature -> plain jit path, zero applications,
    negative-cached so the store is probed once."""
    import jax
    import jax.numpy as jnp

    ij = compilex.instrument(jax.jit(lambda x: x * 2), "tune_toy_miss")
    a0 = tune.applied_count()
    assert tune.set_autotune(tmp_path) is not None
    try:
        x = jnp.arange(4.0)
        assert np.allclose(np.asarray(ij(x)), [0, 2, 4, 6])
        ij(x)
    finally:
        tune.set_autotune(enabled=False)
    assert tune.applied_count() == a0


_WORKER = textwrap.dedent("""
    import json
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import tune
    from mxnet_tpu.observability import compilex, registry

    ij = compilex.instrument(
        jax.jit(lambda x, w: jnp.tanh(x @ w)), "tune_toy_proc")
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 16).astype(np.float32))
    out1 = np.asarray(ij(x, w))
    out2 = np.asarray(ij(x, w))
    print(json.dumps({
        "dir": tune.autotune_dir(),
        "applied": tune.applied_count(),
        "compiles": int(ij._compiles.value),
        "out_equal": bool(np.array_equal(out1, out2)),
        "checksum": float(out1.sum()),
    }))
""")


def test_cross_process_reuse(tmp_path):
    """The fleet path: this process persists a winner; a COLD process
    with MXTPU_AUTOTUNE applies it (tune_applied >= 1, exactly one
    compile) and computes the same numbers as an untuned cold process."""
    import jax.numpy as jnp

    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 16).astype(np.float32))
    st = tune.TuneStore(tmp_path / "tune")
    st.record(_entry(executable="tune_toy_proc", platform="cpu",
                     shape_class=tune.shape_class((x, w), {})))
    st.save()

    def run(autotune):
        script = tmp_path / "worker.py"
        script.write_text(_WORKER)
        env = dict(os.environ)
        repo = os.path.join(os.path.dirname(__file__), "..")
        env["PYTHONPATH"] = os.path.abspath(repo) + os.pathsep + \
            env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["MXTPU_HLO_TELEMETRY"] = "0"
        env.pop("MXTPU_TUNE_DIR", None)
        if autotune:
            env["MXTPU_AUTOTUNE"] = str(tmp_path / "tune")
        else:
            env.pop("MXTPU_AUTOTUNE", None)
        proc = subprocess.run([sys.executable, str(script)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.DEVNULL,
                              env=env, timeout=300)
        assert proc.returncode == 0, proc.stdout.decode(errors="replace")
        line = [l for l in proc.stdout.decode().splitlines()
                if l.strip().startswith("{")][-1]
        return json.loads(line)

    tuned = run(autotune=True)
    assert tuned["dir"] == str(tmp_path / "tune")
    assert tuned["applied"] == 1
    assert tuned["compiles"] == 1          # zero extra retraces/compiles
    assert tuned["out_equal"]

    plain = run(autotune=False)
    assert plain["dir"] is None and plain["applied"] == 0
    # the applied flag set keeps this executable's numerics contract
    assert tuned["checksum"] == plain["checksum"]
