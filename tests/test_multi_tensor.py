"""Fused multi-tensor Trainer path (optimizer/multi_tensor.py): numerical
parity vs the per-param reference path, dispatch-count regression guards,
bucketing, and the engine bulk-size wiring."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, engine, gluon, nd, profiler
from mxnet_tpu.optimizer import multi_tensor

FUSED_OPTS = ["sgd", "nag", "adam", "adamw", "lamb"]


def _data(n=8, d=16, k=4):
    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(n, d).astype(np.float32))
    y = nd.array(rng.randint(0, k, n).astype(np.float32))
    return X, y


def _build(X, layers=3, hidden=16, k=4, seed=0):
    mx.random.seed(seed)
    net = gluon.nn.Sequential()
    for _ in range(layers):
        net.add(gluon.nn.Dense(hidden, activation="relu"))
    net.add(gluon.nn.Dense(k))
    net.initialize(mx.init.Xavier())
    net(X)  # materialise
    return net


def _train(fused, opt, X, y, steps=3, opt_params=None, trainer_kw=None,
           cast=None):
    net = _build(X)
    if cast:
        net.cast(cast)
        X = X.astype(cast)
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), opt,
                       dict({"learning_rate": 0.05}, **(opt_params or {})),
                       fused=fused, **(trainer_kw or {}))
    for _ in range(steps):
        with autograd.record():
            L = lossf(net(X), y).mean()
        L.backward()
        tr.step(X.shape[0])
    return [p.data().asnumpy().astype(np.float32)
            for p in net.collect_params().values()]


def _assert_parity(fused, unfused, rtol=1e-4, atol=1e-7, tag=""):
    for i, (a, b) in enumerate(zip(fused, unfused)):
        np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                   err_msg=f"{tag} param {i}")


# ------------------------------------------------------------ parity suite
@pytest.mark.parametrize("opt", FUSED_OPTS)
def test_fused_parity(opt):
    """Fused step() matches the per-param path to fp32-reassociation
    tolerance (the kernel fuses mul/add chains XLA keeps separate in the
    eager path) for all five fused optimizers."""
    X, y = _data()
    _assert_parity(_train(True, opt, X, y, opt_params={"wd": 0.01}),
                   _train(False, opt, X, y, opt_params={"wd": 0.01}),
                   tag=opt)


@pytest.mark.parametrize("opt", ["sgd", "adam", "lamb"])
def test_fused_parity_multi_precision(opt):
    """bf16 weights + fp32 master copies: the fused kernel applies the
    update on the master and downcasts, like update_multi_precision."""
    X, y = _data()
    kw = {"opt_params": {"multi_precision": True, "momentum": 0.9}
          if opt == "sgd" else {"multi_precision": True},
          "cast": "bfloat16"}
    _assert_parity(_train(True, opt, X, y, **kw),
                   _train(False, opt, X, y, **kw),
                   rtol=2e-2, atol=1e-3, tag=f"{opt}-mp")


def test_fused_skip_nonfinite_and_null_grads():
    """A nan gradient skips the whole update on both paths; grad_req="null"
    params ride along untouched."""
    X, y = _data()
    net = _build(X)
    params = net.collect_params()
    list(params.values())[-1].grad_req = "null"   # sparse-style frozen head
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.5},
                       skip_nonfinite=True)
    assert tr._fused
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        L = lossf(net(X), y).mean()
    L.backward()
    w_before = [p.data().asnumpy() for p in params.values()]
    poisoned = next(p for p in params.values() if p._grad is not None)
    poisoned._grad._rebind(poisoned._grad._data * np.nan)
    tr.step(X.shape[0])
    for a, b in zip(w_before, [p.data().asnumpy() for p in params.values()]):
        np.testing.assert_array_equal(a, b)
    # finite grads do update, with the frozen param still untouched
    with autograd.record():
        L = lossf(net(X), y).mean()
    L.backward()
    tr.step(X.shape[0])
    after = [p.data().asnumpy() for p in params.values()]
    assert any(not np.array_equal(a, b) for a, b in zip(w_before, after))
    np.testing.assert_array_equal(w_before[-1], after[-1])


def test_fused_amp_overflow_skip_parity():
    """Under the fp16 DynamicLossScaler the fused path folds unscale into
    the kernel, skips on overflow, and halves the scale — same protocol
    (and same resulting weights) as the per-param path."""
    X, y = _data()

    def run(fused):
        amp.reset()
        amp.init("float16")
        net = _build(X)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, fused=fused)
        lossf = gluon.loss.SoftmaxCrossEntropyLoss()
        for i in range(3):
            with autograd.record():
                L = amp.scale_loss(lossf(net(X), y).mean())
            L.backward()
            if i == 1:   # poison step 1: must be skipped, scale halved
                p0 = list(net.collect_params().values())[0]
                p0._grad._rebind(p0._grad._data * np.inf)
            tr.step(X.shape[0])
        scale = amp._state["scaler"].loss_scale
        amp.reset()
        return [p.data().asnumpy() for p in
                net.collect_params().values()], scale

    wf, sf = run(True)
    wu, su = run(False)
    assert sf == su
    _assert_parity(wf, wu, tag="amp")


def test_fused_matches_with_per_param_buckets():
    """bulk_size=0 keeps reference 'unbulked' semantics: one param per
    bucket, still numerically identical."""
    X, y = _data()
    prev = engine.set_bulk_size(0)
    try:
        fused = _train(True, "adam", X, y)
    finally:
        engine.set_bulk_size(prev)
    _assert_parity(fused, _train(False, "adam", X, y), tag="bulk0")


# ------------------------------------------------ dispatch regression guard
def test_dispatch_count_50_param_mlp():
    """Acceptance guard: a >=50-parameter model steps in <= 4 device
    dispatches on the fused imperative path, and dumps(reset=True) resets
    the counter."""
    X, y = _data()
    net = _build(X, layers=24)          # 25 Dense layers -> 50 params
    params = net.collect_params()
    assert len(params) >= 50
    tr = gluon.Trainer(params, "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(2):                  # warm the kernel cache
        with autograd.record():
            L = lossf(net(X), y).mean()
        L.backward()
        tr.step(X.shape[0])
    with autograd.record():
        L = lossf(net(X), y).mean()
    L.backward()
    profiler.reset_dispatches()
    tr.step(X.shape[0])
    assert profiler.dispatch_count() <= 4, profiler.dumps()
    assert profiler.jit_cache_stats() == (1, 0)   # warm: pure cache hit
    assert "[dispatch]" in profiler.dumps()
    profiler.dumps(reset=True)
    assert profiler.dispatch_count() == 0
    assert profiler.jit_cache_stats() == (0, 0)


def test_unfused_dispatch_count_scales_with_params():
    """The per-param escape hatch really is O(num_params)."""
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05},
                       fused=False)
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        L = lossf(net(X), y).mean()
    L.backward()
    profiler.reset_dispatches()
    tr.step(X.shape[0])
    assert profiler.dispatch_count() == len(net.collect_params())


# ----------------------------------------------------------- bucketing unit
def test_build_buckets_caps_and_dtype_homogeneity():
    X, _ = _data()
    net = _build(X)
    pairs = [(i, p) for i, p in enumerate(net.collect_params().values())]
    # cap 0: per-param
    assert [len(b) for b in multi_tensor.build_buckets(pairs, 0)] == \
        [1] * len(pairs)
    # huge cap: one bucket (all fp32)
    assert len(multi_tensor.build_buckets(pairs, 1 << 30)) == 1
    # tiny cap: each param alone even though larger than the cap
    assert [len(b) for b in multi_tensor.build_buckets(pairs, 8)] == \
        [1] * len(pairs)
    # dtype change breaks a bucket
    list(pairs[1][1].cast("bfloat16") for _ in range(1))
    bks = multi_tensor.build_buckets(pairs, 1 << 30)
    assert len(bks) == 3   # fp32 | bf16 | fp32 (declaration order kept)


def test_bucket_cache_invalidates_on_bulk_size_change():
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        L = lossf(net(X), y).mean()
    L.backward()
    tr.step(X.shape[0])
    assert len(tr._buckets) == 1
    prev = engine.set_bulk_size(0)
    try:
        with autograd.record():
            L = lossf(net(X), y).mean()
        L.backward()
        tr.step(X.shape[0])
        assert len(tr._buckets) == len(net.collect_params())
    finally:
        engine.set_bulk_size(prev)


# ------------------------------------------------------- engine bulk wiring
def test_set_bulk_size_roundtrip_and_scope():
    base = engine.get_bulk_size()
    assert base > 0                       # fused/bulked by default
    prev = engine.set_bulk_size(12345)
    assert prev == base
    assert engine.get_bulk_size() == 12345
    with engine.bulk(1 << 20):
        assert engine.get_bulk_size() == 1 << 20
    assert engine.get_bulk_size() == 12345
    # reference op-count-scale sizes (set_bulk_size(15) / bulk(15) idiom)
    # mean "bulked at the default byte cap", never a tiny byte cap
    with engine.bulk(15):
        assert engine.get_bulk_size() == engine._DEFAULT_BULK_BYTES
    with engine.bulk(0):
        assert engine.get_bulk_size() == 0    # 0 stays per-param
    assert engine.get_bulk_size() == 12345
    engine.set_bulk_size(15)
    assert engine.get_bulk_size() == engine._DEFAULT_BULK_BYTES
    engine.set_bulk_size(12345)
    engine.set_bulk_size(base)
    assert engine.get_bulk_size() == base


def test_hyperparam_mutation_recompiles():
    """Mutating a trace-time hyperparameter (momentum) mid-run must key a
    fresh fused kernel — the per-param path reads it eagerly every step,
    so a stale cached kernel would silently diverge."""
    X, y = _data()

    def run(fused):
        net = _build(X)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           fused=fused)
        lossf = gluon.loss.SoftmaxCrossEntropyLoss()
        for step in range(4):
            if step == 2:
                # 0.0 also shrinks apply()'s state arity: the kernel must
                # pass the now-untouched momentum slot through (donation
                # safety) while matching the per-param stale-state keep
                tr._optimizer.momentum = 0.0
            with autograd.record():
                L = lossf(net(X), y).mean()
            L.backward()
            tr.step(X.shape[0])
        return [p.data().asnumpy() for p in net.collect_params().values()]

    _assert_parity(run(True), run(False), tag="momentum-mutation")


# ----------------------------------------------------- fallback / coverage
def test_unsupported_optimizer_falls_back():
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "dcasgd",
                       {"learning_rate": 0.05})
    assert not tr._fused                  # aliasing state: per-param path
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        L = lossf(net(X), y).mean()
    L.backward()
    tr.step(X.shape[0])                   # still trains


def test_fused_save_load_states_roundtrip(tmp_path):
    X, y = _data()
    net = _build(X)
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.05})
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        L = lossf(net(X), y).mean()
    L.backward()
    tr.step(X.shape[0])
    f = str(tmp_path / "states.bin")
    tr.save_states(f)
    tr2 = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 0.05})
    tr2.load_states(f)
    assert tr2._optimizer.num_update == tr._optimizer.num_update
    for k, v in tr._updater.states.items():
        for a, b in zip(v, tr2._updater.states[k]):
            np.testing.assert_allclose(np.asarray(a._data),
                                       np.asarray(b._data))


def test_kvstore_allreduce_flat_identity_and_roundtrip():
    """allreduce_flat: identity fast-paths return the inputs untouched;
    the flatten/split programs round-trip shapes exactly."""
    import jax.numpy as jnp
    from mxnet_tpu import kvstore
    kv = kvstore.create("ici")
    arrs = [jnp.ones((3, 4)), jnp.zeros((5,)), jnp.full((2, 2), 7.0)]
    out = kv.allreduce_flat(arrs)
    assert all(a is b for a, b in zip(arrs, out))   # single process: identity
    flatten, split = kvstore.KVStore._build_flat_fns(
        tuple((tuple(a.shape), str(a.dtype)) for a in arrs))
    back = split(flatten(arrs))
    for a, b in zip(arrs, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
