"""mx.nd — the imperative NDArray namespace.

Aggregates the NDArray type, creation ops, tensor/nn/linalg operators and the
random sub-namespace, mirroring the reference `mxnet.ndarray` module surface.
"""
from .ndarray import (NDArray, zeros, ones, full, empty, array, arange,
                      linspace, eye, zeros_like, ones_like, full_like,
                      from_numpy, waitall, _apply, _wrap_apply, _lift)
from .utils import save, load, load_frombuffer
from ..ops.tensor_ops import *          # noqa: F401,F403
from ..ops.nn_ops import *              # noqa: F401,F403
from ..ops.seq_ops import (SequenceMask, SequenceLast,  # noqa: F401
                           SequenceReverse, smooth_l1, softmin, hard_sigmoid)
from ..ops.extra_ops import *           # noqa: F401,F403
from ..optimizer.optimizer import (multi_sgd_update,  # noqa: F401
                                   multi_sgd_mom_update)
from ..ops import tensor_ops as _t
from ..ops import nn_ops as _n
from ..ops import linalg_ops as linalg  # mx.nd.linalg.*
from .. import random                   # mx.nd.random.*

# the star import surfaces the raw jax-level kernels; the imperative
# NDArray namespace wants the recorded wrappers under the reference names
softmax = _n.softmax_nd
log_softmax = _n.log_softmax_nd

from ..ops.compat_ops import *          # noqa: F401,F403  (classic names)

# reference exposes a handful of random samplers at top level too
from ..random import (uniform, normal, randn, randint, multinomial,
                      exponential, gamma, poisson)

sample_multinomial = multinomial

# flat linalg_* spellings (upstream registers la_op under both
# mx.nd.linalg.gemm2 and mx.nd.linalg_gemm2)
from ..ops import linalg_ops as _linalg_mod
for _ln in _linalg_mod.__all__:
    globals()[f"linalg_{_ln}"] = getattr(_linalg_mod, _ln)
del _ln
sample_uniform = uniform
sample_normal = normal
sample_gamma = gamma
sample_exponential = exponential
sample_poisson = poisson
random_uniform = uniform
random_normal = normal
random_gamma = gamma

# custom-op invocation entry (reference: mx.nd.Custom)
from ..operator import Custom

# control-flow operators (reference: mx.nd.contrib.foreach/while_loop/cond)
from . import contrib

# sparse compatibility namespace (densifying — SURVEY §8)
from . import sparse
