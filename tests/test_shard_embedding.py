"""Sharded embedding tables (mxnet_tpu/shard/embedding.py, ISSUE 15):
the bucketed all-to-all lookup, the sparse-gradient fast path through
the captured step (no O(vocab) dense gradient), the scatter-add
optimizer arm's lazy semantics, elastic resize + checkpoint manifests
with row-sharded tables, and the integer-index dtype contract."""
import os
import tempfile
import warnings

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, gluon, nd, shard
from mxnet_tpu.observability import registry
from mxnet_tpu.shard import embedding as semb

V, D, B, F = 64, 8, 8, 3
_rng = np.random.RandomState(0)
IDX = _rng.randint(0, V, (B, F)).astype(np.int32)
XD = _rng.randn(B, 4).astype(np.float32)
Y = _rng.randn(B).astype(np.float32)


class _DLRM(gluon.nn.HybridBlock):
    """Tiny DLRM shape: one categorical table + a dense tower."""

    def __init__(self, sharded=True, **kw):
        super().__init__(**kw)
        with self.name_scope():
            cls = gluon.nn.ShardedEmbedding if sharded \
                else gluon.nn.Embedding
            self.embed = cls(V, D)
            self.top = gluon.nn.Dense(1, in_units=F * D + 4)

    def hybrid_forward(self, Fm, idx, xd):
        e = self.embed(idx)
        flat = e.reshape((idx.shape[0], -1))
        return self.top(Fm.concat(flat, xd, dim=1))


def _build(sharded=True, opt="sgd", opt_args=None, seed=0):
    mx.random.seed(seed)
    net = _DLRM(sharded=sharded)
    net.initialize(mx.init.Xavier())
    net(nd.array(IDX, dtype=np.int32), nd.array(XD))
    tr = gluon.Trainer(net.collect_params(), opt,
                       opt_args or {"learning_rate": 0.1},
                       kvstore="ici")
    return net, tr


def _capture(net, tr):
    lossf = gluon.loss.L2Loss()
    return tr.capture(lambda i, x, y: lossf(net(i, x), y).mean())


def _table(net):
    return [p for p in net.collect_params().values()
            if "embed" in p.name][0]


# ----------------------------------------------------------- exchange
def test_plan_buckets_layout():
    """Every id lands front-packed in its owner's bucket row; pads are
    the out-of-range sentinel; the (owner, rank, order) triple addresses
    each original slot."""
    uniq = jnp.asarray([5, 0, 13, 9, 2, 15], dtype=jnp.int32)
    buckets, owner, rank, order = semb.plan_buckets(uniq, 2, 8, 16)
    bk = np.asarray(buckets)
    assert bk.shape == (2, 6)
    assert sorted(x for x in bk[0] if x < 16) == [0, 2, 5]
    assert sorted(x for x in bk[1] if x < 16) == [9, 13, 15]
    # front-packed: sentinel only after the real ids
    for row in bk:
        real = [i for i, x in enumerate(row) if x < 16]
        assert real == list(range(len(real)))
    # the addressing triple reconstructs the original vector
    back = bk[np.asarray(owner), np.asarray(rank)]
    inv_order = np.argsort(np.asarray(order), kind="stable")
    np.testing.assert_array_equal(back[inv_order], np.asarray(uniq))


def test_gather_rows_matches_dense_take():
    mesh = shard.make_mesh_2d(dp=2, tp=2)
    table = jnp.asarray(_rng.randn(V, D).astype(np.float32))
    sh = jax.sharding.NamedSharding(mesh, P("tp", None))
    tab = jax.device_put(table, sh)
    uniq = jnp.asarray(
        np.r_[_rng.permutation(V)[:12], [V, V]], dtype=jnp.int32)
    got = jax.jit(lambda t, u: semb.gather_rows(t, u, mesh, "tp"))(
        tab, uniq)
    ref = np.asarray(table)[np.clip(np.asarray(uniq), 0, V - 1)]
    real = np.asarray(uniq) < V
    np.testing.assert_array_equal(np.asarray(got)[real], ref[real])


# ------------------------------------------------- captured fast path
def test_sharded_dlrm_parity_structure_and_prefetch():
    """The headline contract in one warm run: sharded-vs-dense step
    parity (plain SGD: the sparse update IS the dense update on the
    touched rows), the pinned 2-all-to-alls-per-table HLO, the
    `sharded_embed_step` observatory name, table donation aliased,
    1 dispatch + zero sync H2D through the device prefetcher, and the
    (unique_ids, rows) sparse gradient pair."""
    from mxnet_tpu import profiler
    from mxnet_tpu.prefetch import DevicePrefetcher

    net, tr = _build(sharded=True)
    plan = tr.shard(mesh={"dp": 2, "tp": 2})
    step = _capture(net, tr)
    losses = []
    L = step(nd.array(IDX, dtype=np.int32), nd.array(XD), nd.array(Y))
    losses.append(float(L.asnumpy()))

    sync = registry().counter("prefetch_h2d_sync")
    pf = DevicePrefetcher(
        ((IDX, XD, Y) for _ in range(3)), capture_spec=tr._kvstore)
    before = sync.value
    for ib, xb, yb in pf:
        profiler.reset_dispatches()
        L = step(ib, xb, yb)
        assert profiler.dispatch_count() <= 2
        assert step.last_fallback_reason is None
        losses.append(float(L.asnumpy()))
    pf.close()
    assert sync.value == before          # integer index batches staged
    assert step.cache_size == 1

    info = step.hlo_info()
    assert info["collectives"].get("all-to-all") == semb.A2A_PER_TABLE
    from mxnet_tpu.observability import compilex
    assert "sharded_embed_step" in compilex.instrumented()
    # donated table + dense weight + bias all alias in place
    assert info["aliased_inputs"] == 3

    # sparse gradient pair: (U,) ids + (U, D) touched rows, U = B*F
    tp = _table(net)
    u, r = tp._sparse_grad
    assert u.shape == (B * F,) and r.shape == (B * F, D)

    # all-to-all byte accounting rode the collective counters
    assert registry().counter("kv_collective_bytes",
                              op="embed_all_to_all").value > 0

    # dense control on the SAME plan (plain Embedding lowers through
    # GSPMD's dense path): identical losses and identical table
    net_d, tr_d = _build(sharded=False)
    tr_d.shard(mesh={"dp": 2, "tp": 2})
    step_d = _capture(net_d, tr_d)
    losses_d = [float(step_d(nd.array(IDX, dtype=np.int32),
                             nd.array(XD),
                             nd.array(Y)).asnumpy())
                for _ in range(4)]
    np.testing.assert_allclose(losses, losses_d, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(_table(net).data().asnumpy(),
                               _table(net_d).data().asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_momentum_lazy_semantics():
    """Sparse-update semantics with momentum state: rows touched at
    step 1 but NOT at step 2 keep their step-1 weight (no momentum
    coast), everything else matches the dense twin exactly."""
    idx2 = ((IDX + 17) % V).astype(np.int32)   # different touch set

    def run(sharded):
        net, tr = _build(sharded=sharded,
                         opt_args={"learning_rate": 0.1,
                                   "momentum": 0.9})
        tr.shard(mesh={"dp": 2, "tp": 2})
        step = _capture(net, tr)
        snaps = []
        for ib in (IDX, idx2):
            step(nd.array(ib, dtype=np.int32), nd.array(XD),
                 nd.array(Y))
            snaps.append(_table(net).data().asnumpy().copy())
        return snaps

    s1, s2 = run(True)
    d1, d2 = run(False)
    np.testing.assert_allclose(s1, d1, rtol=1e-5, atol=1e-6)
    t1 = np.zeros(V, bool)
    t1[IDX.reshape(-1)] = True
    t2 = np.zeros(V, bool)
    t2[idx2.reshape(-1)] = True
    coast = t1 & ~t2          # dense decays momentum, lazy freezes
    ref2 = d2.copy()
    ref2[coast] = d1[coast]
    np.testing.assert_allclose(s2, ref2, rtol=1e-5, atol=1e-6)
    # and the dense twin genuinely coasted somewhere, else the test
    # proves nothing
    assert coast.any() and not np.allclose(d2[coast], d1[coast])


def test_adam_sparse_rows_and_scalar_state():
    """Adam through the scatter-add arm: untouched rows never move
    (weight, m, v all frozen), the scalar step counter advances once
    per step, and the loss goes down."""
    net, tr = _build(opt="adam", opt_args={"learning_rate": 0.01})
    tr.shard(mesh={"dp": 2, "tp": 2})
    step = _capture(net, tr)
    w0 = _table(net).data().asnumpy().copy()
    losses = [float(step(nd.array(IDX, dtype=np.int32), nd.array(XD),
                         nd.array(Y)).asnumpy()) for _ in range(3)]
    assert losses[-1] < losses[0]
    w1 = _table(net).data().asnumpy()
    touched = np.zeros(V, bool)
    touched[IDX.reshape(-1)] = True
    np.testing.assert_array_equal(w1[~touched], w0[~touched])
    assert not np.allclose(w1[touched], w0[touched])
    st = tr._updater.states[[i for i, p in enumerate(
        tr._params) if "embed" in p.name][0]]
    m, v, t = (np.asarray(s._data) for s in st)
    assert int(t) == 3                       # one tick per applied step
    np.testing.assert_array_equal(m[~touched], 0)
    assert np.abs(m[touched]).sum() > 0


def test_no_dense_vocab_gradient_materialised():
    """The backward's table cotangent is the (U, D) row block: the
    executable's output avals hold no (V, D) gradient, and its temp
    memory stays far under one dense table-gradient."""
    net, tr = _build()
    tr.shard(mesh={"dp": 2, "tp": 2})
    step = _capture(net, tr)
    step(nd.array(IDX, dtype=np.int32), nd.array(XD), nd.array(Y))
    # the step's build classified the table onto the sparse path …
    jfn, meta = step._cache[step._last_key]
    assert meta["sparse"] == [0]
    from mxnet_tpu.observability import compilex
    ij = compilex.instrumented()["sharded_embed_step"]
    args, kwargs = ij.last_abstract
    ma = ij.lower(*args, **kwargs).compile().memory_analysis()
    # … and the executable's temp allocation stays far below one dense
    # (V, D) gradient would cost (tiny model: U ~ V here, so the bound
    # is loose; tools/check_dispatch.py pins the scaled version where
    # vocab >> batch and the bound bites)
    assert ma.temp_size_in_bytes < 16 * V * D * 4
    # the grad OUTPUT for the table is the (U,)/(U,D) pair, live on the
    # param after the step
    u, r = _table(net)._sparse_grad
    assert u.shape == (B * F,) and r.shape == (B * F, D)


# ------------------------------------- elastic resize + checkpointing
def test_resize_mesh_redistributes_tables():
    """(2,2) -> (1,2): the row-sharded table redistributes through
    collectives (bitwise), the sparse fast path stays live on the new
    mesh, and training continues without fallback."""
    net, tr = _build()
    tr.shard(mesh={"dp": 2, "tp": 2})
    step = _capture(net, tr)
    for _ in range(2):
        step(nd.array(IDX, dtype=np.int32), nd.array(XD), nd.array(Y))
    w = _table(net).data().asnumpy().copy()
    hg = registry().counter("shard_host_gather_bytes")
    h0 = hg.value
    tr.resize_mesh({"dp": 1, "tp": 2})
    assert hg.value == h0
    np.testing.assert_array_equal(_table(net).data().asnumpy(), w)
    step(nd.array(IDX, dtype=np.int32), nd.array(XD), nd.array(Y))
    assert step.last_fallback_reason is None
    from mxnet_tpu.observability import compilex
    ij = compilex.instrumented()["sharded_embed_step"]
    assert ij.last_hlo is None or \
        ij.last_hlo["collectives"].get("all-to-all", 0) in (
            semb.A2A_PER_TABLE, 0)
    assert not np.allclose(_table(net).data().asnumpy(), w)


def test_checkpoint_manifest_records_table_spec():
    """The manifest persists the table's row-sharded PartitionSpec and
    a (1,2) template restores the exact values (template layout wins)."""
    plan22 = shard.plan({"dp": 2, "tp": 2})
    w = jnp.asarray(_rng.randn(V, D).astype(np.float32))
    params = {"embedding0_weight": jax.device_put(
        w, plan22.sharding("embedding0_weight", w.shape))}
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save_sharded(d, 0, params)
        specs = checkpoint.saved_partition_specs(d, 0)
        assert tuple(specs["embedding0_weight"]) == ("tp",)
        plan12 = plan22.with_mesh({"dp": 1, "tp": 2})
        tmpl = {"embedding0_weight": jax.device_put(
            jnp.zeros_like(w),
            plan12.sharding("embedding0_weight", w.shape))}
        out = checkpoint.load_sharded(d, 0, tmpl)
        np.testing.assert_array_equal(
            np.asarray(out["embedding0_weight"]), np.asarray(w))


def test_amp_overflow_skip_parity_on_sparse_path():
    """The sparse arm of the AMP/skip guard: with fp16 loss scaling and
    a poisoned step (grad.nan -> in-graph NaN), a NONFINITE touched-row
    gradient must trip the same skip reflex as the dense path — scale
    halves identically, the skip branch emits the (uniq, rows) pair
    without a pytree mismatch, and the final table matches the dense-
    Embedding twin trained under the identical schedule."""
    from mxnet_tpu import amp, fault

    def run(sharded):
        amp.reset()
        amp.init("float16")
        fault.injection.clear()
        fault.injection.inject("grad.nan", at=[2])
        try:
            net, tr = _build(sharded=sharded)
            tr.shard(mesh={"dp": 2, "tp": 2})
            step = _capture(net, tr)
            for _ in range(4):
                step(nd.array(IDX, dtype=np.int32), nd.array(XD),
                     nd.array(Y))
                assert step.last_fallback_reason is None
            # the sparse pair exists even on the skipped step (parity
            # of the two cond branches), unscaled like dense grads
            if sharded:
                u, r = _table(net)._sparse_grad
                assert u.shape == (B * F,) and r.shape == (B * F, D)
            return (_table(net).data().asnumpy(),
                    amp._state["scaler"].loss_scale)
        finally:
            amp.reset()
            fault.injection.clear()

    ws, ss = run(True)
    wd, sd = run(False)
    assert ss == sd                      # one skip -> same halved scale
    np.testing.assert_allclose(ws, wd, rtol=1e-5, atol=1e-6)


def test_amp_convert_block_casts_sharded_table():
    """amp.convert_block must cast ShardedEmbedding tables like plain
    Embedding ones — they hold ~99% of the bytes in this workload, and
    an exact-name match list silently skipping the subclass would keep
    them fp32 with no warning."""
    from mxnet_tpu import amp
    net = gluon.nn.ShardedEmbedding(16, 4)
    net.initialize()
    amp.convert_block(net, "bfloat16")
    assert net.weight.data().dtype == amp.bfloat16
    # integer index contract survives the cast (indices never casted)
    out = net(nd.array(np.array([3, 7], np.int32), dtype=np.int32))
    assert out.dtype == amp.bfloat16


def test_tied_table_use_demotes_to_dense():
    """A table READ outside its lookup sites (here a weight-norm
    regularizer; same class as a tied output projection) cannot ride
    the sparse fast path — the hoisted-table backward would drop that
    use's gradient. The build must demote it to the DENSE path loudly,
    and the numerics must match a plain-Embedding twin exactly."""

    class _Tied(gluon.nn.HybridBlock):
        def __init__(self, sharded=True, **kw):
            super().__init__(**kw)
            with self.name_scope():
                cls = gluon.nn.ShardedEmbedding if sharded \
                    else gluon.nn.Embedding
                self.embed = cls(V, D)
                self.top = gluon.nn.Dense(1, in_units=F * D + 4)

        def hybrid_forward(self, Fm, idx, xd):
            e = self.embed(idx)
            flat = e.reshape((idx.shape[0], -1))
            out = self.top(Fm.concat(flat, xd, dim=1))
            w = self.embed.weight.data()     # NON-lookup use
            return out + 1e-3 * Fm.sum(w * w)

    def run(sharded):
        mx.random.seed(0)
        net = _Tied(sharded=sharded)
        net.initialize(mx.init.Xavier())
        net(nd.array(IDX, dtype=np.int32), nd.array(XD))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore="ici")
        tr.shard(mesh={"dp": 2, "tp": 2})
        lossf = gluon.loss.L2Loss()
        step = tr.capture(lambda i, x, y: lossf(net(i, x), y).mean())
        losses = [float(step(nd.array(IDX, dtype=np.int32),
                             nd.array(XD), nd.array(Y)).asnumpy())
                  for _ in range(3)]
        assert step.last_fallback_reason is None
        return net, step, losses

    demos = registry().counter("cachedop_sparse_demotions")
    d0 = demos.value
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        net_s, step_s, losses_s = run(True)
    assert any("outside its lookup" in str(x.message) for x in w)
    assert demos.value > d0
    # the build classified NOTHING onto the sparse path …
    _, meta = step_s._cache[step_s._last_key]
    assert meta["sparse"] == []
    # … so the table has a dense gradient and NO sparse pair
    assert getattr(_table(net_s), "_sparse_grad", None) is None
    # and the numerics are the dense twin's, exactly
    _, _, losses_d = run(False)
    np.testing.assert_allclose(losses_s, losses_d, rtol=1e-6, atol=1e-8)


def test_sparse_grad_cleared_when_path_goes_dense():
    """A table that trained sparse leaves its (ids, rows) pair on the
    param; once the same trainer's step goes DENSE (here: resize to a
    (1,1) mesh collapses the rule spec to replicated), the stale pair
    must be cleared, not left for consumers to read."""
    net, tr = _build()
    tr.shard(mesh={"dp": 2, "tp": 2})
    step = _capture(net, tr)
    step(nd.array(IDX, dtype=np.int32), nd.array(XD), nd.array(Y))
    tp = _table(net)
    assert tp._sparse_grad is not None
    tr.resize_mesh({"dp": 1, "tp": 1})
    step(nd.array(IDX, dtype=np.int32), nd.array(XD), nd.array(Y))
    assert step.last_fallback_reason is None
    _, meta = step._cache[step._last_key]
    assert meta["sparse"] == []
    assert tp._sparse_grad is None


# ------------------------------------------------- rules + reporting
def test_default_rules_cover_embedding_names():
    mesh = shard.make_mesh_2d(dp=2, tp=2)
    for name in ("embedding0_weight", "shardedembedding0_weight",
                 "dlrm0_shardedembedding3_weight", "emb0_weight",
                 "net0_emb_cat2_weight", "decoder_embed_weight",
                 # compound names the pre-ISSUE-15 rule already
                 # sharded — they must never silently lose the layout
                 "wordembed0_weight", "posembed_weight",
                 "tokenembedding0_weight"):
        specs, rep = shard.match_partition_rules(
            shard.DEFAULT_RULES, {name: (V, D)}, mesh=mesh)
        assert specs[name] == P("tp"), name
        assert not rep["unmatched"]
    # non-embedding names stay on their own rules
    specs, _ = shard.match_partition_rules(
        shard.DEFAULT_RULES, {"member0_weight": (V, D)}, mesh=mesh)
    assert specs["member0_weight"] != P("tp")


def test_large_unmatched_table_reports_loudly():
    """A recommender-scale table that ends up replicated (rule typo,
    non-divisible vocab) REPORTS via RuntimeWarning instead of silently
    eating a device's HBM; small params stay silent; the env knob
    disables."""
    no_embed_rules = ((r"_bias$", None), (r".*", None))
    plan = shard.plan({"dp": 2, "tp": 2}, rules=no_embed_rules)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        plan.spec_for("huge_embedding_weight", (10**8, 64))
    assert any("replicates" in str(x.message) for x in w)
    # once per name
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        plan.spec_for("huge_embedding_weight", (10**8, 64))
    assert not w
    # small replicated params are normal, not a report
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        plan.spec_for("dense0_bias", (64,))
    assert not w
    # matched-and-sharded big tables are the healthy case
    plan2 = shard.plan({"dp": 2, "tp": 2})
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        plan2.spec_for("embedding0_weight", (10**8, 64))
    assert not w
    # opt-out
    os.environ["MXTPU_SHARD_WARN_BYTES"] = "0"
    try:
        plan3 = shard.plan({"dp": 2, "tp": 2}, rules=no_embed_rules)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            plan3.spec_for("huge2_embedding_weight", (10**8, 64))
        assert not w
    finally:
        del os.environ["MXTPU_SHARD_WARN_BYTES"]


def test_embed_param_bytes_frac():
    plan = shard.plan({"dp": 2, "tp": 2})
    arrs = {"embedding0_weight": np.zeros((V, D), np.float32),
            "dense0_weight": np.zeros((D, D), np.float32)}
    frac = semb.embed_param_bytes_frac(plan, arrs)
    assert frac == pytest.approx(0.5)    # 1 / tp
    assert semb.embed_param_bytes_frac(
        plan, {"dense0_weight": arrs["dense0_weight"]}) is None
    # DLRM-style names count too: the selector is the SAME pattern the
    # DEFAULT_RULES embedding rule shards, not a substring guess
    frac2 = semb.embed_param_bytes_frac(
        plan, {"net0_emb_cat3_weight": np.zeros((V, D), np.float32)})
    assert frac2 == pytest.approx(0.5)
    # "member0_weight" is a Dense weight, not an embedding table
    assert semb.embed_param_bytes_frac(
        plan, {"member0_weight": np.zeros((V, D), np.float32)}) is None


# -------------------------------------------------- index dtype fixes
def test_embedding_integer_indices_untouched():
    """gluon.nn.Embedding: int32 indices reach the gather as int32 —
    and with x64 enabled int64 stays int64 (the old unconditional
    astype(int32) truncated it) — while the float compat path still
    casts. ShardedEmbedding refuses float indices outright."""
    from mxnet_tpu.ops import nn_ops
    w = jnp.asarray(_rng.randn(16, 4).astype(np.float32))
    i32 = jnp.asarray([1, 2, 3], dtype=jnp.int32)
    jaxpr = str(jax.make_jaxpr(nn_ops.embedding)(i32, w))
    assert "convert_element_type" not in jaxpr.split("take")[0]
    with jax.experimental.enable_x64(True):
        i64 = jnp.asarray([1, 2], dtype=jnp.int64)
        assert i64.dtype == jnp.int64
        out = jax.eval_shape(nn_ops.embedding, i64,
                             jax.ShapeDtypeStruct((16, 4), np.float32))
        jaxpr64 = str(jax.make_jaxpr(nn_ops.embedding)(
            i64, jnp.zeros((16, 4), np.float32)))
        assert "convert_element_type[new_dtype=int32" not in jaxpr64
    # float compat path still works (and still casts)
    f = jnp.asarray([1.0, 2.0])
    np.testing.assert_array_equal(np.asarray(nn_ops.embedding(f, w)),
                                  np.asarray(w)[[1, 2]])
    # block level: int batch in, exact rows out
    net = gluon.nn.Embedding(16, 4)
    net.initialize()
    out = net(nd.array(np.array([3, 7], np.int32), dtype=np.int32))
    np.testing.assert_array_equal(
        out.asnumpy(), net.weight.data().asnumpy()[[3, 7]])
    # ShardedEmbedding: float indices are a wrong-row hazard -> raise
    snet = gluon.nn.ShardedEmbedding(16, 4)
    snet.initialize()
    with pytest.raises(mx.base.MXNetError, match="integer"):
        snet(nd.array([1.0, 2.0]))
    # symbolic path: a float dtype HINT raises at graph build; an
    # int/absent hint builds (execution enforces the eager contract)
    from mxnet_tpu import symbol as sym
    with pytest.raises(mx.base.MXNetError, match="integer"):
        snet(sym.Variable("idx", dtype=np.float32))
    assert snet(sym.Variable("idx", dtype=np.int32)) is not None
    assert snet(sym.Variable("idx")) is not None
