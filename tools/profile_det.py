"""Device profile of the detection bench train steps (VERDICT r4 item 2:
give SSD/Faster-RCNN the ResNet profile treatment).

Usage:  python tools/profile_det.py [--model ssd|rcnn] [--batch N]
                                    [--steps N] [--input N]

Reuses bench_det's exact step builders (so the profile measures the
benched program, not a lookalike) and profile_bench's xplane parser for
the per-HLO table that goes into docs/PERF.md.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=("ssd", "rcnn"), default="ssd")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--input", type=int, default=None)
    ap.add_argument("--logdir", default=None)
    ap.add_argument("--min-pct", type=float, default=0.3)
    args = ap.parse_args()

    import jax
    on_tpu = jax.default_backend() == "tpu"
    input_size = args.input or (512 if on_tpu else 128)
    import bench_det
    if args.model == "ssd":
        batch = args.batch or (16 if on_tpu else 2)
        step, params, mom, data, _ = bench_det.build_step(
            batch, input_size)
    else:
        batch = args.batch or (8 if on_tpu else 2)
        step, params, mom, data = bench_det.build_rcnn_step(
            batch, input_size)
    logdir = args.logdir or f"/tmp/mxtpu_prof_{args.model}"

    params, mom, loss = step(params, mom, *data)
    params, mom, loss = step(params, mom, *data)
    print(f"[profile_det] {args.model} b{batch}@{input_size} "
          f"loss={float(loss):.4f}", file=sys.stderr)

    jax.profiler.start_trace(logdir)
    for _ in range(args.steps):
        params, mom, loss = step(params, mom, *data)
    float(loss)
    jax.profiler.stop_trace()

    from profile_bench import parse_xspace
    parse_xspace(logdir, min_pct=args.min_pct)


if __name__ == "__main__":
    main()
