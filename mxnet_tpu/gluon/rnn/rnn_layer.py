"""Recurrent layers (reference: python/mxnet/gluon/rnn/rnn_layer.py).

TPU-native: the whole sequence loop is a `lax.scan`, so a multi-layer
(bi)LSTM compiles to one fused XLA while-loop with MXU matmuls — the
counterpart of the reference's fused cuDNN RNN op (src/operator/rnn.cc).
Gate layout matches the reference: [i, f, g, o] for LSTM, [r, z, n] for GRU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ndarray.ndarray import NDArray, _apply
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


def _step_rnn(mode, x_t, states, wi, wh, bi, bh):
    """One timestep. x_t: (N, I). Returns (new_states, output)."""
    if mode == "lstm":
        h, c = states
        gates = x_t @ wi.T + bi + h @ wh.T + bh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h
    if mode == "gru":
        (h,) = states
        xw = x_t @ wi.T + bi
        hw = h @ wh.T + bh
        xr, xz, xn = jnp.split(xw, 3, axis=-1)
        hr, hz, hn = jnp.split(hw, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h = (1 - z) * n + z * h
        return (h,), h
    (h,) = states
    g = x_t @ wi.T + bi + h @ wh.T + bh
    h = jnp.tanh(g) if mode == "rnn_tanh" else jax.nn.relu(g)
    return (h,), h


def _scan_layer(mode, x, init_states, wi, wh, bi, bh, reverse=False):
    """x: (T, N, I) -> outputs (T, N, H), final states."""
    def step(carry, x_t):
        new_states, out = _step_rnn(mode, x_t, carry, wi, wh, bi, bh)
        return new_states, out

    final, outs = jax.lax.scan(step, init_states, x, reverse=reverse)
    return outs, final


def _scan_layer_masked(mode, x, lengths, init_states, wi, wh, bi, bh):
    """Variable-length scan: past each row's length the carry freezes (so
    final states are the states at t = len-1) and outputs are zeroed —
    cuDNN variable-length semantics (reference rnn-inl.h
    use_sequence_length path). One lax.scan; the mask is a select fused
    into the loop body, not a host-side ragged loop."""
    T = x.shape[0]
    ln = lengths.astype(jnp.int32)

    def step(carry, inp):
        x_t, t = inp
        new_states, out = _step_rnn(mode, x_t, carry, wi, wh, bi, bh)
        valid = (t < ln)[:, None]
        new_states = tuple(jnp.where(valid, ns, cs)
                           for ns, cs in zip(new_states, carry))
        return new_states, jnp.where(valid, out, 0).astype(out.dtype)

    final, outs = jax.lax.scan(
        step, init_states, (x, jnp.arange(T, dtype=jnp.int32)))
    return outs, final


def rnn_forward(mode, num_layers, num_dir, layout_ntc, pnames,
                xv, svals, pvseq, dropout=0.0, rng=None, seq_len=None):
    """Pure multi-layer (bi)RNN forward over raw arrays: the single kernel
    behind both the eager layer and the symbolic "RNN" op. Inter-layer
    dropout (reference rnn-inl.h semantics: between stacked layers, not
    after the last) applies only when an `rng` key is given — training
    paths thread one, inference paths pass None. With `seq_len` (N,), the
    cuDNN use_sequence_length contract holds: padded steps emit zeros,
    final states come from each row's last valid step, and the reverse
    direction flips only the valid prefix (SequenceReverse + forward
    masked scan — the classic variable-length-biRNN correctness trap).
    Returns (outputs, stacked_h[, stacked_c])."""
    import jax
    from ...ops.seq_ops import sequence_reverse_k
    L, D = num_layers, num_dir
    pv = dict(zip(pnames, pvseq))
    seq = jnp.swapaxes(xv, 0, 1) if layout_ntc else xv  # (T,N,I)
    hs = [svals[0][i] for i in range(L * D)]
    cs = [svals[1][i] for i in range(L * D)] if mode == "lstm" else None
    out = seq
    final_h, final_c = [], []
    for layer in range(L):
        layer_outs = []
        for d, sfx in zip(range(D), ["l", "r"]):
            idx = layer * D + d
            init = (hs[idx], cs[idx]) if mode == "lstm" else (hs[idx],)
            ws = (pv[f"{sfx}{layer}_i2h_weight"],
                  pv[f"{sfx}{layer}_h2h_weight"],
                  pv[f"{sfx}{layer}_i2h_bias"],
                  pv[f"{sfx}{layer}_h2h_bias"])
            if seq_len is None:
                o, fin = _scan_layer(mode, out, init, *ws, reverse=(d == 1))
            else:
                inp = out if d == 0 else sequence_reverse_k(out, seq_len)
                o, fin = _scan_layer_masked(mode, inp, seq_len, init, *ws)
                if d == 1:
                    o = sequence_reverse_k(o, seq_len)
            layer_outs.append(o)
            final_h.append(fin[0])
            if mode == "lstm":
                final_c.append(fin[1])
        out = layer_outs[0] if D == 1 else \
            jnp.concatenate(layer_outs, axis=-1)
        if dropout and rng is not None and layer < L - 1:
            keep = jax.random.bernoulli(
                jax.random.fold_in(rng, layer), 1 - dropout, out.shape)
            out = jnp.where(keep, out / (1 - dropout), 0).astype(out.dtype)
    outs = jnp.swapaxes(out, 0, 1) if layout_ntc else out
    ret = [outs, jnp.stack(final_h)]
    if mode == "lstm":
        ret.append(jnp.stack(final_c))
    return tuple(ret)


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers=1, layout="TNC",
                 dropout=0.0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", use_sequence_length=False, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC")
        self._use_sequence_length = use_sequence_length
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, nh = self._gates, hidden_size
        with self.name_scope():
            for layer in range(num_layers):
                for d, suffix in zip(range(self._dir), ["l", "r"]):
                    in_size = input_size if layer == 0 else nh * self._dir
                    for name, shape, init_ in [
                            ("i2h_weight", (ng * nh, in_size),
                             i2h_weight_initializer),
                            ("h2h_weight", (ng * nh, nh),
                             h2h_weight_initializer),
                            ("i2h_bias", (ng * nh,), i2h_bias_initializer),
                            ("h2h_bias", (ng * nh,), h2h_bias_initializer)]:
                        p = self.params.get(
                            f"{suffix}{layer}_{name}", shape=shape,
                            init=init_, dtype=dtype,
                            allow_deferred_init=(layer == 0 and "i2h_weight"
                                                 in name and input_size == 0))
                        self._reg_params[f"{suffix}{layer}_{name}"] = p

    def _infer_shapes(self, x, *args):
        in_size = x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        for d, suffix in zip(range(self._dir), ["l", "r"]):
            self._reg_params[f"{suffix}0_i2h_weight"]._finish_deferred_init(
                (ng * nh, in_size))
        self._input_size = in_size

    def state_info(self, batch_size=0):
        ns = 2 if self._mode == "lstm" else 1
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}
                for _ in range(ns)]

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        from ... import ndarray as F
        func = func or F.zeros
        return [func(shape=info["shape"], ctx=ctx, **kwargs)
                for info in self.state_info(batch_size)]

    def hybrid_forward(self, F, x, *states, **params):
        layout_ntc = self._layout == "NTC"
        # call styles: net(x, [h, c]) (reference), net(x, h, c), and with
        # use_sequence_length=True the LAST positional arg is the (N,)
        # sequence_length (reference: rnn_layer.forward(inputs, states,
        # sequence_length))
        seq_len = None
        if self._use_sequence_length:
            if not states:
                raise ValueError("use_sequence_length=True: call as "
                                 "net(x[, states], sequence_length)")
            seq_len = states[-1]
            # catch net(x, states) with the lengths forgotten: lengths are
            # a 1-D (N,) vector, never a state tensor or state list.
            # Symbols have no static rank while tracing — let them through.
            from ..block import is_symbolic
            if not is_symbolic(seq_len) and (
                    isinstance(seq_len, (list, tuple)) or
                    getattr(seq_len, "ndim", None) != 1):
                raise ValueError(
                    "use_sequence_length=True: the last positional argument "
                    "must be the 1-D (batch,) sequence_length vector, got "
                    f"{type(seq_len).__name__} with shape "
                    f"{getattr(seq_len, 'shape', '?')}")
            states = states[:-1]
        if len(states) == 1 and isinstance(states[0], (list, tuple)):
            states = tuple(states[0])
        has_states = len(states) > 0
        ns = 2 if self._mode == "lstm" else 1
        pnames = sorted(params.keys())
        pvals = [params[k] for k in pnames]
        mode, L, D = self._mode, self._num_layers, self._dir

        from ..block import is_symbolic
        if is_symbolic(x):
            # zero initial states are synthesised inside the RNN op at
            # bind time (batch size is unknown while tracing)
            extra = ([seq_len] if seq_len is not None else []) + \
                (list(states) if has_states else [])
            node = F.RNN(x, *(extra + pvals), mode=mode,
                         num_layers=L, num_dir=D,
                         hidden_size=self._hidden_size,
                         layout_ntc=layout_ntc, pnames=tuple(pnames),
                         state_outputs=has_states,
                         use_sequence_length=seq_len is not None,
                         dropout=self._dropout)
            if not has_states:
                return node[0]
            return node[0], [node[i] for i in range(1, 1 + ns)]

        if not has_states:
            batch = x.shape[0] if layout_ntc else x.shape[1]
            states = self.begin_state(batch, dtype=x.dtype)
        state_inputs = list(states)

        from ... import autograd
        from ..block import _layer_rng
        key = _layer_rng() if (self._dropout and autograd.is_training()) \
            else None

        has_seq = seq_len is not None

        def fn(xv, *rest, _pn=tuple(pnames), _m=mode, _L=L, _D=D,
               _ln=layout_ntc, _ns=ns, _dp=self._dropout, _k=key,
               _hs=has_seq):
            sl = rest[_ns] if _hs else None
            pv = rest[_ns + 1:] if _hs else rest[_ns:]
            return rnn_forward(_m, _L, _D, _ln, _pn,
                               xv, rest[:_ns], pv,
                               dropout=_dp, rng=_k, seq_len=sl)

        seq_in = [seq_len] if has_seq else []
        flat = _apply(fn, [x] + state_inputs + seq_in + pvals,
                      n_out=2 + (ns - 1))
        out = flat[0]
        new_states = list(flat[1:])
        if has_states:
            return out, new_states
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._input_size} -> "
                f"{self._hidden_size}, layers={self._num_layers}, "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, activation="tanh", **kwargs):
        super().__init__(f"rnn_{activation}", hidden_size, num_layers, **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, **kwargs):
        super().__init__("gru", hidden_size, num_layers, **kwargs)
