"""Autograd tests (reference model: tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_simple_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y * x  # x^3 -> dz/dx = 3x^2
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [12.0])


def test_multiple_inputs():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    assert np.allclose(a.grad.asnumpy(), [3, 4])
    assert np.allclose(b.grad.asnumpy(), [1, 2])


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [20, 200])


def test_backward_outside_scope():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x.exp()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), np.exp(3.0), rtol=1e-5)


def test_pause():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = x * 100  # not recorded
        w = y + 1
    w.backward()
    assert np.allclose(x.grad.asnumpy(), [2.0])
    assert not autograd.is_recording()


def test_training_modes():
    assert not autograd.is_training()
    with autograd.record(train_mode=True):
        assert autograd.is_training()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()


def test_grad_function():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        grads = autograd.grad(y, [x])
    assert np.allclose(grads[0].asnumpy(), [12.0])


def test_grad_req_add():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(2):
        with autograd.record():
            y = x * 3
        y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_detach():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    # z = const(4) * x -> dz/dx = 4
    assert np.allclose(x.grad.asnumpy(), [4.0])


def test_mark_variables():
    x = nd.array([5.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [10.0])


def test_inplace_during_record():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        y += 1
        z = y.sum()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [2, 2])


def test_getitem_grad():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = x[0].sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [[1, 1], [0, 0]])
