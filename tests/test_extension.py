"""Custom-op extension points (SURVEY gap: autograd.Function +
mx.operator.CustomOp; reference: python/mxnet/autograd.py class Function,
python/mxnet/operator.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


class _WrongGrad(autograd.Function):
    """Custom backward that deliberately disagrees with the natural
    gradient — proves the tape calls OUR backward, not autodiff."""

    def forward(self, x):
        return x * x

    def backward(self, dy):
        return dy * 100.0


def test_function_custom_backward_overrides_autodiff():
    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    x.attach_grad()
    f = _WrongGrad()
    with autograd.record():
        y = f(x)
        z = (y * 2).sum()
    z.backward()
    # natural grad would be 2*2x = [4, 8, 12]; custom gives 2*100
    np.testing.assert_allclose(x.grad.asnumpy(), [200.0, 200.0, 200.0])


def test_function_multi_input_output():
    class Swap(autograd.Function):
        def forward(self, a, b):
            return b * 2, a * 3

        def backward(self, da, db):
            return db * 3, da * 2

    a = nd.array(np.array([1.0], np.float32))
    b = nd.array(np.array([5.0], np.float32))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        o1, o2 = Swap()(a, b)
        loss = o1.sum() + 10 * o2.sum()
    loss.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [30.0])  # 10 * 3
    np.testing.assert_allclose(b.grad.asnumpy(), [2.0])


def test_function_saved_state():
    class Scale(autograd.Function):
        def forward(self, x):
            self._x = x
            return x * x

        def backward(self, dy):
            return dy * 2 * self._x  # the true gradient, via saved state

    x = nd.array(np.array([3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = Scale()(x)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_function_bad_grad_count_raises():
    class Bad(autograd.Function):
        def forward(self, a, b):
            return a + b

        def backward(self, dy):
            return dy  # one grad for two inputs

    a = nd.ones((2,))
    b = nd.ones((2,))
    a.attach_grad()
    b.attach_grad()
    with pytest.raises(Exception):
        with autograd.record():
            y = Bad()(a, b)
        y.backward()


class _SigmoidOp(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], 1 / (1 + (-in_data[0]).exp()))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))


@mx.operator.register("test_sigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return _SigmoidOp()


def test_custom_op_forward_backward():
    x = nd.array(np.array([0.0, 1.0, -1.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="test_sigmoid")
        z = y.sum()
    z.backward()
    sig = 1 / (1 + np.exp(-np.array([0.0, 1.0, -1.0])))
    np.testing.assert_allclose(y.asnumpy(), sig, rtol=1e-5)
    np.testing.assert_allclose(x.grad.asnumpy(), sig * (1 - sig), rtol=1e-5)


def test_custom_op_unregistered_raises():
    with pytest.raises(Exception):
        nd.Custom(nd.ones((2,)), op_type="never_registered")


def test_custom_op_wrong_arity_raises():
    with pytest.raises(Exception):
        nd.Custom(nd.ones((2,)), nd.ones((2,)), op_type="test_sigmoid")


def test_custom_op_in_symbol_graph():
    """Registered CustomOps work as Symbol nodes: forward through the
    jitted Executor, custom backward through vjp, JSON round-trip
    (reference: mx.sym.Custom)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, sym

    class Scale3(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], in_data[0] * 3.0)

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            # deliberately non-natural gradient: 5x (proves the custom
            # backward is the one used)
            self.assign(in_grad[0], req[0], out_grad[0] * 5.0)

    @mx.operator.register("scale3_sym")
    class Scale3Prop(mx.operator.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return Scale3()

    x = sym.Variable("x")
    out = sym.Custom(x, op_type="scale3_sym", name="sc") * 2.0
    xv = nd.array(np.array([1.0, 2.0], np.float32))
    grads = {"x": nd.zeros((2,))}
    ex = out.bind(None, {"x": xv}, grads)
    np.testing.assert_allclose(ex.forward(is_train=True)[0].asnumpy(),
                               [6.0, 12.0])
    ex.backward(nd.ones((2,)))
    np.testing.assert_allclose(grads["x"].asnumpy(), [10.0, 10.0])

    loaded = sym.load_json(out.tojson())
    ex2 = loaded.bind(None, {"x": xv})
    np.testing.assert_allclose(ex2.forward()[0].asnumpy(), [6.0, 12.0])


def test_custom_op_train_flag_and_multi_output_roundtrip():
    """CustomOp.forward sees the real is_train flag; multi-output custom
    nodes keep their arity through symbol.json (round-2 review
    findings)."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd, sym

    seen = []

    class Flagged(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            seen.append(bool(is_train))
            self.assign(out_data[0], req[0], in_data[0] * 2.0)
            self.assign(out_data[1], req[1], in_data[0] + 1.0)

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            self.assign(in_grad[0], req[0], out_grad[0] * 2.0 + out_grad[1])

    @mx.operator.register("flagged2")
    class FlaggedProp(mx.operator.CustomOpProp):
        def list_outputs(self):
            return ["doubled", "plus1"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0], in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            return Flagged()

    # imperative: flag follows autograd training mode
    x = nd.ones((3,))
    mx.operator.Custom(x, op_type="flagged2")
    assert seen[-1] is False
    with autograd.record():
        mx.operator.Custom(x, op_type="flagged2")
    assert seen[-1] is True

    # symbolic: Executor.forward(is_train=...) drives the flag
    node = sym.Custom(sym.Variable("x"), op_type="flagged2", name="fl")
    g = sym.Group([node[0], node[1]])
    ex = g.bind(None, {"x": x})
    ex.forward(is_train=False)
    assert seen[-1] is False
    ex.forward(is_train=True)
    assert seen[-1] is True

    # multi-output arity survives the JSON round trip
    loaded = sym.load_json(g.tojson())
    assert len(loaded.list_outputs()) == 2
    ex2 = loaded.bind(None, {"x": x})
    o1, o2 = ex2.forward()
    np.testing.assert_allclose(o1.asnumpy(), [2, 2, 2])
    np.testing.assert_allclose(o2.asnumpy(), [2, 2, 2])


def test_multi_output_custom_direct_bind():
    """Binding a multi-output custom node DIRECTLY yields all outputs
    (round-2 review finding: index-0-only truncation)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, sym
    try:
        mx.operator.get("flagged2")
    except mx.base.MXNetError:
        test_custom_op_train_flag_and_multi_output_roundtrip()
    node = sym.Custom(sym.Variable("x"), op_type="flagged2", name="direct")
    assert len(node.list_outputs()) == 2
    ex = node.bind(None, {"x": nd.ones((3,))})
    outs = ex.forward()
    assert len(outs) == 2
    np.testing.assert_allclose(outs[0].asnumpy(), [2, 2, 2])
    np.testing.assert_allclose(outs[1].asnumpy(), [2, 2, 2])
    _, out_shapes, _ = node.infer_shape(x=(3,))
    assert out_shapes == [(3,), (3,)]
    loaded = sym.load_json(node.tojson())
    assert len(loaded.bind(None, {"x": nd.ones((3,))}).forward()) == 2
