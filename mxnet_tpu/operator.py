"""Custom operator extension point (reference: python/mxnet/operator.py —
CustomOp / CustomOpProp / register, backed by src/operator/custom/custom.cc).

The reference runs custom ops on a dedicated thread through the C API; here
a custom op is packaged as an `autograd.Function`-style `jax.custom_vjp`
pure function, so it records on the imperative tape, differentiates through
`backward()`, and traces under jit like any built-in op. The CustomOp
methods must therefore use traceable array ops (no `.asnumpy()`).

Usage (reference idiom):

    class Sigmoid(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], 1 / (1 + (-in_data[0]).exp()))
        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            y = out_data[0]
            self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))

    @mx.operator.register("sigmoid")
    class SigmoidProp(mx.operator.CustomOpProp):
        def list_arguments(self): return ["data"]
        def list_outputs(self): return ["output"]
        def infer_shape(self, in_shape): return in_shape, [in_shape[0]]
        def create_operator(self, ctx, shapes, dtypes): return Sigmoid()

    y = mx.nd.Custom(x, op_type="sigmoid")
"""
from __future__ import annotations

import jax

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get", "Custom",
           "get_all_registered_operators"]

_registry = {}


class CustomOp:
    """Base class for custom operator implementations."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    @staticmethod
    def assign(dst, req, src):
        """Write `src` into `dst` honouring the grad_req (reference
        semantics: 'write'/'inplace' overwrite, 'add' accumulates,
        'null' drops)."""
        if req == "null":
            return
        if req == "add":
            dst._rebind(dst._data + src._data)
        else:
            dst._rebind(src._data)


class CustomOpProp:
    """Describes a custom op: arguments, outputs, shapes, operator factory.
    `needs_top_grad` mirrors the reference default (True)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, shapes, dtypes):
        raise NotImplementedError


def register(op_type):
    """Class decorator registering a CustomOpProp under `op_type`
    (reference: mx.operator.register)."""
    def wrap(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError(f"{prop_cls} must subclass CustomOpProp")
        _registry[op_type] = prop_cls
        return prop_cls
    return wrap


def get(op_type):
    if op_type not in _registry:
        raise MXNetError(f"custom op {op_type!r} is not registered")
    return _registry[op_type]


def get_all_registered_operators():
    """All operator names: the built-in imperative op surface plus
    registered custom ops (reference contract: MXListAllOpNames returns
    every operator, not just custom ones)."""
    from . import ndarray as nd
    builtin = [n for n in dir(nd)
               if not n.startswith("_") and callable(getattr(nd, n))]
    return sorted(set(builtin) | set(_registry))


def _prop_for(op_type, prop_kwargs, n_inputs):
    """Instantiate the registered prop and check input arity (shared by
    nd.Custom, sym.Custom and the graph-eval path)."""
    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    prop = get(op_type)(**prop_kwargs)
    n_in = len(prop.list_arguments())
    if n_inputs != n_in:
        raise MXNetError(f"{op_type} expects {n_in} inputs, got "
                         f"{n_inputs}")
    return prop


def _build_custom_fn(op_type, prop_kwargs, in_shapes, train=False):
    """Package a registered CustomOp as one `jax.custom_vjp` pure function
    over raw arrays (shared by the imperative mx.nd.Custom and the
    symbolic sym.Custom node). `train` is the is_train flag forwarded to
    CustomOp.forward (captured by the CALLER before any autograd.pause).
    Returns (custom_fn, n_in, n_out)."""
    from .ndarray.ndarray import NDArray
    from . import autograd
    from .context import current_context

    prop = _prop_for(op_type, prop_kwargs, len(in_shapes))
    n_in = len(prop.list_arguments())
    n_out = len(prop.list_outputs())
    shapes = prop.infer_shape(list(in_shapes))
    out_shapes = list(shapes[1])
    op = prop.create_operator(current_context(), in_shapes, None)

    def run_forward(raw):
        import jax.numpy as jnp
        with autograd.pause():
            ins = [NDArray(r) for r in raw]
            outs = [NDArray(jnp.zeros(s, ins[0].dtype if ins else None))
                    for s in out_shapes]
            op.forward(train, ["write"] * n_out, ins, outs, [])
        return tuple(o._data for o in outs)

    @jax.custom_vjp
    def custom_fn(*raw):
        outs = run_forward(raw)
        return outs if n_out > 1 else outs[0]

    def custom_fwd(*raw):
        outs = run_forward(raw)
        return (outs if n_out > 1 else outs[0]), (raw, outs)

    def custom_bwd(res, g):
        import jax.numpy as jnp
        raw, outs = res
        gs = g if isinstance(g, tuple) else (g,)
        with autograd.pause():
            ins_nd = [NDArray(r) for r in raw]
            outs_nd = [NDArray(o) for o in outs]
            grads_nd = [NDArray(gg) for gg in gs]
            in_grads = [NDArray(jnp.zeros(s, r.dtype))
                        for s, r in zip(in_shapes, raw)]
            op.backward(["write"] * n_in, grads_nd, ins_nd, outs_nd,
                        in_grads, [])
        return tuple(ig._data for ig in in_grads)

    custom_fn.defvjp(custom_fwd, custom_bwd)
    return custom_fn, n_in, n_out


def Custom(*inputs, op_type=None, **prop_kwargs):
    """Invoke a registered custom op on NDArrays (reference:
    mx.nd.Custom(..., op_type=...))."""
    from .ndarray.ndarray import NDArray
    from . import autograd

    in_shapes = [tuple(x.shape) for x in inputs]
    custom_fn, _, n_out = _build_custom_fn(
        op_type, prop_kwargs, in_shapes, train=autograd.is_training())

    raw = [x._data for x in inputs]
    out = custom_fn(*raw)
    outs = out if isinstance(out, tuple) else (out,)
    nd_outs = tuple(NDArray(o) for o in outs)
    autograd.record_op(custom_fn, list(inputs), {}, nd_outs)
    return nd_outs[0] if n_out == 1 else nd_outs
