"""SqueezeNet 1.0/1.1 (reference: gluon/model_zoo/vision/squeezenet.py)."""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(HybridBlock):
    def __init__(self, squeeze_channels, expand1x1_channels,
                 expand3x3_channels, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self._axis = 1 if layout == "NCHW" else 3
        self.squeeze = nn.Conv2D(squeeze_channels, 1, activation="relu",
                                 layout=layout)
        self.expand1x1 = nn.Conv2D(expand1x1_channels, 1, activation="relu",
                                   layout=layout)
        self.expand3x3 = nn.Conv2D(expand3x3_channels, 3, padding=1,
                                   activation="relu", layout=layout)

    def hybrid_forward(self, F, x):
        # F.concat, not the nd-level helper: symbolic export needs the
        # trace-polymorphic namespace (this was an export-blocking bug)
        x = self.squeeze(x)
        return F.concat(self.expand1x1(x), self.expand3x3(x),
                        dim=self._axis)


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        assert version in ("1.0", "1.1")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if version == "1.0":
                self.features.add(nn.Conv2D(96, 7, 2, activation="relu",
                                            layout=layout))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                for s, e in [(16, 64), (16, 64), (32, 128)]:
                    self.features.add(_Fire(s, e, e, layout=layout))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                for s, e in [(32, 128), (48, 192), (48, 192), (64, 256)]:
                    self.features.add(_Fire(s, e, e, layout=layout))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                self.features.add(_Fire(64, 256, 256, layout=layout))
            else:
                self.features.add(nn.Conv2D(64, 3, 2, activation="relu",
                                            layout=layout))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                for s, e in [(16, 64), (16, 64)]:
                    self.features.add(_Fire(s, e, e, layout=layout))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                for s, e in [(32, 128), (32, 128)]:
                    self.features.add(_Fire(s, e, e, layout=layout))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                for s, e in [(48, 192), (48, 192), (64, 256), (64, 256)]:
                    self.features.add(_Fire(s, e, e, layout=layout))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, 1, layout=layout))
            self.output.add(nn.Activation("relu"))
            self.output.add(nn.GlobalAvgPool2D(layout=layout))
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def squeezenet1_0(**kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return SqueezeNet("1.1", **kwargs)
