"""Data-parallel training as ONE pjit-compiled XLA program.

Reference path being replaced: Gluon Trainer + KVStore nccl allreduce
(python/mxnet/gluon/trainer.py, src/kvstore/kvstore_nccl.cc). TPU-native
path: parameters live replicated over the mesh, the batch is sharded over
'dp', and XLA's SPMD partitioner inserts the gradient psum over ICI
automatically from the sharding annotations — no explicit collective calls,
no host round-trips, buffers donated so weights update in place in HBM.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .functional import functional_call, param_values, collect_params_ordered
from .mesh import make_mesh

__all__ = ["DataParallelTrainer", "make_train_step"]


def make_train_step(block, loss_block, optimizer, mesh=None, dp_axis="dp",
                    donate=True, compute_dtype=None, remat=False,
                    zero=False):
    """Build (step_fn, init_state). step_fn(state, x, y, lr) -> (state, loss).

    The returned step is jit-compiled once; with a mesh, x/y are expected
    sharded over `dp_axis` and params replicated. remat=True wraps the
    model forward in `jax.checkpoint` so backward recomputes activations
    instead of keeping them live (long-seq / big-batch memory relief).

    zero=True shards the OPTIMIZER STATE over `dp_axis` (ZeRO-1 / the
    automatic cross-replica weight-update sharding of Xu et al.,
    arXiv:2004.13336 — PAPERS.md): each leaf partitions on its first
    dp-divisible dim, and the sharding annotations make GSPMD lower the
    gradient reduction to reduce_scatter + the update to a 1/P-shard
    compute — optimizer memory per chip drops by the dp size. Params stay
    replicated, so the rest of the program is unchanged.
    """
    names = [n for n, _ in collect_params_ordered(block)]
    trainable = [n for n, p in collect_params_ordered(block)
                 if p.grad_req != "null"]
    trainable_set = set(trainable)

    def fwd(params, x, rng):
        return functional_call(block, params, [x], training=True, rng=rng)

    if remat:
        fwd = jax.checkpoint(fwd)

    def loss_of(params, x, y, rng):
        out, aux = fwd(params, x, rng)
        out = out[0] if isinstance(out, tuple) else out
        if compute_dtype is not None:
            out = out.astype(jnp.float32)
        loss_nd, _ = functional_call(loss_block, {}, [out, y], training=True)
        loss = loss_nd[0] if isinstance(loss_nd, tuple) else loss_nd
        return jnp.mean(loss), aux

    def step(state, x, y, lr, rng):
        params, opt_state, num_update = state
        (loss, aux), grads = jax.value_and_grad(loss_of, has_aux=True)(
            params, x, y, rng)
        new_params = dict(params)
        new_opt = dict(opt_state)
        wd = optimizer.wd
        for n in names:
            if n not in trainable_set:
                continue
            g = grads[n]
            if optimizer.clip_gradient is not None:
                g = jnp.clip(g, -optimizer.clip_gradient,
                             optimizer.clip_gradient)
            w, s = optimizer.apply(params[n], g.astype(params[n].dtype),
                                   opt_state[n], lr, wd)
            new_params[n] = w
            new_opt[n] = s
        # BatchNorm running stats updated functionally
        for n, v in aux.items():
            if n in new_params:
                new_params[n] = v
        return (new_params, new_opt, num_update + 1), loss

    def init_state():
        params = param_values(block)
        opt_state = {n: optimizer.init_state(params[n]) for n in trainable}
        return (params, opt_state, jnp.zeros((), jnp.int32))

    donate_argnums = (0,) if donate else ()
    if zero and mesh is None:
        raise ValueError("zero=True (sharded optimizer state) requires a "
                         "mesh")
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        data_sh = NamedSharding(mesh, P(dp_axis))
        if zero:
            ndev = mesh.shape[dp_axis]

            def leaf_sharding(leaf):
                for dim, size in enumerate(leaf.shape):
                    if size % ndev == 0 and size >= ndev:
                        spec = [None] * leaf.ndim
                        spec[dim] = dp_axis
                        return NamedSharding(mesh, P(*spec))
                return repl  # tiny leaves (scalars/biases) replicate

            # template from the single source of truth: a structural
            # drift between this and the real state would break the
            # in_shardings pytree match on every zero=True step
            opt_template = jax.eval_shape(lambda: init_state()[1])
            opt_sh = jax.tree_util.tree_map(leaf_sharding, opt_template)
            state_sh = ({n: repl for n in names}, opt_sh, repl)
            step_fn = jax.jit(
                step,
                in_shardings=(state_sh, data_sh, data_sh, None, repl),
                out_shardings=(state_sh, repl),
                donate_argnums=donate_argnums)

            base_init = init_state

            def init_state():  # noqa: F811 — sharded initial placement
                # donated args must ALREADY carry the declared shardings;
                # place the fresh state accordingly (this is also where
                # the 1/P optimizer-memory saving materialises)
                return jax.device_put(base_init(), state_sh)
        else:
            # params/opt-state replicate over the mesh (broadcast over the
            # state pytree); batch shards over dp; lr python scalar, rng
            # replicates
            step_fn = jax.jit(
                step,
                in_shardings=(repl, data_sh, data_sh, None, repl),
                donate_argnums=donate_argnums)
    else:
        step_fn = jax.jit(step, donate_argnums=donate_argnums)
    return step_fn, init_state


class DataParallelTrainer:
    """High-level fused data-parallel trainer.

    Usage:
        trainer = DataParallelTrainer(net, loss, mx.optimizer.SGD(...), mesh)
        loss = trainer.step(x, y)           # one XLA program per step
        trainer.sync_to_params()            # write weights back to Gluon
    """

    def __init__(self, block, loss_block, optimizer, mesh=None, dp_axis="dp",
                 zero=False):
        self.block = block
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.optimizer = optimizer
        self._step_fn, init = make_train_step(block, loss_block, optimizer,
                                              mesh, dp_axis, zero=zero)
        self.state = init()
        self._rng = jax.random.PRNGKey(0)
        self.num_update = 0

    def step(self, x, y, lr=None):
        from ..ndarray.ndarray import NDArray
        x = x._data if isinstance(x, NDArray) else x
        y = y._data if isinstance(y, NDArray) else y
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, P(self.dp_axis))
            x = jax.device_put(x, sh)
            y = jax.device_put(y, sh)
        self.num_update += 1
        lr = lr if lr is not None else self.optimizer.learning_rate
        self.optimizer.num_update = self.num_update
        self._rng, sub = jax.random.split(self._rng)
        self.state, loss = self._step_fn(self.state, x, y, lr, sub)
        return loss

    def sync_to_params(self):
        """Write the functional state back into the Gluon Parameters."""
        params, _, _ = self.state
        for name, p in collect_params_ordered(self.block):
            p._data._rebind(params[name])
