"""Train an MLP on (synthetic) MNIST — the canonical Gluon flow.

Usage: python examples/train_mnist.py [--epochs N] [--smoke]
Mirrors the reference's gluon MNIST example: Dataset -> DataLoader ->
HybridBlock -> Trainer -> metric, with hybridize() compiling the whole
net into one XLA executable.
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data.vision import MNIST


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="device",
                    help="device | local | dist (multi-process via "
                         "tools/launch.py)")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.epochs = 1

    kv = None
    if args.kv_store.startswith("dist"):
        # launched by tools/launch.py: coordinator/rank come from the env
        from mxnet_tpu import kvstore
        kvstore.init_distributed()
        kv = kvstore.create(args.kv_store)
        print(f"kvstore rank {kv.rank}/{kv.num_workers}")

    mx.random.seed(kv.rank if kv is not None else 0)  # per-worker shuffle
    train = MNIST(train=True)
    loader = gluon.data.DataLoader(
        train.transform_first(lambda x: x.astype("float32") / 255.0),
        batch_size=args.batch_size, shuffle=True)

    net = nn.HybridSequential()
    net.add(nn.Flatten(), nn.Dense(128, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier(), ctx=mx.tpu())
    net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9},
                            kvstore=kv if kv is not None else "device")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        for i, (x, y) in enumerate(loader):
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update([y], [out])
            if args.smoke and i >= 3:
                break
        print(f"epoch {epoch}: accuracy={metric.get()[1]:.4f}")

    net.save_parameters("mnist_mlp.params")
    print("saved mnist_mlp.params")


if __name__ == "__main__":
    main()
