"""mx.mod.Module (reference: python/mxnet/module/module.py).

Symbol-based training harness: bind -> init_params -> fit/forward/backward/
update, with epoch checkpoints. Executes through the jitted Executor.
"""
from __future__ import annotations

import logging

import numpy as np

from .base import MXNetError, _as_list
from . import metric as metric_mod
from . import optimizer as opt_mod
from . import initializer as init_mod
from .ndarray.ndarray import NDArray, zeros
from .checkpoint import save_checkpoint, load_checkpoint
from .callback import BatchEndParam

__all__ = ["Module", "BaseModule", "BucketingModule",
           "SequentialModule"]


class BaseModule:
    """Shared train/eval driver (reference: module/base_module.py — the
    generic fit/score loops live on the base, concrete modules provide
    bind/init/forward/backward/update)."""

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    def score(self, eval_data, eval_metric, num_batch=None, **kwargs):
        eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        eval_data.reset()
        for i, batch in enumerate(eval_data):
            if num_batch is not None and i == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
        return eval_metric.get_name_value()

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=None, initializer=None,
            num_epoch=1, arg_params=None, aux_params=None,
            begin_epoch=0, **kwargs):
        if not self.binded:
            self.bind([(d.name, d.shape) for d in train_data.provide_data],
                      [(l.name, l.shape) for l in train_data.provide_label])
        if not self.params_initialized:
            self.init_params(initializer, arg_params, aux_params)
        if not self.optimizer_initialized:
            self.init_optimizer(kvstore, optimizer, optimizer_params)
        eval_metric = metric_mod.create(eval_metric)
        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward(batch, is_train=True)
                self.backward()
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback:
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=eval_metric,
                                          locals=None)
                    for cb in _as_list(batch_end_callback):
                        cb(param)
            if epoch_end_callback:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, getattr(self, "_symbol", None), arg_p, aux_p)
            if eval_data is not None:
                self.score(eval_data, eval_metric)
        return self


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, **kwargs):
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._ctx = context
        self._exec = None
        self._optimizer = None
        self._updater = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    @property
    def symbol(self):
        return self._symbol

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        self._inputs_need_grad = inputs_need_grad
        shapes = {}
        for desc in data_shapes:
            name, shape = (desc.name, desc.shape) if hasattr(desc, "name") \
                else desc
            shapes[name] = shape
        for desc in (label_shapes or []):
            name, shape = (desc.name, desc.shape) if hasattr(desc, "name") \
                else desc
            shapes[name] = shape
        args = self._symbol.list_arguments()
        # label vars may not require shapes if the loss ignores them
        bind_shapes = {}
        for a in args:
            if a in shapes:
                bind_shapes[a] = shapes[a]
        self._input_names = list(bind_shapes)
        self._param_names = [a for a in args if a not in shapes]
        self._for_training = for_training
        self._grad_req = grad_req
        self._bind_shapes = bind_shapes
        self.binded = True
        return self

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, **kwargs):
        if not self.binded:
            raise MXNetError("bind before init_params")
        loaded = getattr(self, "_loaded_params", None)
        if loaded is not None:  # Module.load: restore checkpoint params
            arg_params = arg_params or loaded[0]
            aux_params = aux_params or loaded[1]
        initializer = initializer or init_mod.Uniform(0.07)
        from . import random as rnd
        # infer param shapes from graph with given input shapes
        arg_shapes, _, _ = self._symbol.infer_shape(**self._bind_shapes)
        names = self._symbol.list_arguments()
        shape_of = dict(zip(names, arg_shapes)) if arg_shapes else {}
        args = {}
        for name in names:
            if name in self._bind_shapes:
                args[name] = zeros(self._bind_shapes[name], ctx=self._ctx)
            elif arg_params and name in arg_params:
                args[name] = arg_params[name]
            else:
                shape = shape_of.get(name)
                if shape is None:
                    raise MXNetError(f"cannot infer shape for {name}")
                key = rnd._next_key()
                args[name] = NDArray(
                    initializer(name, shape, np.float32, key))
        grad_names = set(self._param_names)
        if getattr(self, "_inputs_need_grad", False):
            grad_names.update(self._data_names)  # chained modules need dX
        grad_args = {name: zeros(a.shape, ctx=self._ctx)
                     for name, a in args.items()
                     if name in grad_names} \
            if self._for_training else None
        # restored aux states pass through; anything missing is defaulted
        # by Executor.__init__ (moving_var=1, else 0)
        aux = {n: aux_params[n]
               for n in self._symbol.list_auxiliary_states()
               if aux_params and n in aux_params} or None
        self._exec = self._symbol.bind(self._ctx, args, grad_args,
                                       self._grad_req, aux_states=aux)
        self.params_initialized = True
        return self

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        self._optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self._updater = opt_mod.get_updater(self._optimizer)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        is_train = self._for_training if is_train is None else is_train
        feeds = {}
        for name, arr in zip(self._data_names, _as_list(data_batch.data)):
            if name in self._exec.arg_dict:
                feeds[name] = arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names,
                                 _as_list(data_batch.label)):
                if name in self._exec.arg_dict:
                    feeds[name] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        if self._updater is None:
            raise MXNetError("call init_optimizer() before update() "
                             "(reference: Module.update asserts "
                             "optimizer_initialized)")
        for i, name in enumerate(self._param_names):
            self._updater(i, self._exec.grad_dict[name],
                          self._exec.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        """Gradients wrt the data inputs (requires bind(inputs_need_grad=
        True); reference: Module.get_input_grads)."""
        if not getattr(self, "_inputs_need_grad", False) \
                or not self._for_training:
            raise MXNetError("bind with for_training=True and "
                             "inputs_need_grad=True to read input gradients")
        return [self._exec.grad_dict[n] for n in self._data_names]

    def get_params(self):
        arg_params = {n: self._exec.arg_dict[n] for n in self._param_names}
        return arg_params, dict(self._exec.aux_dict)

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        params = arg_params or {}
        if not allow_missing:
            missing = [n for n in self._param_names if n not in params]
            if missing:
                raise MXNetError(
                    f"set_params: missing {missing} (pass "
                    f"allow_missing=True to initialize them)")
        if not allow_extra:
            extra = [n for n in params if n not in self._param_names]
            if extra:
                raise MXNetError(
                    f"set_params: unknown parameters {extra} (pass "
                    f"allow_extra=True to ignore)")
        # upstream documents set_params as init_params(arg_params=...,
        # force_init=...); before the executor exists (bind -> set_params
        # -> score, the classic deploy flow) that is literally what runs
        if self._exec is None:
            return self.init_params(arg_params=params,
                                    aux_params=aux_params,
                                    allow_missing=allow_missing,
                                    force_init=force_init)
        for n, v in params.items():
            if n in self._exec.arg_dict:
                self._exec.arg_dict[n]._assign_value(v._data)
        for n, v in (aux_params or {}).items():
            if n in self._exec.aux_dict:
                self._exec.aux_dict[n]._assign_value(v._data)

    def predict(self, eval_data, num_batch=None, **kwargs):
        outs = []
        eval_data.reset()
        for i, batch in enumerate(eval_data):
            if num_batch is not None and i == num_batch:
                break
            self.forward(batch, is_train=False)
            out = self.get_outputs()[0]
            pad = getattr(batch, "pad", 0) or 0
            if pad:  # NDArrayIter wraps the last batch; drop the filler
                out = out[:out.shape[0] - pad]
            outs.append(out)
        from .ops.tensor_ops import concat
        return concat(*outs, dim=0) if len(outs) > 1 else outs[0]

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._loaded_params = (arg_params, aux_params)
        return mod


class BucketingModule(BaseModule):
    """Variable-length Symbol training over shape buckets (reference:
    python/mxnet/module/bucketing_module.py).

    `sym_gen(bucket_key) -> (symbol, data_names, label_names)` builds the
    per-bucket graph; one Module (one jitted Executor — XLA needs static
    shapes, so a bucket IS a compile cache entry) is created per key, and
    every bucket shares the default bucket's parameter arrays (the same
    NDArray objects are bound into each Executor, so one optimizer update
    is visible to all buckets) and one shared updater/optimizer state."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, **kwargs):
        if default_bucket_key is None:
            raise MXNetError("BucketingModule needs default_bucket_key")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._ctx = context
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None

    def _gen_module(self, bucket_key, data_shapes, label_shapes):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        self._names_cache = getattr(self, "_names_cache", {})
        self._names_cache[bucket_key] = (data_names, label_names)
        mod = Module(sym, data_names, label_names, context=self._ctx)
        mod.bind(data_shapes, label_shapes,
                 for_training=self._for_training, grad_req=self._grad_req)
        return mod

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        self._for_training = for_training
        self._grad_req = grad_req
        mod = self._gen_module(self._default_bucket_key, data_shapes,
                               label_shapes)
        self._buckets[self._default_bucket_key] = mod
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True
        return self

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, **kwargs):
        if not self.binded:
            raise MXNetError("bind before init_params")
        self._buckets[self._default_bucket_key].init_params(
            initializer, arg_params, aux_params, allow_missing, force_init)
        self.params_initialized = True
        return self

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        base = self._buckets[self._default_bucket_key]
        base.init_optimizer(kvstore, optimizer, optimizer_params)
        # one updater (one optimizer-state dict) shared across buckets
        self._optimizer = base._optimizer
        self._updater = base._updater
        for mod in self._buckets.values():
            mod._optimizer, mod._updater = self._optimizer, self._updater
        self.optimizer_initialized = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Select (creating + param-sharing on first use) the bucket's
        executor. Per-bucket jit caches are keyed by the bucket's static
        shapes, so re-switching is free after first compile."""
        if bucket_key not in self._buckets:
            mod = self._gen_module(bucket_key, data_shapes, label_shapes)
            base = self._buckets[self._default_bucket_key]
            # buckets must agree on parameter names AND order: storage is
            # shared by name, and the one shared updater keys optimizer
            # state by positional index in _param_names — a silent
            # mismatch would train private weights / cross momenta
            if mod._param_names != base._param_names:
                raise MXNetError(
                    f"bucket {bucket_key!r} parameters "
                    f"{mod._param_names} do not match the default "
                    f"bucket's {base._param_names}; sym_gen must produce "
                    f"identically-named/-ordered parameters per bucket")
            if self.params_initialized:
                arg_params, aux_params = base.get_params()
                # same NDArray objects => shared storage across buckets
                mod.init_params(arg_params=arg_params,
                                aux_params=aux_params)
            if self.optimizer_initialized:
                mod._optimizer, mod._updater = self._optimizer, self._updater
            self._buckets[bucket_key] = mod
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key
        return self._curr_module

    def _shapes_for(self, batch):
        # names cached per bucket — sym_gen builds a whole graph, far too
        # heavy for the per-batch hot path
        cached = getattr(self, "_names_cache", {}).get(batch.bucket_key)
        if cached is None:
            _, data_names, label_names = self._sym_gen(batch.bucket_key)
            self._names_cache = getattr(self, "_names_cache", {})
            self._names_cache[batch.bucket_key] = (data_names, label_names)
        else:
            data_names, label_names = cached
        data = [(n, a.shape) for n, a in
                zip(data_names, _as_list(batch.data))]
        labels = None
        if batch.label is not None:
            labels = [(n, a.shape) for n, a in
                      zip(label_names, _as_list(batch.label))]
        return data, labels

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key
        if key is None:
            key = self._default_bucket_key
            data_batch.bucket_key = key
        data_shapes, label_shapes = self._shapes_for(data_batch)
        self.switch_bucket(key, data_shapes, label_shapes)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def set_params(self, arg_params, aux_params=None, **kwargs):
        for mod in self._buckets.values():
            mod.set_params(arg_params, aux_params, **kwargs)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)


class SequentialModule(BaseModule):
    """Chain of Modules executed in order (reference:
    python/mxnet/module/sequential_module.py).

    Each added module consumes the previous module's outputs as its data.
    By default only the LAST module receives labels (the reference's
    META_TAKE_LABELS); pass take_labels=True to add() to override. All
    modules after the first bind with inputs_need_grad=True so backward
    chains output gradients through the whole stack.
    """

    def __init__(self, logger=logging, **kwargs):
        self._modules = []
        self._take_labels = []
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    def add(self, module, take_labels=False, **kwargs):
        self._modules.append(module)
        self._take_labels.append(take_labels)
        return self

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        if not self._modules:
            raise MXNetError("add modules before bind")
        cur_shapes = [(d.name, d.shape) if hasattr(d, "name") else d
                      for d in data_shapes]
        label_shapes = [(l.name, l.shape) if hasattr(l, "name") else l
                        for l in (label_shapes or [])]
        for i, mod in enumerate(self._modules):
            last = i == len(self._modules) - 1
            takes = self._take_labels[i] or (last and not
                                             any(self._take_labels))
            mod.bind(cur_shapes, label_shapes if takes else None,
                     for_training=for_training,
                     inputs_need_grad=inputs_need_grad or i > 0,
                     grad_req=grad_req)
            if not last:
                shapes = dict(cur_shapes)
                if takes:
                    shapes.update(dict(label_shapes))
                _, out_shapes, _ = mod._symbol.infer_shape(
                    **{k: v for k, v in shapes.items()
                       if k in mod._symbol.list_arguments()})
                if out_shapes is None:
                    raise MXNetError(
                        f"cannot infer output shapes of module {i}")
                next_names = self._modules[i + 1]._data_names
                if len(next_names) != len(out_shapes):
                    raise MXNetError(
                        f"module {i} produces {len(out_shapes)} outputs "
                        f"but module {i + 1} declares "
                        f"{len(next_names)} data inputs {next_names}")
                cur_shapes = list(zip(next_names, out_shapes))
        self.binded = True
        return self

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    **kwargs):
        for mod in self._modules:
            mod.init_params(initializer, arg_params, aux_params, **kwargs)
        self.params_initialized = True
        return self

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, **kwargs):
        for mod in self._modules:
            mod.init_optimizer(kvstore, optimizer, optimizer_params)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        from .io import DataBatch
        batch = data_batch
        for i, mod in enumerate(self._modules):
            mod.forward(batch, is_train=is_train)
            if i < len(self._modules) - 1:
                batch = DataBatch(data=mod.get_outputs(),
                                  label=data_batch.label)

    def backward(self, out_grads=None):
        for i in range(len(self._modules) - 1, -1, -1):
            self._modules[i].backward(out_grads)
            out_grads = self._modules[i].get_input_grads() if i > 0 else None

    def update(self):
        for mod in self._modules:
            mod.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs()

    def get_params(self):
        arg_params, aux_params = {}, {}
        for mod in self._modules:
            a, x = mod.get_params()
            arg_params.update(a)
            aux_params.update(x)
        return arg_params, aux_params

    def set_params(self, arg_params, aux_params=None, **kwargs):
        # each sub-module owns only its slice of the combined dict, so
        # sibling params are expected "extras" here
        kwargs.setdefault("allow_extra", True)
        for mod in self._modules:
            mod.set_params(arg_params, aux_params, **kwargs)

    def get_input_grads(self, merge_multi_context=True):
        return self._modules[0].get_input_grads()
