"""mx.io iterator tests (SURVEY.md §2 #29)."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import io as mio


def test_ndarrayiter_batches():
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10, dtype=np.float32)
    it = mio.NDArrayIter(x, y, batch_size=4, shuffle=False)
    batches = list(it)
    assert len(batches) == 3  # 10/4 -> pad to 12
    b0 = batches[0]
    np.testing.assert_allclose(b0.data[0].asnumpy(), x[:4])
    np.testing.assert_allclose(b0.label[0].asnumpy(), y[:4])
    assert batches[-1].pad == 2


def test_ndarrayiter_discard_and_rollover():
    x = np.arange(10, dtype=np.float32)
    it = mio.NDArrayIter(x, None, batch_size=4, last_batch_handle="discard")
    assert len(list(it)) == 2
    it.reset()
    assert len(list(it)) == 2


def test_ndarrayiter_shuffle_reproducible_cover():
    x = np.arange(8, dtype=np.float32)
    it = mio.NDArrayIter(x, None, batch_size=4, shuffle=True)
    seen = np.concatenate([b.data[0].asnumpy() for b in it])
    np.testing.assert_array_equal(np.sort(seen), x)


def test_ndarrayiter_dict_data():
    data = {"a": np.zeros((6, 2), np.float32), "b": np.ones((6, 3), np.float32)}
    it = mio.NDArrayIter(data, batch_size=3)
    descs = it.provide_data
    names = sorted(d.name for d in descs)
    assert names == ["a", "b"]


def test_resizeiter():
    x = np.arange(8, dtype=np.float32)
    base = mio.NDArrayIter(x, None, batch_size=4)
    it = mio.ResizeIter(base, 5)
    assert len(list(it)) == 5  # rolls over the underlying iterator


def test_prefetchingiter():
    x = np.arange(16, dtype=np.float32)
    base = mio.NDArrayIter(x, None, batch_size=4)
    it = mio.PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 4
    seen = np.concatenate([b.data[0].asnumpy() for b in batches])
    np.testing.assert_array_equal(np.sort(seen), x)


def test_csviter():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "data.csv")
        arr = np.arange(12, dtype=np.float32).reshape(6, 2)
        np.savetxt(path, arr, delimiter=",")
        it = mio.CSVIter(data_csv=path, data_shape=(2,), batch_size=3)
        batches = list(it)
        assert len(batches) == 2
        np.testing.assert_allclose(batches[0].data[0].asnumpy(), arr[:3])


def test_imagerecorditer_synthetic():
    it = mio.ImageRecordIter(batch_size=2, data_shape=(3, 16, 16),
                             label_width=1, num_samples=6)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (2, 3, 16, 16)


def test_libsvm_iter_densifies(tmp_path):
    """LibSVMIter parses the reference on-disk format; rows densify
    (SURVEY SS8) and batch like NDArrayIter."""
    import os
    f = os.path.join(tmp_path, "data.libsvm")
    with open(f, "w") as fh:
        fh.write("1 0:1.5 3:2.0\n")
        fh.write("0 1:0.5  # trailing comment\n")
        fh.write("\n")
        fh.write("1 2:3.0 3:1.0\n")
        fh.write("0 0:2.5\n")
    it = mio.LibSVMIter(data_libsvm=f, data_shape=(4,), batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    x0 = batches[0].data[0].asnumpy()
    np.testing.assert_allclose(x0, [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), [1, 0])


def _make_rec(path, n=12, corrupt=(), img_size=8):
    """A .rec+.idx pack with optionally corrupt payloads (garbage bytes
    framed as valid records — the framing survives, decode fails)."""
    from mxnet_tpu import recordio
    rng = np.random.RandomState(0)
    idx = os.path.splitext(path)[0] + ".idx"
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(n):
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        if i in corrupt:
            blob = recordio.pack(header, b"\xba\xad" * 8)
        else:
            img = (rng.rand(img_size, img_size) * 255).astype(np.uint8)
            blob = recordio.pack_img(header, img, img_fmt=".png")
        w.write_idx(i, blob)
    w.close()


def test_imagerecorditer_skips_corrupt_records_bounded(tmp_path):
    """ISSUE 3: bounded bad-record tolerance with the
    data_records_skipped metric (reference C++ iter behaviour)."""
    from mxnet_tpu.observability import registry
    rec = os.path.join(tmp_path, "c.rec")
    _make_rec(rec, n=12, corrupt={1, 5})
    c0 = registry().counter("data_records_skipped").value
    it = mio.ImageRecordIter(path_imgrec=rec, data_shape=(1, 8, 8),
                             batch_size=5)
    batches = list(it)
    assert len(batches) == 2          # 10 good records -> 2 batches of 5
    assert it.records_skipped == 2
    assert registry().counter("data_records_skipped").value == c0 + 2
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    expect = [float(i % 3) for i in range(12) if i not in (1, 5)]
    np.testing.assert_allclose(labels, expect)
    it.reset()                        # budget is per epoch
    assert len(list(it)) == 2 and it.records_skipped == 4


def test_imagerecorditer_bad_record_budget_enforced(tmp_path):
    rec = os.path.join(tmp_path, "c2.rec")
    _make_rec(rec, n=8, corrupt={0, 2, 4})
    it = mio.ImageRecordIter(path_imgrec=rec, data_shape=(1, 8, 8),
                             batch_size=4, max_bad_records=2)
    with pytest.raises(mx.MXNetError, match="max_bad_records"):
        list(it)


def test_imagerecorditer_read_fault_retries(tmp_path):
    """ISSUE 3: transient read errors retry (io.read fault point + the
    MXTPU_IO policy) without skipping data."""
    from mxnet_tpu import fault
    from mxnet_tpu.observability import registry
    rec = os.path.join(tmp_path, "r.rec")
    _make_rec(rec, n=8)
    fault.inject("io.read", times=2)
    r0 = registry().counter("fault_retries", site="io_read").value
    try:
        it = mio.ImageRecordIter(path_imgrec=rec, data_shape=(1, 8, 8),
                                 batch_size=4)
        batches = list(it)
    finally:
        fault.clear()
    assert len(batches) == 2
    assert it.records_skipped == 0    # retried, never skipped
    assert registry().counter("fault_retries",
                              site="io_read").value >= r0 + 2


def test_prefetchingiter_surfaces_worker_error_and_stays_usable():
    """Satellite: a worker exception surfaces promptly from next() and
    the iterator keeps working (and is fully reusable after reset)."""
    class Flaky(mio.DataIter):
        def __init__(self):
            super().__init__(2)
            self.n = 0
            self.fail_once = True

        @property
        def provide_data(self):
            return []

        @property
        def provide_label(self):
            return []

        def reset(self):
            self.n = 0

        def next(self):
            self.n += 1
            if self.n == 2 and self.fail_once:
                self.fail_once = False
                raise RuntimeError("worker-boom")
            if self.n > 4:
                raise StopIteration
            return mio.DataBatch([self.n], [])

    pf = mio.PrefetchingIter(Flaky())
    assert pf.next().data[0] == 1
    with pytest.raises(RuntimeError, match="worker-boom"):
        pf.next()
    assert pf.next().data[0] == 3     # usable right after the error
    pf.reset()
    assert [b.data[0] for b in pf] == [1, 2, 3, 4]
    pf.reset()                        # reusable repeatedly
    assert [b.data[0] for b in pf] == [1, 2, 3, 4]


def test_prefetchingiter_reset_recovers_from_pending_error():
    """reset() must drain a failed in-flight fetch and resubmit — it
    used to re-raise and permanently wedge the iterator."""
    class FailFirst(mio.DataIter):
        def __init__(self):
            super().__init__(1)
            self.n = 0
            self.armed = True

        @property
        def provide_data(self):
            return []

        @property
        def provide_label(self):
            return []

        def reset(self):
            self.n = 0

        def next(self):
            self.n += 1
            if self.n == 1 and self.armed:
                self.armed = False
                raise ValueError("first-fetch-boom")
            if self.n > 2:
                raise StopIteration
            return mio.DataBatch([self.n], [])

    pf = mio.PrefetchingIter(FailFirst())   # in-flight fetch fails
    pf.reset()                              # swallow + resubmit
    assert [b.data[0] for b in pf] == [1, 2]


def test_libsvm_iter_label_file_and_multilabel(tmp_path):
    import os
    data_f = os.path.join(tmp_path, "d.libsvm")
    lab_f = os.path.join(tmp_path, "l.libsvm")
    with open(data_f, "w") as f:
        f.write("0:1.0\n2:2.0\n")         # no leading label field
    with open(lab_f, "w") as f:
        f.write("1,0\n0,1\n")             # multi-label rows
    it = mio.LibSVMIter(data_libsvm=data_f, label_libsvm=lab_f,
                        data_shape=(3,), label_shape=(2,), batch_size=2)
    b = next(iter(it))
    np.testing.assert_allclose(b.data[0].asnumpy(),
                               [[1, 0, 0], [0, 0, 2.0]])
    np.testing.assert_allclose(b.label[0].asnumpy(), [[1, 0], [0, 1]])
