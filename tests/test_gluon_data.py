"""Gluon data tests (SURVEY.md §2 #19-20): datasets, samplers, DataLoader,
vision transforms."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import gluon
from mxnet_tpu.gluon.data import (ArrayDataset, SimpleDataset, DataLoader,
                                  SequentialSampler, RandomSampler,
                                  BatchSampler)
from mxnet_tpu.gluon.data.vision import transforms, MNIST, CIFAR10


def test_array_dataset_and_transform():
    ds = ArrayDataset(np.arange(10, dtype=np.float32),
                      np.arange(10, dtype=np.float32) * 2)
    assert len(ds) == 10
    x, y = ds[3]
    assert float(y) == 6.0
    ds2 = ds.transform(lambda x, y: (x + 1, y), lazy=True)
    assert float(ds2[0][0]) == 1.0
    first = SimpleDataset(list(range(5))).transform_first(lambda x: x * 10)
    assert first[2] == 20


def test_samplers():
    assert list(SequentialSampler(5)) == [0, 1, 2, 3, 4]
    rs = list(RandomSampler(50))
    assert sorted(rs) == list(range(50)) and rs != list(range(50))
    bs = list(BatchSampler(SequentialSampler(7), 3, "keep"))
    assert bs == [[0, 1, 2], [3, 4, 5], [6]]
    bs2 = list(BatchSampler(SequentialSampler(7), 3, "discard"))
    assert bs2 == [[0, 1, 2], [3, 4, 5]]
    bs3 = list(BatchSampler(SequentialSampler(7), 3, "rollover"))
    assert bs3[0] == [0, 1, 2]


def test_dataloader_batching_shuffle_lastbatch():
    x = np.arange(10, dtype=np.float32)
    y = x * 2
    ds = ArrayDataset(x, y)
    dl = DataLoader(ds, batch_size=4, shuffle=False, last_batch="keep")
    bs = list(dl)
    assert len(bs) == 3 and bs[-1][0].shape == (2,)
    dl2 = DataLoader(ds, batch_size=4, shuffle=True, last_batch="discard")
    seen = np.concatenate([b[0].asnumpy() for b in dl2])
    assert len(seen) == 8
    dl3 = DataLoader(ds, batch_size=5, num_workers=2)
    total = sum(b[0].shape[0] for b in dl3)
    assert total == 10


def test_dataloader_batchify_structure():
    ds = SimpleDataset([(np.float32(i), np.float32(i * 2), np.float32(i * 3))
                        for i in range(6)])
    dl = DataLoader(ds, batch_size=2)
    b = next(iter(dl))
    assert len(b) == 3 and b[0].shape == (2,)


def test_vision_datasets_learnable_and_shapes():
    tr = MNIST(train=True)
    x, y = tr[0]
    assert x.shape == (28, 28, 1)
    c = CIFAR10(train=False)
    xc, yc = c[5]
    assert xc.shape == (32, 32, 3)
    # deterministic per index
    x2, y2 = tr[0]
    np.testing.assert_array_equal(x.asnumpy(), x2.asnumpy())
    # same class templates distinguishable: two samples of same class closer
    a0 = tr[0][0].asnumpy().astype(np.float32)
    a10 = tr[10][0].asnumpy().astype(np.float32)   # same class (idx % 10)
    b1 = tr[1][0].asnumpy().astype(np.float32)     # different class
    assert np.abs(a0 - a10).mean() < np.abs(a0 - b1).mean() + 30


def test_transforms():
    img = nd.array(np.random.randint(0, 255, (8, 6, 3)), dtype="uint8")
    t = transforms.ToTensor()(img)
    assert t.shape == (3, 8, 6)
    assert float(t.asnumpy().max()) <= 1.0
    norm = transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5))
    n = norm(t)
    assert n.shape == (3, 8, 6)
    assert n.asnumpy().min() >= -1.01
    res = transforms.Resize((4, 4))(img)
    assert res.shape[:2] == (4, 4)
    cc = transforms.CenterCrop((4, 4))(img)
    assert cc.shape[:2] == (4, 4)
    rc = transforms.RandomCrop(4)(img)
    assert rc.shape[:2] == (4, 4)
    f = transforms.RandomFlipLeftRight()(img)
    assert f.shape == img.shape
    comp = transforms.Compose([transforms.Resize((4, 4)),
                               transforms.ToTensor()])
    assert comp(img).shape == (3, 4, 4)


def test_dataloader_over_transformed_vision():
    ds = MNIST(train=False).transform_first(transforms.ToTensor())
    dl = DataLoader(ds, batch_size=32)
    x, y = next(iter(dl))
    assert x.shape == (32, 1, 28, 28)
    assert float(x.asnumpy().max()) <= 1.0


def test_filter_sampler_and_random_hue():
    from mxnet_tpu.gluon.data import FilterSampler, ArrayDataset
    from mxnet_tpu.gluon.data.vision import transforms
    ds = ArrayDataset(np.arange(10).astype(np.float32))
    samp = FilterSampler(lambda x: float(x) % 2 == 0, ds)
    assert list(samp) == [0, 2, 4, 6, 8] and len(samp) == 5

    img = mx.nd.random.uniform(shape=(8, 8, 3)) * 255
    out = transforms.RandomHue(0.5)(img)
    assert out.shape == (8, 8, 3)
    # hue rotation preserves luma (Y of YIQ) up to float error
    y_w = np.array([0.299, 0.587, 0.114], np.float32)
    np.testing.assert_allclose((out.asnumpy() * y_w).sum(-1),
                               (img.asnumpy() * y_w).sum(-1),
                               rtol=1e-3, atol=1e-2)
    jitter = transforms.RandomColorJitter(brightness=0.1, hue=0.1)
    assert len(jitter._ts) == 2
    assert jitter(img).shape == (8, 8, 3)


def test_image_list_dataset(tmp_path):
    import os
    from PIL import Image
    from mxnet_tpu.gluon.data.vision import ImageListDataset
    os.makedirs(os.path.join(tmp_path, "imgs"), exist_ok=True)
    lst = os.path.join(tmp_path, "data.lst")
    with open(lst, "w") as f:
        for i in range(3):
            p = os.path.join("imgs", f"im{i}.png")
            Image.new("RGB", (8, 8), (i * 40, 0, 0)).save(
                os.path.join(tmp_path, p))
            f.write(f"{i}\t{i % 2}\t{p}\n")
    ds = ImageListDataset(root=str(tmp_path), imglist=lst)
    assert len(ds) == 3
    img, label = ds[2]
    assert img.shape == (8, 8, 3) and label == 0.0
    # in-memory list form
    ds2 = ImageListDataset(root=str(tmp_path),
                           imglist=[[1.0, "imgs/im0.png"]])
    img2, label2 = ds2[0]
    assert label2 == 1.0 and img2.shape == (8, 8, 3)


# ---------------------------------------------------------------------------
# Device-resident input pipeline (ISSUE 5): DataLoader(prefetch_to_device=),
# DevicePrefetcher metrics, pin_memory mapping, abandoned-epoch cleanup.
# ---------------------------------------------------------------------------
def _prefetch_snapshot():
    from mxnet_tpu.observability import registry
    return {k: v for k, v in registry().snapshot().items()
            if k.startswith("prefetch")}


def test_device_prefetch_parity_bitwise():
    """Device-staged batches are BITWISE the host path's batches — the
    prefetcher moves placement, never values."""
    x = np.arange(120, dtype=np.float32).reshape(30, 4)
    y = np.arange(30, dtype=np.float32)
    ds = ArrayDataset(x, y)
    host = [(a.asnumpy(), b.asnumpy())
            for a, b in DataLoader(ds, batch_size=8)]
    dev = list(DataLoader(ds, batch_size=8, prefetch_to_device=True))
    assert len(host) == len(dev)
    for (ha, hb), (da, db) in zip(host, dev):
        np.testing.assert_array_equal(ha, da.asnumpy())
        np.testing.assert_array_equal(hb, db.asnumpy())
        # staged = COMMITTED placement (the point of the device mode)
        assert da._data.committed and db._data.committed


def test_device_prefetch_sharded_matches_mesh_layout():
    """A mesh placement target stages batches with the captured step's
    exact NamedSharding (leading dim over the axis), replicating leaves
    whose dim 0 does not divide it."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.prefetch import DevicePrefetcher
    mesh = make_mesh({"dp": 2})
    xb = np.arange(48, dtype=np.float32).reshape(8, 6)
    odd = np.arange(3, dtype=np.float32)          # 3 % 2 -> replicated
    pf = DevicePrefetcher(iter([(xb, odd)]), capture_spec=mesh)
    a, b = next(pf)
    assert a._data.sharding == NamedSharding(mesh, P("dp"))
    assert b._data.sharding == NamedSharding(mesh, P())
    np.testing.assert_array_equal(a.asnumpy(), xb)
    with pytest.raises(StopIteration):
        next(pf)
    pf.close()


def test_device_prefetch_metrics_depth_bytes_batches():
    from mxnet_tpu.observability import registry
    reg = registry()
    batches0 = reg.counter("prefetch_batches").value
    h2d = reg.histogram("prefetch_h2d_bytes", base=1.0)
    count0 = h2d.count
    ds = ArrayDataset(np.ones((24, 5), np.float32))
    dl = DataLoader(ds, batch_size=6, prefetch_to_device=True)
    it = iter(dl)
    first = next(it)
    # depth gauge: staging slots are in flight while the epoch runs
    assert reg.gauge("prefetch_depth").value >= 1
    rest = list(it)
    assert 1 + len(rest) == 4
    assert reg.counter("prefetch_batches").value - batches0 == 4
    assert h2d.count - count0 == 4
    # 6*5 float32 = 120 bytes per batch staged
    assert h2d.min <= 120 <= h2d.max


def test_device_prefetch_starvation_counter():
    """A slow producer + fast consumer is INPUT-BOUND: the consumer
    arrives before the head slot is ready and the starvation counter
    says so."""
    import time
    from mxnet_tpu.observability import registry
    starved = registry().counter("prefetch_starved")

    def slow(x):
        time.sleep(0.05)
        return x
    ds = ArrayDataset(np.ones((8, 3), np.float32)).transform(slow)
    before = starved.value
    n = len(list(DataLoader(ds, batch_size=2, prefetch_to_device=True)))
    assert n == 4
    assert starved.value > before


def test_dataloader_early_break_cancels_pending_prefetch():
    """Abandoning the iterator mid-epoch (early break) must DROP queued
    engine prefetch work — the dataset stops being consumed (the
    satellite fix: previously the whole epoch kept batchifying)."""
    from mxnet_tpu import engine
    from mxnet_tpu.gluon.data.dataset import Dataset

    class Counting(Dataset):
        def __init__(self, n):
            self.n = n
            self.reads = 0

        def __len__(self):
            return self.n

        def __getitem__(self, i):
            self.reads += 1
            return np.float32(i)

    ds = Counting(400)
    it = iter(DataLoader(ds, batch_size=4, prefetch=8))
    next(it)
    it.close()                    # generator close = the early-break path
    engine.wait_for_all()         # in-flight tasks finish as no-ops
    settled = ds.reads
    assert settled < 400          # the epoch was NOT fully consumed
    engine.wait_for_all()
    assert ds.reads == settled    # ...and nothing keeps running after

    # device mode: same contract through the DevicePrefetcher
    ds2 = Counting(400)
    it2 = iter(DataLoader(ds2, batch_size=4, prefetch=8,
                          prefetch_to_device=True))
    next(it2)
    it2.close()
    engine.wait_for_all()
    settled2 = ds2.reads
    assert settled2 < 400
    engine.wait_for_all()
    assert ds2.reads == settled2


def test_prefetching_iter_close_drops_pending():
    """PrefetchingIter.close()/__del__: the in-flight fetch is dropped and
    the backing iter stops being consumed; reset() reopens."""
    from mxnet_tpu import io as mio
    from mxnet_tpu import engine

    class CountingIter(mio.DataIter):
        def __init__(self):
            super().__init__(2)
            self.calls = 0

        def reset(self):
            self.calls = 0

        def next(self):
            self.calls += 1
            if self.calls > 100:
                raise StopIteration
            return mio.DataBatch([nd.array(np.ones((2, 3)))],
                                 [nd.array(np.zeros(2))])

    base = CountingIter()
    pf = mio.PrefetchingIter(base)
    pf.next()
    pf.close()
    engine.wait_for_all()
    settled = base.calls
    assert settled <= 3
    with pytest.raises(StopIteration):
        pf.next()                  # closed: no new work is queued
    engine.wait_for_all()
    assert base.calls == settled
    pf.reset()                     # reopens for reuse
    assert pf.next() is not None
    pf.close()


def test_prefetching_iter_device_mode():
    from mxnet_tpu import io as mio
    data = np.random.RandomState(0).rand(8, 4).astype(np.float32)
    base = mio.NDArrayIter(data, np.arange(8).astype(np.float32),
                           batch_size=4)
    pf = mio.PrefetchingIter(base, prefetch_to_device=True)
    batch = pf.next()
    assert batch.data[0]._data.committed
    np.testing.assert_array_equal(batch.data[0].asnumpy(), data[:4])
    pf.close()


def test_pin_memory_explicit_false_opts_out():
    """prefetch_to_device=False is an explicit opt-out: pin_memory must
    neither warn nor force device staging over it."""
    import warnings as _w
    from mxnet_tpu.gluon.data import dataloader as dl_mod
    ds = ArrayDataset(np.ones((4, 2), np.float32))
    prev = dl_mod._PIN_MEMORY_WARNED
    dl_mod._PIN_MEMORY_WARNED = False
    try:
        with _w.catch_warnings():
            _w.simplefilter("error")
            dl = DataLoader(ds, batch_size=2, pin_memory=True,
                            prefetch_to_device=False)
        assert dl._prefetch_to_device is False
    finally:
        dl_mod._PIN_MEMORY_WARNED = prev


def test_pin_memory_maps_to_device_prefetch_with_one_warning():
    """pin_memory=True is not silently ignored anymore: it maps onto the
    staging-slot path (one-time warning documents the mapping)."""
    import warnings as _w
    from mxnet_tpu.gluon.data import dataloader as dl_mod
    ds = ArrayDataset(np.ones((8, 2), np.float32))
    prev = dl_mod._PIN_MEMORY_WARNED
    dl_mod._PIN_MEMORY_WARNED = False
    try:
        with pytest.warns(UserWarning, match="prefetch_to_device"):
            dl = DataLoader(ds, batch_size=4, pin_memory=True)
        assert dl._prefetch_to_device is True
        for b in dl:
            assert b._data.committed
        with _w.catch_warnings():
            _w.simplefilter("error")      # second construction: silent
            DataLoader(ds, batch_size=4, pin_memory=True)
    finally:
        dl_mod._PIN_MEMORY_WARNED = prev


def test_device_prefetch_surfaces_worker_error_and_continues():
    """A staging error surfaces exactly once; the pipeline keeps going on
    the following batch (same contract as PrefetchingIter)."""
    from mxnet_tpu.prefetch import DevicePrefetcher

    def gen():
        yield np.ones((2, 2), np.float32)
        raise ValueError("bad batch")

    pf = DevicePrefetcher(gen(), depth=1)
    first = next(pf)
    assert first.shape == (2, 2)
    with pytest.raises(ValueError, match="bad batch"):
        next(pf)
    with pytest.raises(StopIteration):
        next(pf)
    pf.close()


def test_resolve_placement_trainer_without_kvstore():
    """A kvstore-less Trainer is a documented placement target: it
    degrades to default-device staging instead of raising."""
    import jax
    from mxnet_tpu.prefetch import resolve_placement
    net = gluon.nn.Dense(2)
    net.initialize()
    net(nd.array(np.ones((2, 3), np.float32)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    assert resolve_placement(tr) == jax.devices()[0]


def test_concurrent_device_loaders_share_the_blocking_budget():
    """Two interleaved device pipelines (train + eval) must not pin the
    whole engine pool: the blocking-slot ledger grants at most
    workers-1 slots ACROSS pipelines, and both epochs complete."""
    from mxnet_tpu import engine
    from mxnet_tpu import prefetch as pf_mod
    x = np.arange(64, dtype=np.float32).reshape(16, 4)
    ds = ArrayDataset(x)
    a = iter(DataLoader(ds, batch_size=4, prefetch_to_device=True))
    b = iter(DataLoader(ds, batch_size=4, prefetch_to_device=True))
    got_a, got_b = next(a), next(b)            # both pipelines live at once
    assert pf_mod._blocking_slots <= max(0, engine.num_workers() - 1)
    ra = [got_a] + list(a)
    rb = [got_b] + list(b)
    assert len(ra) == len(rb) == 4
    np.testing.assert_array_equal(ra[0].asnumpy(), rb[0].asnumpy())
    assert pf_mod._blocking_slots == 0         # ledger drains with the epochs


def test_prefetching_iter_close_then_reset_immediately():
    """close() immediately followed by reset() (no drain in between):
    the orphaned in-flight fetch must not race the new epoch — reset
    drains it, and the reopened iterator yields the epoch's batches in
    order with none lost."""
    from mxnet_tpu import io as mio
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    base = mio.NDArrayIter(data, np.zeros(12, np.float32), batch_size=4)
    pf = mio.PrefetchingIter(base)
    pf.next()
    pf.close()
    pf.reset()                     # no engine.wait_for_all() on purpose
    got = [pf.next().data[0].asnumpy() for _ in range(3)]
    np.testing.assert_array_equal(np.concatenate(got), data)
    pf.close()


def test_shed_background_batchify_falls_back_inline():
    """QoS backpressure (ISSUE 7): a DataLoader batchify task SHED by a
    bounded background queue is recomputed inline from its sampler
    indices — backpressure drops engine work, never training batches."""
    import threading
    import time
    from mxnet_tpu import engine
    x = np.arange(24, dtype=np.float32).reshape(12, 2)
    loader = DataLoader(ArrayDataset(x), batch_size=3, prefetch=2)
    gate = threading.Event()
    wedges = [engine.push(gate.wait) for _ in range(engine.num_workers())]
    time.sleep(0.05)
    prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 1,
                                  "shed_oldest")
    try:
        it = iter(loader)               # queues batchify tasks; sheds fire
        time.sleep(0.05)
        gate.set()
        got = [b.asnumpy() for b in it]
    finally:
        engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
        gate.set()
        engine.wait_for_all()
    np.testing.assert_allclose(np.concatenate(got, axis=0), x)
    assert all(f.done() for f in wedges)


def test_shed_staging_slot_is_restaged_not_lost():
    """QoS backpressure (ISSUE 7): a DevicePrefetcher staging slot SHED
    by a bounded background queue is re-staged — the pipeline keeps its
    depth and delivers every batch in order."""
    import threading
    import time
    from mxnet_tpu import engine
    from mxnet_tpu.prefetch import DevicePrefetcher
    gate = threading.Event()
    engine.push(gate.wait)              # occupy at least one worker
    for _ in range(max(0, engine.num_workers() - 1)):
        engine.push(gate.wait)
    time.sleep(0.05)
    prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 1,
                                  "shed_oldest")
    try:
        src = [np.full((2, 2), float(i), np.float32) for i in range(6)]
        pf = DevicePrefetcher(iter(src), depth=2)   # 2nd push sheds 1st
        time.sleep(0.05)
        gate.set()
        out = [b.asnumpy()[0, 0] for b in pf]
        pf.close()
    finally:
        engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
        gate.set()
        engine.wait_for_all()
    assert out == [float(i) for i in range(6)], out


def _wedge_and_fill_background(gate):
    """Occupy every worker (normal class) and park ONE background dummy
    in the queue so a limit-1 reject policy bounces every later
    background push deterministically."""
    import time
    from mxnet_tpu import engine
    wedges = [engine.push(gate.wait) for _ in range(engine.num_workers())]
    time.sleep(0.05)
    dummy = engine.push(lambda: None, priority=engine.PRIORITY_BACKGROUND)
    time.sleep(0.05)
    return wedges, dummy


def test_rejected_background_batchify_falls_back_inline():
    """QoS backpressure (ISSUE 7 review): a DataLoader batchify push
    REJECTED by a bounded background queue (reject policy) is computed
    inline — EngineQueueFull never escapes the training loop."""
    import threading
    from mxnet_tpu import engine
    x = np.arange(24, dtype=np.float32).reshape(12, 2)
    loader = DataLoader(ArrayDataset(x), batch_size=3, prefetch=2)
    gate = threading.Event()
    _wedge_and_fill_background(gate)
    prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 1, "reject")
    try:
        got = [b.asnumpy() for b in loader]   # every push rejects
    finally:
        engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
        gate.set()
        engine.wait_for_all()
    np.testing.assert_allclose(np.concatenate(got, axis=0), x)


def test_rejected_staging_slot_staged_synchronously():
    """QoS backpressure (ISSUE 7 review): a DevicePrefetcher staging push
    REJECTED by the bounded background class stages the slot
    synchronously — every batch still arrives, in order."""
    import threading
    from mxnet_tpu import engine
    from mxnet_tpu.prefetch import DevicePrefetcher
    gate = threading.Event()
    _wedge_and_fill_background(gate)
    prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 1, "reject")
    try:
        src = [np.full((2, 2), float(i), np.float32) for i in range(6)]
        pf = DevicePrefetcher(iter(src), depth=2)   # both slots reject
        out = [b.asnumpy()[0, 0] for b in pf]
        pf.close()
    finally:
        engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
        gate.set()
        engine.wait_for_all()
    assert out == [float(i) for i in range(6)], out


def test_rejected_submit_over_poisoned_source_drops_no_batch():
    """Regression (ISSUE 7 review): a rejected staging push that finds
    the source var POISONED by an earlier failure must NOT advance the
    source inline — the consumed item would be discarded by the failure
    recovery's _drop_pending, silently losing a batch. The fallback
    rides the poison instead; after the error surfaces, the item is
    still there to deliver."""
    import threading
    from mxnet_tpu import engine
    from mxnet_tpu.prefetch import DevicePrefetcher

    class Src:
        def __init__(self):
            self.n = 0

        def __iter__(self):
            return self

        def __next__(self):
            self.n += 1
            if self.n == 1:
                return np.full((2, 2), 1.0, np.float32)
            if self.n == 2:
                raise ValueError("bad batch")
            if self.n == 3:
                return np.full((2, 2), 3.0, np.float32)
            raise StopIteration

    pf = DevicePrefetcher(Src(), depth=2)   # s1 fails -> poisons _src_var
    for f in list(pf._pending):             # let both stages settle
        try:
            f.result(timeout=5)
        except Exception:
            pass
    gate = threading.Event()
    _wedge_and_fill_background(gate)
    prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 1, "reject")
    try:
        first = next(pf)                    # re-arm push rejects, var poisoned
        assert first.asnumpy()[0, 0] == 1.0
        with pytest.raises(ValueError, match="bad batch"):
            next(pf)
        third = next(pf)                    # the item the bug used to lose
        assert third.asnumpy()[0, 0] == 3.0
        with pytest.raises(StopIteration):
            next(pf)
        pf.close()
    finally:
        engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
        gate.set()
        engine.wait_for_all()


def test_rejected_prefetching_iter_fetches_inline():
    """QoS backpressure (ISSUE 7 review): a PrefetchingIter fetch push
    REJECTED by the bounded background class falls back to the inline
    fetch path (same as shed) — no batch lost, no EngineQueueFull."""
    import threading
    from mxnet_tpu import engine
    from mxnet_tpu import io as mio
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    gate = threading.Event()
    _wedge_and_fill_background(gate)
    prev = engine.set_queue_limit(engine.PRIORITY_BACKGROUND, 1, "reject")
    try:
        base = mio.NDArrayIter(data, np.zeros(12, np.float32), batch_size=4)
        pf = mio.PrefetchingIter(base)              # arm push rejects
        got = [pf.next().data[0].asnumpy() for _ in range(3)]
        pf.close()
    finally:
        engine.set_queue_limit(engine.PRIORITY_BACKGROUND, *prev)
        gate.set()
        engine.wait_for_all()
    np.testing.assert_array_equal(np.concatenate(got), data)
