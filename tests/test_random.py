"""mx.random tests (SURVEY.md §2 #31)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_seed_reproducibility():
    mx.random.seed(7)
    a = mx.random.uniform(shape=(16,)).asnumpy()
    mx.random.seed(7)
    b = mx.random.uniform(shape=(16,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    c = mx.random.uniform(shape=(16,)).asnumpy()
    assert not np.array_equal(b, c)  # key chain advances


def test_uniform_range_and_moments():
    x = mx.random.uniform(-2, 3, shape=(5000,)).asnumpy()
    assert x.min() >= -2 and x.max() <= 3
    assert abs(x.mean() - 0.5) < 0.1


def test_normal_moments():
    x = mx.random.normal(1.0, 2.0, shape=(5000,)).asnumpy()
    assert abs(x.mean() - 1.0) < 0.15
    assert abs(x.std() - 2.0) < 0.15


def test_randint():
    x = mx.random.randint(0, 10, shape=(1000,)).asnumpy()
    assert x.min() >= 0 and x.max() <= 9
    assert set(np.unique(x)) == set(range(10))


def test_gamma_exponential_poisson():
    g = mx.random.gamma(2.0, 2.0, shape=(3000,)).asnumpy()
    assert g.min() > 0 and abs(g.mean() - 4.0) < 0.5
    e = mx.random.exponential(2.0, shape=(3000,)).asnumpy()
    assert e.min() >= 0 and abs(e.mean() - 2.0) < 0.3
    p = mx.random.poisson(3.0, shape=(3000,)).asnumpy()
    assert abs(p.mean() - 3.0) < 0.3


def test_multinomial():
    probs = nd.array([0.0, 0.3, 0.7])
    s = mx.random.multinomial(probs, shape=2000).asnumpy().ravel()
    assert (s == 0).sum() == 0
    assert abs((s == 2).mean() - 0.7) < 0.1


def test_shuffle():
    x = nd.arange(100)
    y = mx.random.shuffle(x).asnumpy()
    assert not np.array_equal(y, np.arange(100))
    np.testing.assert_array_equal(np.sort(y), np.arange(100))


def test_nd_random_namespace():
    assert nd.random.uniform(shape=(3,)).shape == (3,)
    assert nd.random.normal(shape=(2, 2)).shape == (2, 2)


def test_dtype_and_ctx():
    x = mx.random.uniform(shape=(4,), dtype="float32")
    assert x.dtype == np.float32
    b = mx.random.bernoulli(0.5, shape=(1000,)).asnumpy()
    assert set(np.unique(b)) <= {0.0, 1.0}
