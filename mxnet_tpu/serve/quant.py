"""Low-precision serving fast path (ISSUE 14): per-channel int8 weight
snapshots for the serve executables + the KV-page byte accounting that
turns a fixed HBM budget into a page count.

Weight quantization rides the same master-weight discipline as AMP
(mxnet_tpu/amp.py): the MODEL keeps its full-precision parameters — the
server quantizes a SNAPSHOT of the decode/encode weight pytrees at
construction, so training/eager paths are untouched and a re-snapshot
(new `Server`) picks up updated masters. Each Dense leaf ``(w, b)``
becomes ``(w_int8, b, scale)`` with one symmetric scale per OUTPUT
channel (`contrib.quantization.quantize_channelwise(axis=0)`); the tied
embedding quantizes per vocabulary row (``embed_scale``). LayerNorm
parameters stay full precision, the same keep-fp32 rule as
`amp.convert_block`. Dequantization is FOLDED INTO THE DOTS by
`models.transformer._affine` / `decode_project`: the dot runs over the
exact int8 values converted in-register and the per-channel scale lands
as one epilogue multiply, so XLA fuses the whole thing into the matmul
(tools/check_fusion.py budgets the quantized-serve executables' copies).

KV byte accounting (`kv_page_bytes` / `pages_for_budget`): the decode
hot loop is memory-bandwidth-bound, so the int8 KV cache's real win is
CAPACITY — the same HBM byte budget holds int8 pages' tokens where fp32
pages held a quarter as many (per-page scale arrays included in the
arithmetic, so the claim is honest). `Server(kv_hbm_bytes=...)` sizes
its pool through `pages_for_budget`; the check_dispatch quantized-serve
phase pins the >= 1.9x token-capacity ratio.
"""
from __future__ import annotations

from ..base import MXNetError
from ..contrib.quantization import quantize_channelwise

__all__ = ["quantize_decoder_weights", "quantize_encoder_weights",
           "kv_page_bytes", "pages_for_budget", "token_capacity"]

# the Dense leaves of one decoder/encoder layer dict (LayerNorm tuples
# — ln1/ln2/ln3 — stay full precision, amp.convert_block's keep-fp32
# rule applied to the snapshot)
_DEC_DENSE = ("qkv", "sproj", "q", "kv", "cproj", "ffn1", "ffn2")
_ENC_DENSE = ("qkv", "proj", "ffn1", "ffn2")


def _quant_dense(wb):
    """(w, b) -> (w_int8, b, scale): per-output-channel symmetric int8.
    `models.transformer._affine` recognises the 3-tuple and folds the
    scale into the dot epilogue."""
    w, b = wb
    wq, scale = quantize_channelwise(w, axis=0)
    return (wq, b, scale)


def _quant_tree(weights, dense_keys):
    out = dict(weights)
    embed_q, embed_scale = quantize_channelwise(weights["embed"], axis=0)
    out["embed"] = embed_q
    out["embed_scale"] = embed_scale      # per-vocab-row (tied projection)
    out["layers"] = [
        {k: (_quant_dense(v) if k in dense_keys else v)
         for k, v in layer.items()}
        for layer in weights["layers"]]
    return out


def quantize_decoder_weights(weights):
    """Per-channel int8 snapshot of a `decoder_weights(model)` pytree
    (Server(weight_dtype="int8") decode path). The input tree is not
    mutated — the model's master weights stay full precision."""
    return _quant_tree(weights, _DEC_DENSE)


def quantize_encoder_weights(weights):
    """Per-channel int8 snapshot of an `encoder_weights(model)` pytree
    (Server(weight_dtype="int8") prefill path)."""
    return _quant_tree(weights, _ENC_DENSE)


# ------------------------------------------------- KV byte accounting
_KV_ITEMSIZE = {"float32": 4, "bfloat16": 2, "float16": 2, "int8": 1}


def kv_page_bytes(n_layers, page_size, num_heads, head_dim,
                  kv_dtype="float32"):
    """Device bytes ONE KV page costs: K + V across all decoder layers,
    plus (int8 mode) the per-page/per-head f32 scale rows the page drags
    along — included so the capacity claim is honest."""
    kv_dtype = str(kv_dtype)
    if kv_dtype not in _KV_ITEMSIZE:
        raise MXNetError(f"unknown kv_dtype {kv_dtype!r} (one of "
                         f"{sorted(_KV_ITEMSIZE)})")
    per_side = n_layers * page_size * num_heads * head_dim \
        * _KV_ITEMSIZE[kv_dtype]
    scale = n_layers * num_heads * 4 if kv_dtype == "int8" else 0
    return 2 * (per_side + scale)


def pages_for_budget(budget_bytes, n_layers, page_size, num_heads,
                     head_dim, kv_dtype="float32"):
    """Pool size (num_pages, INCLUDING the reserved null page) a fixed
    HBM byte budget affords. int8 pages are a quarter the fp32 bytes
    (half of bf16), which is directly more tokens — therefore more
    concurrent users — per chip."""
    per_page = kv_page_bytes(n_layers, page_size, num_heads, head_dim,
                             kv_dtype)
    num_pages = int(budget_bytes) // per_page
    if num_pages < 2:
        raise MXNetError(
            f"kv_hbm_bytes={budget_bytes} affords {num_pages} page(s) of "
            f"{per_page} bytes — the pool needs at least 2 (one usable + "
            f"the reserved null page)")
    return num_pages


def token_capacity(budget_bytes, n_layers, page_size, num_heads, head_dim,
                   kv_dtype="float32"):
    """Usable cached TOKENS the budget holds (null page excluded) — the
    number the >=1.9x int8-vs-fp32 acceptance pin compares."""
    return (pages_for_budget(budget_bytes, n_layers, page_size, num_heads,
                             head_dim, kv_dtype) - 1) * page_size
