#!/usr/bin/env python
"""Chaos check: train the MLP smoke model under a randomized-but-SEEDED
fault schedule and assert loss/param parity with a fault-free run.

The chaos run survives, in one process:
  * ≤5 corrupt records baked into the .rec pack (decode-skipped, bounded
    — `data_records_skipped`);
  * one async checkpoint save killed by an injected engine-task fault
    (`engine_task_failures`), recovered by a synchronous re-save;
  * a SIGTERM preemption mid-epoch (`preempt.sigterm` fault point →
    real signal → emergency checkpoint via the CheckpointManager's
    preemption hook), "restarted" by rebuilding net/trainer/iterator
    from scratch and restoring the emergency step — which must win over
    a deliberately TORN checkpoint at a higher step
    (`checkpoint_fallbacks`);
  * one injected NaN-gradient step (`grad.nan`), skipped by
    `skip_nonfinite` and retried on the same batch
    (`trainer_steps_skipped`).

Final parameters must be BITWISE identical to the uninterrupted run's
(same device count); the emergency checkpoint must additionally restore
onto a different device count (resharded template) numerically equal.

Standalone:  python tools/chaos_check.py [--seed N] [--steps N]
(one JSON line on stdout; exit 0 = parity + all recoveries observed).
Wired into tier-1 by tests/test_chaos.py.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import sys
import tempfile


def _force_cpu():
    # standalone entry: an 8-device CPU topology BEFORE jax initialises
    # (tests/conftest.py already does this under pytest)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            flags + " --xla_force_host_platform_device_count=8"
    import jax
    jax.config.update("jax_platforms", "cpu")


N_RECORDS = 48
N_CORRUPT = 4          # <= 5 per the acceptance schedule
BATCH = 8
IMG = 8                # 8x8 grayscale -> 64 flat features


def make_dataset(path, seed):
    """A .rec+.idx pack of IMG x IMG grayscale records with N_CORRUPT
    garbage payloads at seeded positions (both runs read the SAME file,
    so tolerance is exercised identically)."""
    import numpy as np
    from mxnet_tpu import recordio
    rng = np.random.RandomState(seed)
    corrupt = set(rng.choice(N_RECORDS, N_CORRUPT, replace=False).tolist())
    idx_path = os.path.splitext(path)[0] + ".idx"
    w = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(N_RECORDS):
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        if i in corrupt:
            blob = recordio.pack(header, b"\xde\xad\xbe\xef" * 4)
        else:
            img = (rng.rand(IMG, IMG) * 255).astype(np.uint8)
            blob = recordio.pack_img(header, img, img_fmt=".png")
        w.write_idx(i, blob)
    w.close()
    return sorted(corrupt)


def make_iter(rec_path):
    from mxnet_tpu import io as mio
    return mio.ImageRecordIter(path_imgrec=rec_path,
                               data_shape=(1, IMG, IMG), batch_size=BATCH)


def build(seed):
    """Deterministic net + trainer (momentum SGD so optimizer STATE must
    survive the restart too)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=IMG * IMG),
            nn.Dense(10, in_units=32))
    net.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0))
    net(nd.zeros((1, 1, IMG, IMG)))     # materialise
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            skip_nonfinite=True, max_skipped_steps=3)
    return net, trainer


def _stable_params(net):
    """(key, Parameter) pairs keyed by STRUCTURAL position, not the
    gluon auto-name — a rebuilt net in the same process draws fresh
    name counters (dense4 vs dense2), and checkpoint keys must match
    across the restart."""
    return [(f"p{i:03d}", p)
            for i, p in enumerate(net.collect_params().values())]


def params_np(net):
    import numpy as np
    return {k: np.asarray(p.data().asnumpy()) for k, p in _stable_params(net)}


def params_jnp(net):
    import jax.numpy as jnp
    return {k: jnp.asarray(p.data()._data) for k, p in _stable_params(net)}


def set_params(net, tree):
    from mxnet_tpu import nd
    import numpy as np
    for k, p in _stable_params(net):
        p.set_data(nd.array(np.asarray(tree[k])))


def trainer_states_blob(trainer):
    import tempfile as _tf
    with _tf.NamedTemporaryFile(suffix=".states", delete=False) as f:
        path = f.name
    try:
        trainer.save_states(path)
        with open(path, "rb") as f:
            return f.read()
    finally:
        os.unlink(path)


def load_trainer_states(trainer, blob):
    import tempfile as _tf
    with _tf.NamedTemporaryFile(suffix=".states", delete=False) as f:
        f.write(blob)
        path = f.name
    try:
        trainer.load_states(path)
    finally:
        os.unlink(path)


class _Loop:
    """The smoke training loop: consumes batches in deterministic order,
    retries a batch whose update was skipped (transient NaN), applies
    exactly `target` updates."""

    def __init__(self, rec_path, net, trainer, lossf):
        self.rec_path = rec_path
        self.net = net
        self.trainer = trainer
        self.lossf = lossf
        self.it = make_iter(rec_path)
        self.applied = 0
        self.last_loss = None

    def fast_forward(self, applied):
        """Replay the deterministic batch stream up to `applied` consumed
        batches (epochs are identical: no shuffle, same skips)."""
        self.applied = applied
        bpe = sum(1 for _ in make_iter(self.rec_path))
        self.it = make_iter(self.rec_path)
        for _ in range(applied % bpe):
            self._next_batch()

    def _next_batch(self):
        try:
            return next(self.it)
        except StopIteration:
            self.it.reset()
            return next(self.it)

    def run(self, target, on_applied=None):
        import mxnet_tpu as mx
        from mxnet_tpu import autograd, fault
        while self.applied < target:
            fault.check("preempt.sigterm")      # harness-armed fault point
            fault.check_preempted()
            batch = self._next_batch()
            for _attempt in range(4):
                with autograd.record():
                    out = self.net(batch.data[0])
                    loss = self.lossf(out, batch.label[0]).mean()
                loss.backward()
                self.trainer.step(BATCH)
                if self.trainer.consecutive_skipped_steps == 0:
                    break       # update applied
                # skipped (NaN/overflow): same batch, fresh grads — a
                # transient fault must not cost the batch
            else:
                raise RuntimeError("update skipped 4x on one batch")
            self.applied += 1
            self.last_loss = float(loss.asnumpy())
            if on_applied is not None:
                on_applied(self)


def _metric(name, **labels):
    from mxnet_tpu.observability import registry
    return registry().counter(name, **labels).value


def run(workdir=None, seed=0, steps=14):
    """Execute clean + chaos runs; returns the result dict (raises on
    any parity/recovery failure)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, fault, checkpoint, engine
    import jax
    import jax.numpy as jnp

    owns_dir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="mxtpu_chaos_")
    os.makedirs(workdir, exist_ok=True)
    rec_path = os.path.join(workdir, "train.rec")
    corrupt = make_dataset(rec_path, seed)
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(seed + 1)
    ckpt_at = int(rng.randint(2, 4))            # async save (killed) here
    preempt_at = int(rng.randint(5, min(9, steps - 3)))   # SIGTERM here
    nan_hit = int(rng.randint(steps - 2, steps + 1))      # late NaN step

    # ---------------------------------------------------- clean run
    fault.clear()
    fault.reset_preemption(clear_callbacks=True)
    net, trainer = build(seed)
    clean = _Loop(rec_path, net, trainer, lossf)
    clean.run(steps)
    clean_params = params_np(net)
    clean_loss = clean.last_loss

    # ---------------------------------------------------- chaos run
    ckpt_dir = os.path.join(workdir, "ckpt")
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    m0 = {k: _metric(k) for k in
          ("data_records_skipped", "engine_task_failures",
           "trainer_steps_skipped", "checkpoint_fallbacks")}

    fault.inject("grad.nan", at=[nan_hit])
    fault.inject("preempt.sigterm", at=[preempt_at + 1], action="sigterm")

    net, trainer = build(seed)
    mgr = checkpoint.CheckpointManager(ckpt_dir, max_to_keep=3)
    chaos = _Loop(rec_path, net, trainer, lossf)

    def arm_emergency():
        mgr.disable_emergency_save()
        mgr.enable_emergency_save(
            params_fn=lambda: params_jnp(net),
            step_fn=lambda: chaos.applied,
            extras_fn=lambda: {
                "trainer.states": trainer_states_blob(trainer),
                "meta.json": json.dumps(
                    {"applied": chaos.applied}).encode()})

    arm_emergency()

    def periodic(loop):
        if loop.applied != ckpt_at:
            return
        # async save whose engine task is killed by injection: the
        # failure must surface sticky (engine.failures) and the sync
        # re-save must recover
        fault.inject("engine.task", times=1)
        mgr.save(loop.applied, params_jnp(net),
                 extras={"trainer.states": trainer_states_blob(trainer),
                         "meta.json": json.dumps(
                             {"applied": loop.applied}).encode()})
        # the injected fault targets the NEXT engine task: push the async
        # flavor and watch it die
        fut = mgr.save(loop.applied, params_jnp(net), _async=True)
        try:
            mgr.wait()
            raise AssertionError("injected engine.task fault did not fire")
        except fault.FaultInjected:
            pass
        fault.clear("engine.task")
        if not engine.failures():
            raise AssertionError("engine.failures() lost the task error")
        # recover: synchronous re-save (atomic rename replaces any tear)
        mgr.save(loop.applied, params_jnp(net),
                 extras={"trainer.states": trainer_states_blob(trainer),
                         "meta.json": json.dumps(
                             {"applied": loop.applied}).encode()})

    preempted_at = None
    try:
        chaos.run(steps, on_applied=periodic)
    except fault.Preempted:
        preempted_at = chaos.applied
    if preempted_at is None:
        raise AssertionError("SIGTERM preemption never fired")

    # ------------------------------------------ simulated restart
    fault.reset_preemption()
    mgr.disable_emergency_save()
    # a torn checkpoint at a HIGHER step: restore must skip it and fall
    # back to the emergency step (counted in checkpoint_fallbacks)
    torn = os.path.join(ckpt_dir, str(steps + 100))
    os.makedirs(torn, exist_ok=True)
    with open(os.path.join(torn, "junk"), "wb") as f:
        f.write(b"\x00torn")

    net, trainer = build(seed + 999)    # deliberately different init:
    template = params_jnp(net)          # the restore must overwrite it
    template = {k: jnp.zeros_like(v) for k, v in template.items()}
    step, restored = mgr.restore_latest(template)
    if step != preempted_at:
        raise AssertionError(f"restored step {step} != emergency "
                             f"{preempted_at}")
    set_params(net, restored)
    meta = json.loads(mgr.read_extra(step, "meta.json").decode())
    load_trainer_states(trainer, mgr.read_extra(step, "trainer.states"))
    if meta["applied"] != preempted_at:
        raise AssertionError("meta/applied mismatch")

    # resharded restore of the SAME emergency checkpoint onto a smaller
    # device count (different mesh), numerically equal
    resharded_devices = 0
    if jax.device_count() >= 2:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from mxnet_tpu.parallel.mesh import make_mesh
        mesh2 = make_mesh({"dp": 2})
        tmpl2 = {k: jax.device_put(jnp.zeros_like(v),
                                   NamedSharding(mesh2, P()))
                 for k, v in template.items()}
        re2 = checkpoint.load_sharded(ckpt_dir, step, tmpl2)
        for k in restored:
            np.testing.assert_array_equal(np.asarray(re2[k]),
                                          np.asarray(restored[k]))
        resharded_devices = len(next(iter(re2.values())).sharding.device_set)

    chaos = _Loop(rec_path, net, trainer, lossf)
    chaos.fast_forward(meta["applied"])
    arm_emergency()
    chaos.run(steps)                    # NaN step fires in here, retried
    chaos_params = params_np(net)
    chaos_loss = chaos.last_loss

    mgr.disable_emergency_save()
    fault.clear()
    fault.uninstall_preemption_handler()
    fault.reset_preemption(clear_callbacks=True)

    # ---------------------------------------------------- verdicts
    mismatch = [k for k in clean_params
                if not np.array_equal(clean_params[k], chaos_params[k])]
    if mismatch:
        raise AssertionError(f"param mismatch after recovery: {mismatch}")
    if clean_loss != chaos_loss:
        raise AssertionError(f"loss mismatch {clean_loss} != {chaos_loss}")
    deltas = {k: _metric(k) - v for k, v in m0.items()}
    expect_min = {"data_records_skipped": N_CORRUPT,
                  "engine_task_failures": 1,
                  "trainer_steps_skipped": 1,
                  "checkpoint_fallbacks": 1}
    short = {k: (deltas[k], need) for k, need in expect_min.items()
             if deltas[k] < need}
    if short:
        raise AssertionError(f"recovery not visible in metrics: {short}")

    result = {
        "metric": "chaos_parity",
        "value": 1,
        "seed": seed,
        "steps": steps,
        "corrupt_records": corrupt,
        "preempted_after": preempted_at,
        "nan_step_hit": nan_hit,
        "final_loss": chaos_loss,
        "parity": "bitwise",
        "resharded_restore_devices": resharded_devices,
        **{f"delta_{k}": v for k, v in deltas.items()},
    }
    if owns_dir:
        shutil.rmtree(workdir, ignore_errors=True)
    return result


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    seed, steps = 0, 14
    if "--seed" in argv:
        seed = int(argv[argv.index("--seed") + 1])
    if "--steps" in argv:
        steps = int(argv[argv.index("--steps") + 1])
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    _force_cpu()
    try:
        res = run(seed=seed, steps=steps)
    except AssertionError as e:
        print(f"chaos_check: FAIL: {e}", file=sys.stderr)
        return 1
    print(json.dumps(res))
    print(f"chaos_check: OK (seed={seed}, parity={res['parity']})",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
