"""KVStore (reference: python/mxnet/kvstore.py + src/kvstore/*).

Backends:
  * 'local' / 'device' — single-process aggregation (reference comm tree /
    device comm); values pushed for a key are summed, pulls broadcast.
  * 'ici' — the TPU-native distributed backend replacing the reference's
    'nccl' / 'dist_sync' (BASELINE.json north star). Aggregation is a
    `jax.lax.psum` over the 'dp' axis of a `jax.sharding.Mesh`, executed via
    `shard_map`, so gradients ride the ICI interconnect and never touch the
    host. Imperative push/pull on sharded NDArrays lower to one fused XLA
    collective; inside a pjit-compiled train step the same `allreduce_`
    helper is traced straight into the step's StableHLO module.

Optimizer offload (`set_optimizer`) runs updates at pull time like the
reference's server-side update path (update_on_kvstore=True).

'ici' allreduce semantics (explicit — see `KVStore.allreduce_`): a list of
tower arrays is summed elementwise; the result is then reduced across a mesh
axis according to its layout — "stacked" (leading dim indexes replicas;
reduced away, like the reference's per-GPU push) or "replicated" (already
identical everywhere; identity). "auto" inspects `.sharding`.

Multi-host (DCN) bootstrap: `init_distributed()` wraps
`jax.distributed.initialize` (reference: src/kvstore/kvstore_dist.h ps-lite
scheduler bootstrap) so `rank`/`num_workers` are real on multi-host pods.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError, _as_list
from .ndarray.ndarray import NDArray
from .observability import tracer as _tracer
from .observability import registry as _obs_registry
from . import _env
from .fault import injection as _finj
from .fault import retry as _retry

__all__ = ["KVStore", "create", "init_distributed", "reset_distributed",
           "CollectiveTimeout", "collective_timeout_ms", "ControlPlane",
           "MemoryControlPlane", "FileControlPlane",
           "DistributedControlPlane", "control_plane"]

# always-on collective accounting (bytes entering a cross-replica reduce),
# per collective kind — the per-collective byte/latency signal motivating
# arxiv 2004.13336-style weight-update sharding decisions
_reg = _obs_registry()
_coll_bytes = {}


def _count_collective(op, nbytes, spec=None):
    """`spec` (a PartitionSpec, stringified) adds a second label so the
    rule-sharded captured step's traffic is attributable per layout —
    which rules move bytes, not just which collective kinds. Op kinds
    counted today: push/pull/broadcast (this module's host collectives),
    in_graph_psum / in_graph_reduce_scatter / spmd_grad_reduce (captured
    gradient reduction), embed_all_to_all (sparse-lookup exchange,
    shard/embedding.py) and moe_all_to_all (expert dispatch/combine,
    shard/moe.py)."""
    key = op if spec is None else (op, str(spec))
    c = _coll_bytes.get(key)
    if c is None:
        labels = {"op": op}
        if spec is not None:
            labels["spec"] = str(spec)
        c = _coll_bytes[key] = _reg.counter("kv_collective_bytes", **labels)
    c.inc(int(nbytes))


def _nbytes(a):
    try:
        return int(a.nbytes)
    except Exception:
        return 0


# ------------------------------------------------- collective deadlines
# A blocking collective on a multi-controller pod hangs FOREVER when a
# peer dies mid-rendezvous — the classic undebuggable multi-host wedge.
# MXTPU_COLLECTIVE_TIMEOUT_MS bounds every host-blocking collective in
# this module: the call runs on a daemon worker thread and a typed
# `CollectiveTimeout` raises when it misses the deadline, which the
# recovery supervisor (fault/supervisor.py) classifies as a HANG and
# answers with a post-mortem + in-process restart from checkpoint.
# Crash-only semantics: the wedged thread is abandoned (XLA offers no
# safe cancellation), so the only sound continuation is restoring from
# a checkpoint. SCOPE: the in-process restart is sound single-
# controller (the abandoned work touches only local devices). On a
# MULTI-CONTROLLER pod an abandoned collective may later unwedge and
# desynchronize this host's collective stream against its peers — there
# the right answer is a PROCESS-level restart coordinated through the
# fleet control plane (fault/fleet.py): the survivors agree on a common
# rollback step over `control_plane()` keys, re-bootstrap the
# distributed runtime (`reset_distributed` + `init_distributed`), and
# resume together — see docs/RELIABILITY.md "Fleet recovery". 0/unset
# disables (no thread, no overhead); the ``kv.timeout`` fault point
# stalls inside the deadline window so the path is testable without a
# real wedge.

class CollectiveTimeout(MXNetError):
    """A blocking collective exceeded ``MXTPU_COLLECTIVE_TIMEOUT_MS``.
    The worker thread running it is abandoned (daemon); treat the
    process's collective state as poisoned and restart from checkpoint
    (see docs/RELIABILITY.md "Recovery playbook")."""

    def __init__(self, op, timeout_ms, key=None):
        self.op = op
        self.timeout_ms = float(timeout_ms)
        self.key = key
        super().__init__(
            f"collective {op!r}{f' (key={key})' if key else ''} did not "
            f"complete within MXTPU_COLLECTIVE_TIMEOUT_MS={timeout_ms:g}ms"
            f" — peer lost or interconnect wedged")


def collective_timeout_ms():
    """The active collective deadline in ms (0 = disabled). Read from the
    environment on every call so tests/operators can toggle it live;
    malformed values fall back to 0 with a one-time warning."""
    return _env.env_ms("MXTPU_COLLECTIVE_TIMEOUT_MS", 0.0)


_deadline_tls = threading.local()


def _deadline_call(fn, op, key=None, timeout=None):
    """Run `fn` under the collective deadline (`timeout` ms; None reads
    the env — pass it when the caller already did, the per-param
    gradient path must not parse the env twice per collective). Inline
    (zero overhead) when we are already inside a deadline-bounded call
    (nested collectives share the outer bound — checked FIRST, before
    any env read) or the deadline is off. Armed mode spawns one worker
    thread per bounded collective: that is the deliberate cost of the
    opt-in knob — it buys a hang bound without a persistent watchdog
    thread's lifecycle, and fused/captured paths issue few collectives
    per step."""
    if getattr(_deadline_tls, "active", False):
        return fn()
    if timeout is None:
        timeout = collective_timeout_ms()
    if timeout <= 0:
        return fn()
    box = {}

    def worker():
        _deadline_tls.active = True    # thread-local: marks the worker
        try:
            box["r"] = fn()
        except BaseException as e:     # noqa: BLE001 — re-raised below
            box["e"] = e

    th = threading.Thread(target=worker, daemon=True,
                          name=f"mxtpu-collective-{op}")
    th.start()
    th.join(timeout / 1000.0)
    if th.is_alive():
        _reg.counter("kv_collective_timeouts", op=op).inc()
        raise CollectiveTimeout(op, timeout, key)
    if "e" in box:
        raise box["e"]
    return box.get("r")


_DIST_INITIALIZED = False


def _cluster_env():
    """Read the launcher-provided cluster spec from the environment.

    Two spellings are honoured: the reference's ps-lite variables
    (DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT/DMLC_NUM_WORKER/DMLC_WORKER_ID —
    what upstream tools/launch.py exports) and the native MXTPU_* ones
    (what tools/launch.py here exports). Returns (coord, n, rank) or
    (None, None, None)."""
    import os
    coord = os.environ.get("MXTPU_COORDINATOR")
    if coord is None and os.environ.get("DMLC_PS_ROOT_URI"):
        coord = (os.environ["DMLC_PS_ROOT_URI"] + ":"
                 + os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
    n = os.environ.get("MXTPU_NUM_WORKERS", os.environ.get("DMLC_NUM_WORKER"))
    rank = os.environ.get("MXTPU_WORKER_ID", os.environ.get("DMLC_WORKER_ID"))
    if coord and n is not None and rank is not None:
        # cluster identity must fail LOUDLY on a garbled launcher export
        # (strict parse) — a worker count degraded to a default would
        # join the wrong collective, not crash. strip() first: int()
        # historically tolerated a newline-padded env-file export, and
        # padding is not garbling
        return (coord,
                _env.parse_int(n.strip(),
                               "MXTPU_NUM_WORKERS/DMLC_NUM_WORKER"),
                _env.parse_int(rank.strip(),
                               "MXTPU_WORKER_ID/DMLC_WORKER_ID"))
    return None, None, None


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, **kwargs):
    """Initialise the multi-host runtime (DCN) so an 'ici' KVStore spans
    processes. Arguments mirror `jax.distributed.initialize`; with none
    given, the launcher env is consulted first (MXTPU_*/DMLC_* — what
    tools/launch.py exports, reference parity with the dmlc_tracker
    bootstrap), then JAX reads its own cluster env (JAX_COORDINATOR_ADDRESS
    / cloud TPU metadata). Safe to call more than once. Reference parity:
    the ps-lite scheduler/server bootstrap of kvstore_dist; here the XLA
    runtime owns rendezvous and the collectives ride ICI/DCN."""
    global _DIST_INITIALIZED
    if _DIST_INITIALIZED:
        return
    if coordinator_address is None and num_processes is None:
        coordinator_address, num_processes, process_id = _cluster_env()
    # NB: do NOT call jax.process_count() (or any backend-touching API)
    # here — it initialises the XLA backend, after which
    # jax.distributed.initialize refuses to run.
    try:
        if jax.distributed.is_initialized():
            _DIST_INITIALIZED = True
            return
    except Exception:
        pass
    def _attempt():
        if _finj.ENABLED:
            _finj.check("kv.init", context=str(coordinator_address))
        try:
            jax.distributed.initialize(coordinator_address, num_processes,
                                       process_id, **kwargs)
        except BaseException:
            # jax's State.initialize assigns service/client BEFORE the
            # connect completes and refuses to run twice; without this
            # reset every retry would die instantly on "should only be
            # called once" instead of re-attempting the rendezvous
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            raise

    explicit = coordinator_address is not None or num_processes is not None
    try:
        if explicit:
            # a cold coordinator is the NORMAL multi-host bootstrap race
            # (rank 0 may come up seconds later): exponential backoff with
            # jitter + deadline instead of one-shot failure
            _retry.policy_from_env(
                "MXTPU_DIST", max_retries=4, base_delay=0.5, max_delay=8.0,
                deadline=120.0, name="init_distributed").call(_attempt)
        else:
            _attempt()
        _DIST_INITIALIZED = True
    except Exception as e:
        if coordinator_address is not None or num_processes is not None:
            raise MXNetError(f"distributed init failed: {e}") from e
        # No explicit args: plain single-host is normal, but if cluster env
        # vars are present this is a FAILED multi-host bootstrap — warn
        # loudly instead of silently training rank-0-everywhere.
        import os
        import warnings
        if any(os.environ.get(k) for k in
               ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                "MEGASCALE_COORDINATOR_ADDRESS")):
            warnings.warn(
                f"init_distributed: cluster env detected but "
                f"jax.distributed.initialize failed ({e!r}); continuing "
                f"SINGLE-PROCESS — cross-host gradients will NOT reduce",
                RuntimeWarning, stacklevel=2)


def reset_distributed():
    """Tear down the multi-host runtime so a SURVIVOR can re-bootstrap
    after a peer died: `jax.distributed.shutdown()` + clear the
    module-level init flag, after which `init_distributed` (with its
    retry/backoff policy) may run again against a re-formed cluster.
    Safe to call when nothing was initialised. The fleet supervisor
    (fault/fleet.py) calls this between the rollback agreement and the
    re-bootstrap; single-process runs never need it."""
    global _DIST_INITIALIZED
    try:
        if jax.distributed.is_initialized():
            jax.distributed.shutdown()
    except Exception as e:
        # a half-dead client may fail its own shutdown; the flag reset
        # below still lets init_distributed re-attempt the bootstrap
        _reg.counter("kv_dist_reset_errors").inc()
        from .log import get_logger
        get_logger("mxnet_tpu.kvstore").warning(
            "reset_distributed: shutdown failed (%r) — proceeding to "
            "re-bootstrap anyway", e)
    _DIST_INITIALIZED = False


# ----------------------------------------------- fleet control plane
# Small-value coordination KEYS for the elastic fleet (fault/fleet.py):
# heartbeats, leader election, epoch counters, rollback-step agreement.
# This is the kvstore's CONTROL plane — tiny strings with atomic
# visibility — distinct from the DATA plane above (gradient
# collectives). Three backends, one duck-typed surface:
#
#   * MemoryControlPlane — in-process dict; tier-1 tests and
#     single-process fleets.
#   * FileControlPlane — one file per key on a shared directory
#     (atomic tmp+rename writes); the launcher-spawned multi-process
#     case, surviving member process restarts.
#   * DistributedControlPlane — the jax.distributed coordination
#     service's key-value store (the same rendezvous service the
#     collectives bootstrap through); multi-host pods without a shared
#     filesystem. Requires `init_distributed` to have run.

class ControlPlane:
    """Duck-typed key-value surface for fleet coordination. Values are
    strings (callers JSON-encode structure). `put` must be atomic at
    key granularity: a concurrent `get` sees the old or the new value,
    never a torn write. `put_new` must be atomic put-if-absent: of N
    concurrent callers exactly one creates the key — the arbitration
    primitive first-detector-wins races (fleet epoch claims) build on."""

    def put(self, key, value):
        raise NotImplementedError

    def put_new(self, key, value):
        """Create `key` with `value` iff it does not exist. Returns True
        when THIS call created it, False when the key already existed
        (the existing value is untouched)."""
        raise NotImplementedError

    def get(self, key, default=None):
        raise NotImplementedError

    def keys(self, prefix=""):
        raise NotImplementedError

    def delete(self, key):
        raise NotImplementedError


class MemoryControlPlane(ControlPlane):
    """In-process backend: a lock-guarded dict. Exercises the exact
    protocol code paths (heartbeats, election, agreement) without
    processes — the tier-1 test backend, and the degenerate
    single-member fleet."""

    def __init__(self):
        self._data = {}
        self._mu = threading.Lock()

    def put(self, key, value):
        with self._mu:
            self._data[str(key)] = str(value)

    def put_new(self, key, value):
        with self._mu:
            if str(key) in self._data:
                return False
            self._data[str(key)] = str(value)
            return True

    def get(self, key, default=None):
        with self._mu:
            return self._data.get(str(key), default)

    def keys(self, prefix=""):
        with self._mu:
            return sorted(k for k in self._data if k.startswith(prefix))

    def delete(self, key):
        with self._mu:
            self._data.pop(str(key), None)


class FileControlPlane(ControlPlane):
    """Shared-directory backend: one file per key, writes go through a
    same-directory tmp file + `os.replace` so readers never observe a
    torn value (POSIX rename atomicity). Keys are percent-encoded into
    filenames, so hierarchical keys ("hb/0") are fine. This is the
    backend a launcher-spawned fleet uses (MXTPU_FLEET_DIR): it
    survives member process restarts, which an in-memory or
    coordination-service store would not."""

    def __init__(self, directory):
        import os
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    @staticmethod
    def _fname(key):
        from urllib.parse import quote
        return quote(str(key), safe="")

    @staticmethod
    def _kname(fname):
        from urllib.parse import unquote
        return unquote(fname)

    def put(self, key, value):
        import os
        import tempfile
        fd, tmp = tempfile.mkstemp(prefix=".cp-", dir=self.directory)
        try:
            with os.fdopen(fd, "w") as f:
                f.write(str(value))
            os.replace(tmp, os.path.join(self.directory, self._fname(key)))
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def put_new(self, key, value):
        # write the tmp file fully, then hard-link it to the final name:
        # link() fails with EEXIST when the key exists (atomic
        # put-if-absent) and readers of a created key never see a torn
        # value (the name only appears after the write completed)
        import errno
        import os
        import tempfile
        fd, tmp = tempfile.mkstemp(prefix=".cp-", dir=self.directory)
        try:
            with os.fdopen(fd, "w") as f:
                f.write(str(value))
            try:
                os.link(tmp, os.path.join(self.directory,
                                          self._fname(key)))
            except OSError as e:
                if e.errno == errno.EEXIST:
                    return False
                raise
            return True
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def get(self, key, default=None):
        import os
        path = os.path.join(self.directory, self._fname(key))
        try:
            with open(path, "r") as f:
                return f.read()
        except OSError:
            return default

    def keys(self, prefix=""):
        import os
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        out = [self._kname(n) for n in names if not n.startswith(".cp-")]
        return sorted(k for k in out if k.startswith(prefix))

    def delete(self, key):
        import os
        try:
            os.unlink(os.path.join(self.directory, self._fname(key)))
        except OSError:
            pass


class DistributedControlPlane(ControlPlane):
    """jax.distributed coordination-service backend: the same rendezvous
    service `init_distributed` bootstraps through also exposes a
    key-value store — multi-host pods coordinate fleet state over it
    without any shared filesystem. Keys live under a namespace prefix so
    fleet traffic cannot collide with XLA's own rendezvous keys.

    Caveats: the service lives in process 0 — if THAT host dies the
    control plane dies with it (prefer FileControlPlane when a shared
    directory exists); deletes of absent keys are best-effort."""

    NAMESPACE = "mxtpu/fleet/"

    def __init__(self, client=None):
        if client is None:
            from jax._src import distributed as _dist
            client = getattr(_dist.global_state, "client", None)
        if client is None:
            raise MXNetError(
                "DistributedControlPlane needs the jax.distributed client "
                "— call init_distributed() first (or use "
                "FileControlPlane/MemoryControlPlane)")
        self._client = client

    def put(self, key, value):
        self._client.key_value_set(self.NAMESPACE + str(key), str(value),
                                   allow_overwrite=True)

    def put_new(self, key, value):
        try:
            self._client.key_value_set(self.NAMESPACE + str(key),
                                       str(value), allow_overwrite=False)
        except Exception as e:
            msg = str(e)
            if "ALREADY_EXISTS" in msg or "already exists" in msg:
                return False
            raise
        return True

    def get(self, key, default=None):
        # the client only exposes a BLOCKING get; a short deadline turns
        # it into a poll (absent key -> timeout error -> default). The
        # deadline is a poll granularity, not a correctness knob.
        timeout_ms = int(_env.env_ms("MXTPU_CP_GET_TIMEOUT_MS", 100.0))
        try:
            return self._client.blocking_key_value_get(
                self.NAMESPACE + str(key), timeout_ms)
        except Exception as e:
            # ONLY the poll expiry means "absent key". A genuine
            # coordination-service failure must propagate: swallowed
            # into `default` it would make every previously-seen peer
            # look dead at once (a spurious HostLost storm) and an
            # agreement read look permanently unpublished.
            msg = str(e)
            if "DEADLINE_EXCEEDED" in msg or "NOT_FOUND" in msg \
                    or "deadline exceeded" in msg.lower():
                return default
            raise

    def keys(self, prefix=""):
        pairs = self._client.key_value_dir_get(self.NAMESPACE + prefix)
        n = len(self.NAMESPACE)
        return sorted(k[n:] for k, _ in pairs)

    def delete(self, key):
        try:
            self._client.key_value_delete(self.NAMESPACE + str(key))
        except Exception:
            pass    # absent key: nothing to delete


def control_plane(directory=None):
    """Build the fleet control plane for this process: an explicit
    `directory` (or MXTPU_FLEET_DIR) selects `FileControlPlane`; else an
    initialised multi-host runtime selects `DistributedControlPlane`;
    else `MemoryControlPlane` (single-process)."""
    import os
    directory = directory or os.environ.get("MXTPU_FLEET_DIR")
    if directory:
        return FileControlPlane(directory)
    try:
        if jax.distributed.is_initialized():
            return DistributedControlPlane()
    except Exception:
        pass
    return MemoryControlPlane()


def _is_process_local(a):
    """True for arrays every device of which is addressable here — i.e.
    NOT an already-global pjit array whose psum XLA inserted in-step."""
    try:
        return bool(a.sharding.is_fully_addressable)
    except AttributeError:
        return True


def create(name="local"):
    """Create a KVStore. Supported: local, device, ici (+ dist aliases)."""
    if isinstance(name, KVStore):
        return name
    name = name.lower()
    if name in ("local", "local_allreduce_cpu", "local_allreduce_device"):
        return KVStore("local")
    if name in ("device", "nccl"):
        return KVStore("device")
    if name in ("ici", "dist", "dist_sync", "dist_device_sync", "dist_async",
                "horovod"):
        return KVStore("ici")
    raise MXNetError(f"unknown kvstore type {name!r}")


class KVStore:
    def __init__(self, kind):
        self._kind = kind
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._mesh = None
        self._shard_plan = None    # shard.ShardPlan (rule-driven 2-D)
        self._compression = None   # {"type": "2bit"|"int8", ...}
        self._residuals = {}       # key -> error-feedback residual (sharded)
        self._wire_cache = {}      # (shape,dtype,axis,cfg) -> jitted program
        self._flat_cache = {}      # bucket sig -> (flatten, split) jits

    def set_gradient_compression(self, compression_params):
        """Enable quantized allreduce with error feedback (reference:
        python/mxnet/kvstore.py set_gradient_compression, 2-bit with
        residuals). TPU-native re-design: instead of ps-lite server
        compression, the stacked 'ici' allreduce becomes a shard_map that
        quantizes each replica's local contribution, `all_gather`s the
        small codes over the mesh axis (a psum of codes is meaningless, so
        the exchange is gather + local dequant-sum — the same traffic
        pattern as the reference's compressed push), and keeps the
        quantization error as a per-replica residual added into the next
        step ("error feedback", which preserves convergence).

        types:
          * '2bit'  — values quantize to {-threshold, 0, +threshold}
            (threshold param, default 0.5); 4 codes pack per byte: 16x
            less wire traffic than f32.
          * 'int8'  — symmetric per-tensor scale (pmax-synced), int8
            codes: 4x less wire traffic.
        """
        p = dict(compression_params or {})
        ctype = p.get("type")
        if ctype not in ("2bit", "int8"):
            raise MXNetError(f"unsupported gradient compression {ctype!r}; "
                             "use '2bit' or 'int8'")
        p.setdefault("threshold", 0.5)
        self._compression = p
        self._residuals = {}
        return self

    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return jax.process_index() if self._kind == "ici" else 0

    @property
    def num_workers(self):
        return jax.process_count() if self._kind == "ici" else 1

    def set_mesh(self, mesh):
        """Attach a jax.sharding.Mesh (ici backend) for psum lowering.
        Invalidates compiled compressed-collective programs and residuals —
        both are placed on the old mesh — and drops any attached shard
        plan (its shardings name the old mesh; re-attach via
        set_shard_plan)."""
        self._mesh = mesh
        self._shard_plan = None
        self._wire_cache = {}
        self._residuals = {}
        return self

    def set_shard_plan(self, plan):
        """Attach a `shard.ShardPlan` (rule-driven FSDP/TP layout over a
        named 2-D mesh — mxnet_tpu/shard/). Implies `set_mesh(plan.mesh)`;
        a captured step over this store then compiles with per-parameter
        in/out shardings instead of the 1-D replicated shard_map (see
        docs/PERFORMANCE.md "Parameter sharding"). 'ici' stores only."""
        if self._kind != "ici":
            raise MXNetError("set_shard_plan needs an 'ici' kvstore "
                             f"(this store is {self._kind!r})")
        self.set_mesh(plan.mesh)
        self._shard_plan = plan
        return self

    def shard_plan(self):
        """The attached `ShardPlan`, or None (replicated 1-D lowering)."""
        return self._shard_plan

    # ------------------------------------------------------------------
    def init(self, key, value):
        keys = _as_list(key)
        values = _as_list(value)
        for k, v in zip(keys, values):
            self._store[str(k)] = NDArray(v._data)

    def push(self, key, value, priority=0, layout="auto"):
        """Aggregate values into the store (sum across devices/workers).
        `layout` forwards to allreduce_ — callers pushing whole per-param
        arrays (not replica stacks) should pin "replicated" so dim0-sharded
        values are never misread as stacks (see allreduce_ caveat)."""
        if _tracer.ACTIVE:
            with _tracer.span("kv.push", cat="kvstore",
                              args={"key": str(key), "store": self._kind}):
                return self._push_impl(key, value, priority, layout)
        return self._push_impl(key, value, priority, layout)

    def _push_impl(self, key, value, priority=0, layout="auto"):
        keys = _as_list(key)
        if len(keys) == 1 and not isinstance(value, (list, tuple)) or \
                (isinstance(value, (list, tuple))
                 and not isinstance(value[0], (list, tuple))
                 and len(keys) == 1):
            values = [_as_list(value)]
        else:
            values = [_as_list(v) for v in value]
        for k, vals in zip(keys, values):
            agg = self.allreduce_([v._data for v in vals], layout=layout,
                                  key=str(k))
            k = str(k)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"key {k} not initialised")
                self._updater(k, NDArray(agg), self._store[k])
            else:
                self._store[k] = NDArray(agg)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if _tracer.ACTIVE:
            with _tracer.span("kv.pull", cat="kvstore",
                              args={"key": str(key), "store": self._kind}):
                return self._pull_impl(key, out, priority, ignore_sparse)
        return self._pull_impl(key, out, priority, ignore_sparse)

    def _pull_impl(self, key, out=None, priority=0, ignore_sparse=True):
        keys = _as_list(key)
        outs = []
        for k in keys:
            k = str(k)
            if k not in self._store:
                raise MXNetError(f"key {k} not initialised")
            val = self._store[k]
            outs.append(val)
        if out is not None:
            flat_out = _as_list(out)
            if len(keys) == 1:
                for o in flat_out:
                    if isinstance(o, (list, tuple)):
                        for oo in o:
                            oo._assign_value(outs[0]._data)
                    else:
                        o._assign_value(outs[0]._data)
            else:
                for o, v in zip(flat_out, outs):
                    if isinstance(o, (list, tuple)):
                        for oo in o:
                            oo._assign_value(v._data)
                    else:
                        o._assign_value(v._data)
            return
        return outs[0] if len(outs) == 1 else outs

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out=out if out is not None else value, priority=priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        raise MXNetError("sparse storage is not supported on TPU "
                         "(SURVEY.md §2 #49); use dense pull")

    # ------------------------------------------------------------------
    def allreduce_(self, arrays, axis=None, layout="auto", key=None):
        """Sum tower values across data-parallel replicas.

        `arrays` (list of jax arrays) is summed elementwise — the 'local' /
        'device' comm-tree aggregation. On the 'ici' backend the result is
        then reduced across mesh axis `axis` (default: the mesh's first axis
        name) according to `layout`:

          * "replicated" — the value is already identical on every device
            (the usual state of a gradient produced by a pjit step, where
            XLA inserted the psum); the cross-replica sum is an identity.
          * "stacked"    — the leading dim indexes replicas (shape[0] a
            multiple of the axis size, sharded over it): local rows are
            summed and psum'd, and the leading dim is REDUCED AWAY, so a
            (R, *shape) stack comes back as (*shape) — matching the
            reference semantics where R workers each push shape-X grads
            and pull back the shape-X sum.
          * "auto"       — "stacked" iff `.sharding` is a NamedSharding
            whose spec partitions dim 0 over `axis`; else "replicated".

        CAVEAT on "auto": a dim0-sharded array is indistinguishable from a
        replica stack by its sharding alone — a gradient that is merely
        SHARDED over dim 0 for memory (FSDP-style) would be misread as a
        stack and lose its leading dim. Callers that know the layout must
        say so explicitly (gluon.Trainer passes layout="replicated");
        "auto" is the convention for imperative push() of stacked towers.

        With ``MXTPU_COLLECTIVE_TIMEOUT_MS`` set the whole reduce runs
        under the collective deadline and raises `CollectiveTimeout`
        instead of blocking forever (see module notes above).
        """
        timeout = collective_timeout_ms()
        if timeout <= 0:
            return self._allreduce_body(arrays, axis, layout, key)
        return _deadline_call(
            lambda: self._allreduce_body(arrays, axis, layout, key),
            "allreduce", key, timeout=timeout)

    def _allreduce_body(self, arrays, axis, layout, key):
        if _finj.ENABLED:
            # 'stall' specs here simulate a hung collective (the watchdog
            # test bed); 'raise' specs simulate a lost peer. kv.timeout is
            # the deadline-specific flavor: its stall happens INSIDE the
            # deadline window, so it deterministically produces a
            # CollectiveTimeout when one is armed
            _finj.check("kv.collective", context=f"key={key}")
            _finj.check("kv.timeout", context=f"key={key}")
        out = arrays[0]
        for a in arrays[1:]:
            out = out + a
        if self._kind != "ici":
            return out
        if self._mesh is None:
            # no mesh attached: imperative multi-PROCESS training (the
            # tools/launch.py path). A process-local array must still
            # reduce across workers — upstream dist_sync sums worker
            # gradients through ps-lite; here it's one psum over the
            # global device mesh.
            if jax.process_count() > 1 and _is_process_local(out):
                return self.allreduce_process_sum(out)
            return out
        mesh = self._mesh
        axis = axis or mesh.axis_names[0]
        if mesh.shape[axis] <= 1:
            return out
        if layout == "auto":
            layout = "stacked" if self._is_stacked(out, axis) else "replicated"
        if layout == "replicated":
            return out
        if layout != "stacked":
            raise MXNetError(f"unknown allreduce layout {layout!r}")
        if self._compression is not None and key is not None:
            return self._compressed_psum_stacked(out, axis, key)
        return self._psum_stacked(out, axis)

    @staticmethod
    def _is_stacked(a, axis):
        sh = getattr(a, "sharding", None)
        spec = getattr(sh, "spec", None)
        if not spec:
            return False
        dim0 = spec[0]
        if isinstance(dim0, (tuple, list)):
            return axis in dim0
        return dim0 == axis

    def allreduce_process_sum(self, a):
        """Sum a process-LOCAL array across all workers (imperative
        dist-sync: each process trained on its own batch and holds its own
        gradient). One shard_map psum over the global device mesh — the
        launcher-spawned CPU case and a multi-host TPU pod take the same
        path. Returns a local array equal to the cross-worker sum."""
        if jax.process_count() <= 1:
            return a
        nbytes = _nbytes(a)
        _count_collective("process_sum", nbytes)
        if _tracer.ACTIVE:
            with _tracer.span("kv.allreduce_process_sum", cat="kvstore",
                              args={"bytes": nbytes,
                                    "workers": jax.process_count(),
                                    "devices": jax.device_count()}):
                return _deadline_call(lambda: self._process_sum_impl(a),
                                      "process_sum")
        return _deadline_call(lambda: self._process_sum_impl(a),
                              "process_sum")

    def _process_sum_impl(self, a):
        import numpy as _np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from .jax_compat import shard_map
        devs = _np.asarray(jax.devices())
        mesh = Mesh(devs, ("dp",))
        ldc = jax.local_device_count()
        # one identical row per local device; the final /ldc undoes the
        # duplication so the result is exactly sum-over-processes
        local = _np.broadcast_to(_np.asarray(a)[None],
                                 (ldc,) + tuple(a.shape))
        garr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("dp")), _np.ascontiguousarray(local))
        f = shard_map(lambda x: jax.lax.psum(jnp.sum(x, axis=0), "dp"),
                      mesh=mesh, in_specs=P("dp"), out_specs=P())
        total = jax.device_get(f(garr))
        return jnp.asarray(total) / ldc

    # ----------------------------------------- bucketed (flat) allreduce
    def allreduce_flat(self, arrays, key=None):
        """Bucketed allreduce for the fused Trainer path: reduce MANY
        same-dtype per-param gradients ("replicated" layout — whole arrays,
        never replica stacks) as ONE flattened buffer, then split back.
        One collective per bucket instead of one per parameter.

        Identity fast paths return the input list untouched with zero
        dispatches: non-'ici' stores, a mesh-attached 'ici' store (a
        replicated value needs no cross-replica sum), and single-process
        runs. The flatten/split programs are jitted and cached per
        (shapes, dtype) signature."""
        if _tracer.ACTIVE:
            with _tracer.span(
                    "kv.allreduce_flat", cat="kvstore",
                    args={"bytes": sum(_nbytes(a) for a in arrays),
                          "arrays": len(arrays), "store": self._kind,
                          "devices": jax.device_count()}):
                return self._allreduce_flat_impl(arrays, key)
        return self._allreduce_flat_impl(arrays, key)

    def _allreduce_flat_impl(self, arrays, key=None):
        from . import profiler
        if len(arrays) <= 1:
            if arrays and self._kind == "ici":
                out = self.allreduce_([arrays[0]], layout="replicated",
                                      key=key)
                if out is not arrays[0]:
                    profiler.record_dispatch("kv_allreduce")
                return [out]
            return list(arrays)
        if self._kind != "ici" or self._mesh is not None:
            return list(arrays)
        if jax.process_count() <= 1:
            return list(arrays)
        local = [_is_process_local(a) for a in arrays]
        if not all(local):
            if not any(local):
                return list(arrays)
            # mixed-locality bucket (e.g. one grad came out of a pjit
            # sub-step as a global array): reduce per-param like the
            # unfused path rather than silently skipping the local ones
            out = []
            for a in arrays:
                r = self.allreduce_([a], layout="replicated", key=key)
                if r is not a:
                    profiler.record_dispatch("kv_allreduce")
                out.append(r)
            return out
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
        fns = self._flat_cache.get(sig)
        if fns is None:
            profiler.record_jit_cache(False)
            fns = self._flat_cache[sig] = self._build_flat_fns(sig)
        else:
            profiler.record_jit_cache(True)
        flatten, split = fns
        profiler.record_dispatch("kv_flatten")
        flat = flatten(list(arrays))

        def _reduce():
            if _finj.ENABLED:
                # fires ONLY where the flat path actually performs a cross-
                # worker collective (the identity/mixed fast paths above hit
                # allreduce_'s own check per array instead)
                _finj.check("kv.collective", context=f"flat key={key}")
                _finj.check("kv.timeout", context=f"flat key={key}")
            return self.allreduce_process_sum(flat)

        profiler.record_dispatch("kv_allreduce")
        red = _deadline_call(_reduce, "allreduce_flat", key)
        profiler.record_dispatch("kv_split")
        return split(red)

    @staticmethod
    def _build_flat_fns(sig):
        from .optimizer.multi_tensor import split_flat
        shapes = [shp for shp, _ in sig]
        flatten = jax.jit(
            lambda xs: jnp.concatenate([x.ravel() for x in xs]))
        split = jax.jit(lambda flat: split_flat(flat, shapes))
        return flatten, split

    # ------------------------------------- in-jit collective lowering
    # The captured train step (cachedop.py) lowers gradient reduction
    # INTO the jitted program instead of the host-driven allreduce_flat
    # round-trip: the helpers below are called while TRACING inside a
    # shard_map over this store's mesh, so the psum / reduce-scatter /
    # all-gather become ops of the step's own StableHLO module and XLA's
    # scheduler overlaps them with backward compute (arXiv:2301.13062).
    def capture_spec(self):
        """(mesh, axis, size) when a captured step should lower its
        gradient reduction in-graph over this store, else None (identity
        reduction: non-'ici' stores, no mesh, or a 1-wide axis)."""
        if self._kind != "ici" or self._mesh is None:
            return None
        axis = self._mesh.axis_names[0]
        n = int(self._mesh.shape[axis])
        if n <= 1:
            return None
        return self._mesh, axis, n

    def batch_sharding(self):
        """The `NamedSharding` a device prefetcher should stage input
        batches with so a captured step over this store consumes them
        without a second placement: leading dim over the capture_spec
        axis. None when capture_spec is None (single-device staging is
        the right call then) — see mxnet_tpu/prefetch.py."""
        if self._shard_plan is not None:
            return self._shard_plan.batch_sharding()
        spec = self.capture_spec()
        if spec is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh, axis, _ = spec
        return NamedSharding(mesh, P(axis))

    def graph_allreduce(self, g, axis, size, mean=False):
        """In-graph psum over `axis` (trace-time only — must run inside a
        shard_map over this store's mesh). `mean` folds the 1/size of a
        batch-mean loss into the same fused region."""
        out = jax.lax.psum(g, axis)
        if mean:
            out = out * (1.0 / size)
        return out

    def graph_reduce_scatter(self, g, axis, size, mean=False):
        """In-graph reduce-scatter over dim 0 (trace-time only): each
        replica gets its 1/size contiguous row-shard of the summed value —
        the gradient half of the arXiv:2004.13336 sharded weight update."""
        out = jax.lax.psum_scatter(g, axis, scatter_dimension=0, tiled=True)
        if mean:
            out = out * (1.0 / size)
        return out

    def graph_all_gather(self, x, axis):
        """In-graph all-gather over dim 0 (trace-time only): reassembles
        row-shards into the full replicated value — the parameter half of
        the sharded weight update."""
        return jax.lax.all_gather(x, axis, axis=0, tiled=True)

    def graph_constrain(self, x, spec):
        """In-graph sharding constraint for an ARBITRARY PartitionSpec
        (trace-time only, inside a jit compiled over this store's mesh):
        the generalisation of the three fixed-lowering helpers above to
        rule-driven layouts — the GSPMD partitioner materialises whatever
        collective the constraint implies (psum, reduce-scatter,
        all-gather, all-to-all). The rule-sharded captured step pins its
        gradients with this so they materialise ALREADY reduce-scattered
        into each parameter's layout instead of replicated-then-sliced."""
        from jax.sharding import NamedSharding
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self._mesh, spec))

    def _psum_stacked(self, a, axis):
        from jax.sharding import PartitionSpec as P
        from .jax_compat import shard_map
        mesh = self._mesh
        n = mesh.shape[axis]
        if a.ndim == 0 or a.shape[0] % n:
            raise MXNetError(
                f"stacked allreduce needs dim0 divisible by mesh axis "
                f"{axis!r} size {n}, got shape {a.shape}")
        _count_collective("psum_stacked", _nbytes(a))
        f = shard_map(lambda x: jax.lax.psum(jnp.sum(x, axis=0), axis),
                      mesh=mesh, in_specs=P(axis), out_specs=P())
        if _tracer.ACTIVE:
            with _tracer.span("kv.psum_stacked", cat="kvstore",
                              args={"bytes": _nbytes(a), "axis": axis,
                                    "devices": int(n)}):
                return f(a)
        return f(a)

    # ----------------------------------------- compressed collectives
    def compression_wire_fn(self, a, axis=None):
        """The compressed-allreduce program for a stacked array like `a`,
        shard_map-wrapped, exposed so tests/tools can inspect its jaxpr
        (e.g. assert the all_gather operand is uint8/int8 — the bytes that
        actually cross the interconnect). Call with (stacked, residual)
        full-shape arrays or pass to jax.make_jaxpr."""
        from jax.sharding import PartitionSpec as P
        from .jax_compat import shard_map
        axis = axis or self._mesh.axis_names[0]
        n = self._mesh.shape[axis]
        wire = self._make_wire_fn(a.shape[1:], a.dtype, axis)
        return shard_map(wire, mesh=self._mesh,
                         in_specs=(P(axis), P(axis)),
                         out_specs=(P(), P(axis)), check_vma=False)

    def _make_wire_fn(self, inner_shape, dtype, axis):
        comp = dict(self._compression)
        ctype, thr = comp["type"], float(comp["threshold"])
        size = 1
        for d in inner_shape:
            size *= int(d)

        if ctype == "2bit":
            pad = (-size) % 4
            weights = jnp.asarray([1, 4, 16, 64], jnp.uint8)

            def encode(local):
                flat = jnp.concatenate(
                    [local.ravel().astype(jnp.float32),
                     jnp.zeros((pad,), jnp.float32)]) if pad else \
                    local.ravel().astype(jnp.float32)
                codes = jnp.where(flat >= thr, jnp.uint8(1),
                                  jnp.where(flat <= -thr, jnp.uint8(2),
                                            jnp.uint8(0)))
                packed = (codes.reshape(-1, 4) * weights).sum(
                    axis=1, dtype=jnp.uint8)
                return packed, None

            def decode(packed, _meta):
                codes = jnp.stack(
                    [(packed >> s) & 3 for s in (0, 2, 4, 6)],
                    axis=1).reshape(-1)[:size]
                val = jnp.where(codes == 1, thr,
                                jnp.where(codes == 2, -thr, 0.0))
                return val.reshape(inner_shape).astype(dtype)

            def wire(rows, r):
                local = jnp.sum(rows, axis=0) + r[0]
                packed, _ = encode(local)
                gathered = jax.lax.all_gather(packed, axis)   # (n, bytes)
                total = jnp.sum(
                    jax.vmap(lambda p: decode(p, None))(gathered), axis=0)
                new_r = local - decode(packed, None)
                return total.astype(dtype), new_r[None].astype(dtype)

            wire_bytes = (size + pad) // 4
        else:  # int8
            def wire(rows, r):
                local = (jnp.sum(rows, axis=0) + r[0]).astype(jnp.float32)
                # one shared scale so the gathered codes sum exactly
                absmax = jax.lax.pmax(jnp.max(jnp.abs(local)), axis)
                scale = jnp.maximum(absmax, 1e-30) / 127.0
                codes = jnp.clip(jnp.round(local / scale),
                                 -127, 127).astype(jnp.int8)
                gathered = jax.lax.all_gather(codes, axis)  # (n, *inner)
                total = jnp.sum(gathered.astype(jnp.int32), axis=0) * scale
                new_r = local - codes.astype(jnp.float32) * scale
                return total.astype(dtype), new_r[None].astype(dtype)

            wire_bytes = size  # int8: one byte per element

        wire.wire_bytes = wire_bytes
        wire.raw_bytes = size * jnp.dtype(dtype).itemsize
        return wire

    def _compressed_psum_stacked(self, a, axis, key):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .jax_compat import shard_map
        mesh = self._mesh
        n = mesh.shape[axis]
        if a.ndim == 0 or a.shape[0] % n:
            raise MXNetError(
                f"stacked allreduce needs dim0 divisible by mesh axis "
                f"{axis!r} size {n}, got shape {a.shape}")
        inner = a.shape[1:]
        res = self._residuals.get(key)
        if res is None or res.shape != (n,) + inner:
            res = jax.device_put(jnp.zeros((n,) + inner, a.dtype),
                                 NamedSharding(mesh, P(axis)))
        cfg = (inner, str(a.dtype), axis, self._compression["type"],
               float(self._compression["threshold"]))
        entry = self._wire_cache.get(cfg)
        if entry is None:
            wire = self._make_wire_fn(inner, a.dtype, axis)
            # check_vma=False: the total IS replicated (every device sums
            # the same all_gathered codes) but the static checker cannot
            # infer replication through the decode/sum pipeline. jit the
            # shard_map and CACHE it — a fresh trace per step would
            # recompile the collective every push.
            f = jax.jit(shard_map(wire, mesh=mesh,
                                  in_specs=(P(axis), P(axis)),
                                  out_specs=(P(), P(axis)),
                                  check_vma=False))
            entry = self._wire_cache[cfg] = (f, wire)
        f, wire = entry
        _count_collective("compressed_gather", int(wire.wire_bytes))
        if _tracer.ACTIVE:
            with _tracer.span("kv.compressed_allreduce", cat="kvstore",
                              args={"wire_bytes": int(wire.wire_bytes),
                                    "raw_bytes": int(wire.raw_bytes),
                                    "devices": int(n), "key": key}):
                total, new_res = f(a, res)
        else:
            total, new_res = f(a, res)
        self._residuals[key] = new_res
        self.compression_stats = {
            "key": key, "type": self._compression["type"],
            "wire_bytes_per_replica": int(wire.wire_bytes),
            "raw_bytes_per_replica": int(wire.raw_bytes)}
        return total

    # ------------------------------------------------------------------
    def set_optimizer(self, optimizer):
        from .optimizer import get_updater, create as opt_create
        self._optimizer = opt_create(optimizer) if not hasattr(
            optimizer, "update") else optimizer
        self._updater = _KVUpdater(self._optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def save_optimizer_states(self, fname, dump_optimizer=False):
        import pickle

        def to_np(x):
            return np.asarray(x._data if isinstance(x, NDArray) else x)

        states = {}
        if self._updater is not None:
            states = {k: jax.tree_util.tree_map(to_np, v)
                      for k, v in getattr(self._updater, "states", {}).items()}
        # num_update AND the per-key counts ride along so lr schedules
        # resume at the right step — num_update is max(per-key counts), so
        # restoring it alone would stagnate until post-resume pushes catch
        # up (the reference pickles the whole updater for the same reason)
        blob = {"states": states, "num_update": 0, "index_update_count": {}}
        if self._optimizer is not None:
            blob["num_update"] = getattr(self._optimizer, "num_update", 0)
            blob["index_update_count"] = dict(
                getattr(self._optimizer, "_index_update_count", {}))
        with open(fname, "wb") as f:
            pickle.dump(blob, f)

    def load_optimizer_states(self, fname):
        import pickle
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        if self._updater is None:
            raise MXNetError("set_optimizer must be called before "
                             "load_optimizer_states")
        # accept both the {"states", "num_update"} blob and the legacy
        # bare state dict
        states = blob.get("states", blob) if isinstance(blob, dict) and \
            "states" in blob else blob
        if isinstance(blob, dict) and "num_update" in blob \
                and self._optimizer is not None:
            self._optimizer.num_update = blob["num_update"]
            self._optimizer._index_update_count = dict(
                blob.get("index_update_count", {}))
        self._updater.states = {
            k: jax.tree_util.tree_map(lambda x: NDArray(jnp.asarray(x)), v)
            for k, v in states.items()}

    def barrier(self):
        from .ndarray.ndarray import waitall
        waitall()


class _KVUpdater:
    """Server-side updater: applies optimizer at push time."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, key, grad, weight):
        if key not in self.states:
            self.states[key] = \
                self.optimizer.create_state_multi_precision(key, weight)
        self.optimizer.update_multi_precision(key, weight, grad,
                                              self.states[key])
