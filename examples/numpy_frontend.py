"""The mx.np numpy front end, end to end (reference: MXNet's "deepnumpy"
crash course). One script shows the contract: np arrays are numpy-
semantic (bool masks, 0-d reductions, np.random/np.linalg), flow through
Gluon blocks and autograd unchanged (np in -> np out), and npx carries
the nn ops numpy doesn't have.

Usage: python examples/numpy_frontend.py [--steps N] [--smoke]
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx
from mxnet_tpu import np, npx
from mxnet_tpu.gluon import nn, Trainer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    steps = 20 if args.smoke else args.steps

    npx.set_np()
    np.random.seed(0)

    # -- numpy semantics on device ---------------------------------------
    a = np.arange(12).reshape((3, 4)).astype("float32")
    print("mean (0-d):", np.mean(a))                # 0-d, numpy-style
    print("masked:", a[a > 5.0])                    # boolean mask (eager)
    u, s, vt = np.linalg.svd(a @ a.T + np.eye(3))
    print("svd singular values:", s)

    # -- np arrays through Gluon + autograd ------------------------------
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(3))
    net.initialize()
    net.hybridize()                                 # one XLA executable

    x = np.random.normal(size=(512, 16))
    w_true = np.random.normal(size=(16, 3))
    labels = np.argmax(x @ w_true, axis=1)

    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 0.01})
    for step in range(steps):
        with mx.autograd.record():
            logits = net(x)                          # np in -> np out
            logp = npx.log_softmax(logits)
            loss = -np.mean(np.take_along_axis(
                logp, labels.astype("int32").reshape(-1, 1), 1))
        loss.backward()
        trainer.step(x.shape[0])
        if step % 50 == 0 or step == steps - 1:
            acc = float(np.mean(np.argmax(logits, axis=1) == labels))
            print(f"step {step}: loss={float(loss):.4f} acc={acc:.3f}")

    assert isinstance(logits, np.ndarray)
    final_acc = float(np.mean(np.argmax(net(x), axis=1) == labels))
    if not args.smoke:
        assert final_acc > 0.9, final_acc
    npx.reset_np()
    print("final accuracy:", final_acc)


if __name__ == "__main__":
    main()
