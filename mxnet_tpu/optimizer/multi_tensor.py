"""Fused multi-tensor optimizer updates for the imperative Trainer path.

The per-param imperative path costs one XLA dispatch per gradient for the
allreduce plus one jitted `apply` per parameter — O(num_params) launches
per `Trainer.step()`, the dispatch-bound regime the XLA-fusion literature
targets. This subsystem makes one step cost O(num_buckets):

  * `build_buckets` groups parameters into dtype-homogeneous, byte-capped
    buckets (cap = `engine.get_bulk_size()`; 0 keeps the reference
    "unbulked" meaning — one parameter per bucket).
  * `KVStore.allreduce_flat` (kvstore.py) reduces each bucket's gradients
    as ONE flattened buffer — one collective per bucket instead of one per
    parameter.
  * `FusedUpdater` compiles ONE jitted multi-tensor update per
    (optimizer, bucket signature): the whole bucket's weights / grads /
    optimizer states go through a single XLA executable that applies the
    optimizer's pure `apply` rule per parameter — including
    `multi_precision` fp32 master weights and folded AMP unscale — with
    the state buffers donated. lr/wd/rescale/inv-scale ride in as
    weak-typed traced scalars, so schedules and loss-scale changes never
    retrace.

Numerics mirror `Optimizer.update` / `update_multi_precision` op for op,
so the fused path matches the per-param path bit for bit (up to XLA's
fp32 reassociation inside a fused region).

Telemetry (profiler.py): every kernel launch is tallied via
`profiler.record_dispatch`, kernel-cache lookups via
`profiler.record_jit_cache`, bucket layouts via `profiler.record_buckets`
— all surfaced in `profiler.dumps()`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import profiler
from ..observability import compilex as _compilex
from .updater import Updater
from .optimizer import Optimizer, DCASGD

__all__ = ["FusedUpdater", "build_buckets", "bucket_signature", "supports",
           "flat_layout", "split_flat", "apply_param_update",
           "sparse_update_rows", "classify_state_rows"]


def flat_layout(shapes):
    """(sizes, offsets, total) for packing arrays of `shapes` into one
    flat buffer — the one offset table shared by the kvstore bucketed
    allreduce and the fused SGD kernels."""
    sizes = [int(np.prod(shp, dtype=np.int64)) if shp else 1
             for shp in shapes]
    offs, total = [], 0
    for sz in sizes:
        offs.append(total)
        total += sz
    return sizes, offs, total


def split_flat(flat, shapes):
    """Inverse of a ravel+concatenate pack over arrays of `shapes`."""
    sizes, offs, _ = flat_layout(shapes)
    return [jax.lax.dynamic_slice_in_dim(flat, off, sz).reshape(shp)
            for off, sz, shp in zip(offs, sizes, shapes)]


def supports(optimizer):
    """True when the optimizer's imperative semantics are fully captured by
    its pure `apply` rule, so the fused kernel reproduces the per-param
    path exactly. Excluded:

      * subclasses overriding `update` / `update_multi_precision` /
        `create_state_multi_precision` / `_preprocess` (custom imperative
        behaviour the kernel would not see — the kernel inlines the BASE
        rescale+clip preprocessing);
      * DCASGD — its `init_state` aliases the live weight buffer as the
        delay-compensation state, which is unsafe with donated state
        buffers.
    """
    t = type(optimizer)
    if isinstance(optimizer, DCASGD):
        return False
    return (t.update is Optimizer.update
            and t.update_multi_precision is Optimizer.update_multi_precision
            and t.create_state_multi_precision
            is Optimizer.create_state_multi_precision
            and t._preprocess is Optimizer._preprocess)


# lr/wd/rescale_grad ride into the kernel as traced scalars and the update
# counters change every step — everything else scalar in the optimizer's
# __dict__ (momentum, betas, epsilon, clip_gradient, bounds, ...) gets
# baked in at trace time and must key the kernel cache
_NON_HYPER = frozenset({"lr", "wd", "rescale_grad", "num_update"})


def _hyper_sig(optimizer):
    """Snapshot of the scalar hyperparameters `apply` closes over, so
    mid-run mutation (opt.momentum = 0.0, opt.beta1 = ...) recompiles the
    fused kernel instead of silently reusing stale trace-time constants —
    matching the per-param path, which reads them eagerly every step."""
    return tuple(sorted(
        (k, v) for k, v in vars(optimizer).items()
        if k not in _NON_HYPER
        and isinstance(v, (int, float, bool, str, type(None)))))


def _grad_nbytes(p):
    g = p.grad()._data
    return int(g.size) * jnp.dtype(g.dtype).itemsize


def build_buckets(pairs, cap_bytes):
    """Group an ordered list of (index, Parameter) into dtype-homogeneous
    buckets of at most `cap_bytes` gradient bytes (cap <= 0: one parameter
    per bucket). A single parameter larger than the cap still gets its own
    bucket. Order within and across buckets is declaration order, so the
    layout is deterministic."""
    buckets, cur, cur_key, cur_bytes = [], [], None, 0
    for i, p in pairs:
        key = (str(p.data().dtype), str(p.grad().dtype))
        nbytes = _grad_nbytes(p)
        if cur and (key != cur_key or cap_bytes <= 0
                    or cur_bytes + nbytes > cap_bytes):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur_key = key
        cur.append((i, p))
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def bucket_signature(bucket, optimizer):
    """Static kernel-cache key for one bucket: per-param shapes/dtypes,
    state layout, and multi-precision role."""
    sig = []
    for idx, p in bucket:
        w = p.data()._data
        g = p.grad()._data
        mp = bool(optimizer.multi_precision and w.dtype != np.float32)
        sig.append((tuple(w.shape), str(w.dtype), str(g.dtype), mp))
    return tuple(sig)


def apply_param_update(optimizer, w, g, sv, lr, wd, mp, clip, rescale,
                       inv_scale=None):
    """ONE parameter's in-graph optimizer application, staged exactly like
    `Optimizer.update` / `update_multi_precision` — optional folded AMP
    unscale, f32 upcast, rescale, clip, dtype-matched downcast, `apply`,
    master-weight downcast, and state-arity passthrough (if a
    hyperparameter mutation shrank apply()'s state arity, e.g.
    momentum -> 0, the untouched slots pass through so every donated
    input buffer has a live output and the stale-state-kept semantics
    match the per-param path). The single source of the fused numerics,
    shared by the bucketed `_make_kernel` and the captured-step program
    (mxnet_tpu/cachedop.py). Returns `(new_w, new_state_tuple,
    unscaled_grad_or_None)`."""
    out_g = None
    if inv_scale is not None:
        g = g * inv_scale
        out_g = g
    gg = g if g.dtype == jnp.float32 else g.astype(jnp.float32)
    gg = gg * rescale
    if clip is not None:
        gg = jnp.clip(gg, -clip, clip)
    if mp:
        master, rest = sv[0], tuple(sv[1:])
        new_m, new_s = optimizer.apply(master, gg, rest, lr, wd)
        new_w = new_m.astype(w.dtype)
        full = (new_m,) + tuple(new_s)
    else:
        if gg.dtype != w.dtype:
            gg = gg.astype(w.dtype)
        new_w, new_s = optimizer.apply(w, gg, tuple(sv), lr, wd)
        full = tuple(new_s)
    return new_w, full + tuple(sv[len(full):]), out_g


def sparse_update_rows(optimizer, w_rows, g_rows, sv_rows, lr, wd, mp,
                       clip, rescale, inv_scale=None):
    """The scatter-add arm of the multi-tensor update (ISSUE 15): stage
    ONE gathered row block of a row-sharded embedding table through the
    exact `apply_param_update` numerics — folded AMP unscale, f32
    upcast, rescale, clip, optional fp32 master rows, the optimizer's
    elementwise `apply` — so the sparse fast path's touched rows update
    bit-for-bit like the dense path would update them. Only valid for
    `Optimizer.elementwise` rules (cachedop gates eligibility on it):
    an elementwise `apply` restricted to the touched rows IS the dense
    update restricted to those rows; untouched rows keep their weight
    AND state (MXNet's lazy/sparse-update semantics — wd and
    momentum-style state decay touch looked-up rows only). Scalar state
    leaves (Adam's step counter) ride whole and update once.
    The caller scatters the returned rows back on the owning shard
    (shard/embedding.py `sparse_row_update`)."""
    return apply_param_update(optimizer, w_rows, g_rows, sv_rows, lr, wd,
                              mp, clip, rescale, inv_scale)


def classify_state_rows(optimizer, index, probe_nd):
    """How each row-shaped optimizer-state leaf initialises, probed on a
    tiny weight slice — what lets a TIERED table's host-resident state
    rows materialise lazily (shard/tiered.py): a row that has never been
    looked up has never been updated, so its state rows are still
    exactly their init values, and the host tier can synthesise them on
    demand instead of holding O(vocab) device state.

    Returns one entry per state leaf of
    ``create_state_multi_precision(index, probe)``:

      "zero"    — the leaf initialises all-zero (momentum, Adam m/v,
                  RMSProp n, ...): cold host rows are zeros
      "master"  — the leaf initialises as a cast of the weight (fp32
                  master under multi_precision): cold host rows are the
                  host weight cast to the leaf dtype
      None      — not row-shaped (scalar step counters, ...): rides
                  whole on-device, never tiered

    A row-shaped leaf matching neither pattern raises: the host tier
    could not reconstruct evicted rows for it, and training through a
    wrong reconstruction would corrupt silently."""
    st = optimizer.create_state_multi_precision(index, probe_nd)
    leaves = st if isinstance(st, tuple) else \
        ((st,) if st is not None else ())
    probe = np.asarray(probe_nd._data)
    if not probe.size or not np.any(probe.astype(np.float64)):
        from ..base import MXNetError
        raise MXNetError(
            "classify_state_rows: the probe slice is all-zero — a "
            "weight-cast (fp32 master) leaf is indistinguishable from "
            "a zero-initialised one on it; probe with synthetic "
            "nonzero rows, never real table rows")
    kinds = []
    for j, s in enumerate(leaves):
        v = np.asarray(getattr(s, "_data", s))
        if tuple(v.shape) != tuple(probe.shape):
            kinds.append(None)
            continue
        if not v.any():
            kinds.append("zero")
        elif np.array_equal(v, probe.astype(v.dtype)):
            kinds.append("master")
        else:
            from ..base import MXNetError
            raise MXNetError(
                f"tiered embedding: optimizer "
                f"{type(optimizer).__name__} state leaf {j} initialises "
                f"to neither zeros nor a cast of the weight — its "
                f"host-resident rows cannot be reconstructed after "
                f"eviction; train this table fully resident "
                f"(tiered=False) or use an optimizer whose row state "
                f"initialises from the weight")
    return tuple(kinds)


def _make_kernel(optimizer, mp_flags, clip, unscale, n):
    """Trace ONE jitted update over a whole bucket (per-param staging:
    `apply_param_update`), so a bucket of n parameters compiles to a
    single XLA executable instead of n launches. When `unscale` is set the
    AMP 1/loss_scale multiply is folded in and the unscaled per-param
    gradients come back as outputs (so `p.grad()` observes the same value
    the per-param path leaves behind). State buffers are donated: for
    Adam-family optimizers that is the bulk of the update's memory
    traffic."""

    def kernel(weights, grads, states, lrs, wds, rescale, inv):
        new_ws, new_ss, out_gs = [], [], []
        for i in range(n):
            new_w, full, out_g = apply_param_update(
                optimizer, weights[i], grads[i], states[i], lrs[i], wds[i],
                mp_flags[i], clip, rescale, inv if unscale else None)
            new_ws.append(new_w)
            new_ss.append(full)
            if out_g is not None:
                out_gs.append(out_g)
        return new_ws, new_ss, out_gs

    # autotune (ISSUE 20): an optimizer update tolerates fp
    # re-association within the documented training tolerance — the
    # contract the search guard compares candidate outputs against
    from .. import tune as _tune
    _tune.register_contract("fused_update", "allclose", rtol=1e-5,
                            atol=1e-7)
    return _compilex.instrument(jax.jit(kernel, donate_argnums=(2,)),
                                "fused_update")


class FusedUpdater(Updater):
    """Updater that applies a whole bucket of parameters in one fused
    dispatch. Shares the per-index `states` dict with the plain Updater,
    so `Trainer.save_states`/`load_states` and the per-param `__call__`
    fallback keep working unchanged."""

    def __init__(self, optimizer):
        super().__init__(optimizer)
        self._kernels = {}

    def update_bucket(self, bucket, inv_scale=None):
        """Apply one optimizer step to every (index, Parameter) in
        `bucket` via a single cached jitted kernel. `inv_scale` (AMP
        1/loss_scale) is folded into the kernel when given; the kernel
        then also rebinds each param's gradient to its unscaled value."""
        opt = self.optimizer
        weights, grads, states, state_nds = [], [], [], []
        lrs, wds, mp_flags = [], [], []
        for idx, p in bucket:
            w = p.data()
            if idx not in self.states:
                self.states[idx] = \
                    opt.create_state_multi_precision(idx, w)
            opt._update_count(idx)
            lrs.append(float(opt._get_lr(idx)))
            wds.append(float(opt._get_wd(idx)))
            st = self.states[idx]
            st = st if isinstance(st, tuple) else \
                ((st,) if st is not None else ())
            mp_flags.append(bool(opt.multi_precision
                                 and w.dtype != np.float32))
            weights.append(w._data)
            grads.append(p.grad()._data)
            states.append(tuple(s._data for s in st))
            state_nds.append(st)

        unscale = inv_scale is not None
        clip = None if opt.clip_gradient is None else float(opt.clip_gradient)
        # state avals belong in the key: load_states() can swap in state
        # arrays with different shapes/dtypes without touching the bucket
        # signature, and jax would retrace while the telemetry claimed a hit
        state_sig = tuple(tuple((tuple(s.shape), str(s.dtype)) for s in sv)
                          for sv in states)
        key = (bucket_signature(bucket, opt), state_sig, _hyper_sig(opt),
               unscale)
        kern = self._kernels.get(key)
        if kern is None:
            profiler.record_jit_cache(False)
            kern = self._kernels[key] = _make_kernel(
                opt, tuple(mp_flags), clip, unscale, len(bucket))
        else:
            profiler.record_jit_cache(True)
        profiler.record_dispatch("fused_update")
        # python-float lr/wd/rescale/inv become weak-typed f32 tracers:
        # identical promotion to the per-param path's python scalars, and
        # value changes (lr schedules, loss-scale moves) hit the jit cache
        new_ws, new_ss, out_gs = kern(
            weights, grads, states, tuple(lrs), tuple(wds),
            float(opt.rescale_grad),
            0.0 if inv_scale is None else float(inv_scale))

        for (idx, p), new_w, new_s, st in zip(bucket, new_ws, new_ss,
                                              state_nds):
            p.data()._rebind(new_w)
            for s_nd, s_val in zip(st, new_s):
                s_nd._rebind(s_val)
        if out_gs:
            for (idx, p), g in zip(bucket, out_gs):
                p.grad()._rebind(g)
