"""mx.rnn.BucketSentenceIter + BucketingModule: the classic bucketed
LM training flow (reference: python/mxnet/rnn/io.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.module import BucketingModule


def _sentences(n=200, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ln = rs.choice([4, 6, 8])
        # deterministic next-token structure: w_{t+1} = (w_t + 1) % V
        start = rs.randint(0, 16)
        out.append([(start + t) % 16 for t in range(ln)])
    return out


def test_bucket_sentence_iter_shapes():
    it = mx.rnn.BucketSentenceIter(_sentences(), batch_size=8,
                                   buckets=[4, 6, 8])
    seen = set()
    n_batches = 0
    for batch in it:
        seen.add(batch.bucket_key)
        assert batch.data[0].shape == (8, batch.bucket_key)
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        np.testing.assert_allclose(l[:, :-1], d[:, 1:])  # shifted target
        assert (l[:, -1] == -1).all()
        n_batches += 1
    assert seen == {4, 6, 8} and n_batches > 3
    it.reset()
    assert sum(1 for _ in it) == n_batches


def test_bucket_sentence_iter_overlong_skipped():
    sents = [[1, 2, 3], [1] * 50]
    it = mx.rnn.BucketSentenceIter(sents, batch_size=1, buckets=[4])
    assert it.skipped == 1


def test_bucketing_module_lm_training():
    """Train a tiny embedding LM over three bucket lengths with shared
    params; loss must fall and all buckets must share weights."""
    def sym_gen(seq_len):
        with mx.name.NameManager():
            data = sym.Variable("data")
            label = sym.Variable("softmax_label")
            emb = sym.Embedding(data, input_dim=16, output_dim=16,
                                name="embed")
            h = sym.FullyConnected(
                sym.reshape(emb, (-1, 16)), num_hidden=16, name="out")
            out = sym.SoftmaxOutput(h, sym.reshape(label, (-1,)),
                                    use_ignore=True, ignore_label=-1,
                                    name="softmax")
        return out, ["data"], ["softmax_label"]

    mod = BucketingModule(sym_gen, default_bucket_key=8)
    it = mx.rnn.BucketSentenceIter(_sentences(400), batch_size=16,
                                   buckets=[4, 6, 8])
    mod.fit(it, num_epoch=4, optimizer="adam",
            optimizer_params={"learning_rate": 0.02},
            eval_metric=mx.metric.Perplexity(ignore_label=-1))
    # accuracy on next-token prediction: the mapping is deterministic, so
    # a learned model beats 1/16 chance decisively (padding rows drag the
    # ceiling below 1.0)
    m = mx.metric.create("acc")
    it.reset()
    for batch in it:
        mod.forward(batch, is_train=False)
        mod.update_metric(m, [nd.array(
            batch.label[0].asnumpy().reshape(-1))])
    assert m.get()[1] > 0.5, m.get()


def test_bucket_sentence_iter_layout_dtype():
    it = mx.rnn.BucketSentenceIter(_sentences(), batch_size=8,
                                   buckets=[4, 6, 8], layout="TN",
                                   dtype="int32")
    b = next(it)
    assert b.data[0].shape == (b.bucket_key, 8)  # time-major
    assert b.data[0].dtype == np.int32
    assert it.provide_data[0].shape == (8, 8)
    import pytest
    with pytest.raises(mx.base.MXNetError):
        mx.rnn.BucketSentenceIter(_sentences(), 8, buckets=[4],
                                  layout="NTC")


def test_softmax_output_normalization():
    """'valid' divides by the non-ignored count; 'batch' by the leading
    dim (reference softmax_output-inl.h scaling)."""
    x = sym.Variable("x")
    y = sym.Variable("y")
    xv = nd.array(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    yv = nd.array(np.array([0, 2, -1, -1], np.float32))

    def grad_for(**kw):
        out = sym.SoftmaxOutput(x, y, **kw)
        ex = out.bind(None, {"x": xv, "y": yv},
                      {"x": nd.zeros((4, 3)), "y": nd.zeros((4,))})
        ex.forward(is_train=True)
        ex.backward()
        return ex.grad_dict["x"].asnumpy()

    g_null = grad_for(use_ignore=True)
    g_valid = grad_for(use_ignore=True, normalization="valid")
    g_batch = grad_for(use_ignore=True, normalization="batch")
    np.testing.assert_allclose(g_valid, g_null / 2.0, rtol=1e-6)  # 2 valid
    np.testing.assert_allclose(g_batch, g_null / 4.0, rtol=1e-6)
    import pytest
    with pytest.raises(mx.base.MXNetError):
        sym.SoftmaxOutput(x, y, normalization="bogus")
