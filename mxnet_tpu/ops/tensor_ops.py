"""Tensor ops: elemwise, broadcast, reduce, shape manipulation.

Reference parity: src/operator/tensor/* (elemwise_binary_op, broadcast_reduce,
matrix_op, indexing_op). Every function takes/returns NDArrays and dispatches
through the single imperative entry point `_apply`, so autograd records them.
Reference-style `broadcast_*` aliases are provided because jnp broadcasts by
default — they are the same XLA op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray, _apply, _binary, _lift

__all__ = [
    # elemwise binary
    "add", "subtract", "multiply", "divide", "modulo", "power", "maximum",
    "minimum", "hypot", "broadcast_add", "broadcast_sub", "broadcast_mul",
    "broadcast_div", "broadcast_mod", "broadcast_power", "broadcast_maximum",
    "broadcast_minimum", "broadcast_equal", "broadcast_not_equal",
    "broadcast_greater", "broadcast_greater_equal", "broadcast_lesser",
    "broadcast_lesser_equal", "broadcast_logical_and", "broadcast_logical_or",
    "broadcast_logical_xor", "broadcast_like", "broadcast_to", "broadcast_axis",
    # elemwise unary
    "abs", "sign", "round", "rint", "ceil", "floor", "trunc", "fix", "square",
    "sqrt", "rsqrt", "cbrt", "rcbrt", "exp", "expm1", "log", "log10", "log2",
    "log1p", "sin", "cos", "tan", "arcsin", "arccos", "arctan", "sinh", "cosh",
    "tanh", "arcsinh", "arccosh", "arctanh", "reciprocal", "negative",
    "logical_not", "erf", "erfinv", "gamma", "gammaln", "clip",
    "relu6", "log_sigmoid", "mish",
    # reduce
    "sum", "nansum", "mean", "prod", "nanprod", "max", "min", "norm", "argmax",
    "argmin", "sum_axis", "max_axis", "min_axis",
    # shape
    "reshape", "reshape_like", "flatten", "transpose", "expand_dims", "squeeze",
    "concat", "concatenate", "stack", "split", "split_v2", "tile",
    "repeat", "pad", "masked_softmax", "cast_storage",
    "slice", "slice_axis", "slice_like", "flip", "reverse", "swapaxes",
    "depth_to_space", "space_to_depth", "moveaxis", "rollaxis",
    "array_split",
    # indexing / selection
    "take", "pick", "gather_nd", "scatter_nd", "where", "boolean_mask",
    "one_hot", "topk", "sort", "argsort", "shuffle", "diag",
    # misc
    "dot", "batch_dot", "add_n", "ElementWiseSum", "cast", "Cast",
    "zeros_like", "ones_like", "shape_array", "size_array", "cumsum", "Pad",
]


def _unary_factory(fn):
    def op(data, **kwargs):
        return _apply(fn, [data])
    return op


def _binary_factory(fn):
    def op(lhs, rhs, **kwargs):
        if not isinstance(lhs, NDArray):
            lhs = _lift(lhs)
            if not isinstance(lhs, NDArray):   # scalar-scalar degenerate
                return fn(lhs, rhs)
        return _binary(fn, lhs, rhs)
    return op


def _cmp(fn):
    return _binary_factory(lambda a, b: fn(a, b).astype(jnp.float32))


# -- elemwise binary ---------------------------------------------------------
add = broadcast_add = _binary_factory(jnp.add)
subtract = broadcast_sub = _binary_factory(jnp.subtract)
multiply = broadcast_mul = _binary_factory(jnp.multiply)
divide = broadcast_div = _binary_factory(jnp.divide)
modulo = broadcast_mod = _binary_factory(jnp.mod)
power = broadcast_power = _binary_factory(jnp.power)
maximum = broadcast_maximum = _binary_factory(jnp.maximum)
minimum = broadcast_minimum = _binary_factory(jnp.minimum)
hypot = _binary_factory(jnp.hypot)
broadcast_equal = _cmp(jnp.equal)
broadcast_not_equal = _cmp(jnp.not_equal)
broadcast_greater = _cmp(jnp.greater)
broadcast_greater_equal = _cmp(jnp.greater_equal)
broadcast_lesser = _cmp(jnp.less)
broadcast_lesser_equal = _cmp(jnp.less_equal)
broadcast_logical_and = _cmp(jnp.logical_and)
broadcast_logical_or = _cmp(jnp.logical_or)
broadcast_logical_xor = _cmp(jnp.logical_xor)


def broadcast_to(data, shape):
    return data.broadcast_to(shape)


def broadcast_like(lhs, rhs):
    return lhs.broadcast_like(rhs)


def broadcast_axis(data, axis, size):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    sizes = size if isinstance(size, (list, tuple)) else [size]

    def fn(a, _axes=tuple(axes), _sizes=tuple(sizes)):
        shape = list(a.shape)
        for ax, s in zip(_axes, _sizes):
            shape[ax] = s
        return jnp.broadcast_to(a, tuple(shape))
    return _apply(fn, [data])


# -- elemwise unary ----------------------------------------------------------
abs = _unary_factory(jnp.abs)
sign = _unary_factory(jnp.sign)
round = _unary_factory(jnp.round)
rint = _unary_factory(jnp.rint)
ceil = _unary_factory(jnp.ceil)
floor = _unary_factory(jnp.floor)
trunc = _unary_factory(jnp.trunc)
fix = _unary_factory(jnp.trunc)
square = _unary_factory(jnp.square)
sqrt = _unary_factory(jnp.sqrt)
rsqrt = _unary_factory(jax.lax.rsqrt)
cbrt = _unary_factory(jnp.cbrt)
rcbrt = _unary_factory(lambda a: 1.0 / jnp.cbrt(a))
exp = _unary_factory(jnp.exp)
expm1 = _unary_factory(jnp.expm1)
log = _unary_factory(jnp.log)
log10 = _unary_factory(jnp.log10)
log2 = _unary_factory(jnp.log2)
log1p = _unary_factory(jnp.log1p)
sin = _unary_factory(jnp.sin)
cos = _unary_factory(jnp.cos)
tan = _unary_factory(jnp.tan)
arcsin = _unary_factory(jnp.arcsin)
arccos = _unary_factory(jnp.arccos)
arctan = _unary_factory(jnp.arctan)
sinh = _unary_factory(jnp.sinh)
cosh = _unary_factory(jnp.cosh)
tanh = _unary_factory(jnp.tanh)
arcsinh = _unary_factory(jnp.arcsinh)
arccosh = _unary_factory(jnp.arccosh)
arctanh = _unary_factory(jnp.arctanh)
reciprocal = _unary_factory(jnp.reciprocal)
negative = _unary_factory(jnp.negative)
logical_not = _unary_factory(lambda a: jnp.logical_not(a).astype(jnp.float32))
relu6 = _unary_factory(jax.nn.relu6)
log_sigmoid = _unary_factory(jax.nn.log_sigmoid)
mish = _unary_factory(jax.nn.mish)
erf = _unary_factory(jax.scipy.special.erf)
erfinv = _unary_factory(jax.scipy.special.erfinv)
gamma = _unary_factory(lambda a: jnp.exp(jax.scipy.special.gammaln(a)))
gammaln = _unary_factory(jax.scipy.special.gammaln)


def clip(data, a_min=None, a_max=None, **kwargs):
    return data.clip(a_min, a_max)


# -- reductions --------------------------------------------------------------
def sum(data, axis=None, keepdims=False, **kwargs):
    return data.sum(axis=axis, keepdims=keepdims)


def nansum(data, axis=None, keepdims=False):
    return _apply(lambda a, _ax=axis, _k=keepdims:
                  jnp.nansum(a, axis=_ax, keepdims=_k), [data])


def mean(data, axis=None, keepdims=False, **kwargs):
    return data.mean(axis=axis, keepdims=keepdims)


def prod(data, axis=None, keepdims=False):
    return data.prod(axis=axis, keepdims=keepdims)


def nanprod(data, axis=None, keepdims=False):
    return _apply(lambda a, _ax=axis, _k=keepdims:
                  jnp.nanprod(a, axis=_ax, keepdims=_k), [data])


def max(data, axis=None, keepdims=False):
    return data.max(axis=axis, keepdims=keepdims)


def min(data, axis=None, keepdims=False):
    return data.min(axis=axis, keepdims=keepdims)


sum_axis, max_axis, min_axis = sum, max, min


def norm(data, ord=2, axis=None, keepdims=False):
    return data.norm(ord=ord, axis=axis, keepdims=keepdims)


def argmax(data, axis=None, keepdims=False):
    return data.argmax(axis=axis, keepdims=keepdims)


def argmin(data, axis=None, keepdims=False):
    return data.argmin(axis=axis, keepdims=keepdims)


def cumsum(data, axis=None, dtype=None):
    return _apply(lambda a, _ax=axis: jnp.cumsum(a, axis=_ax), [data])


# -- shape manipulation ------------------------------------------------------
def reshape(data, shape, **kwargs):
    return data.reshape(shape)


def reshape_like(lhs, rhs):
    return lhs.reshape_like(rhs)


def flatten(data, **kwargs):
    return data.flatten()


Flatten = flatten


def transpose(data, axes=None):
    return data.transpose(*(axes or ()))


def expand_dims(data, axis):
    return data.expand_dims(axis)


def squeeze(data, axis=None):
    return data.squeeze(axis)


def concat(*data, dim=1, **kwargs):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _apply(lambda *xs, _d=dim: jnp.concatenate(xs, axis=_d), list(data))


def concatenate(arrays, axis=0):
    return concat(*arrays, dim=axis)


def stack(*data, axis=0, **kwargs):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _apply(lambda *xs, _ax=axis: jnp.stack(xs, axis=_ax), list(data))


def split(data, num_outputs, axis=1, squeeze_axis=False):
    def fn(a, _n=num_outputs, _ax=axis, _sq=squeeze_axis):
        parts = jnp.split(a, _n, _ax)
        if _sq:
            parts = [jnp.squeeze(p, _ax) for p in parts]
        return tuple(parts)
    out = _apply(fn, [data], n_out=num_outputs)
    return list(out) if isinstance(out, tuple) else [out]


def split_v2(data, indices_or_sections, axis=0, squeeze_axis=False):
    """numpy-style split (reference: split_v2 / _split_v2): an int means
    equal sections, a tuple means split points along `axis`."""
    if isinstance(indices_or_sections, int):
        n_out = indices_or_sections
    else:
        indices_or_sections = tuple(int(i) for i in indices_or_sections)
        n_out = len(indices_or_sections) + 1

    def fn(a, _s=indices_or_sections, _ax=axis, _sq=squeeze_axis):
        parts = jnp.split(a, _s, _ax)
        if _sq:
            parts = [jnp.squeeze(p, _ax) for p in parts]
        return tuple(parts)
    out = _apply(fn, [data], n_out=n_out)
    return list(out) if isinstance(out, tuple) else [out]


def masked_softmax_k(x, m, axis=-1, temperature=1.0):
    """The ONE masked-softmax kernel (raw arrays) — shared by the nd
    wrapper below and the sym registration (symbol/ops.py)."""
    neg = jnp.finfo(x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                    else jnp.float32).min
    z = jnp.where(m.astype(bool), x / temperature, neg)
    out = jax.nn.softmax(z, axis=axis)
    return jnp.where(m.astype(bool), out, 0.0).astype(x.dtype)


def masked_softmax(data, mask, axis=-1, temperature=1.0):
    """Softmax over `axis` with masked-off positions getting exactly 0
    probability (reference: masked_softmax, src/operator/nn/softmax.cc)."""
    return _apply(lambda x, m: masked_softmax_k(x, m, axis, temperature),
                  [data, _lift(mask)])


def cast_storage(data, stype="default"):
    """Storage-type cast (reference: cast_storage op). 'default' is the
    identity; 'row_sparse'/'csr' build the documented-divergence sparse
    containers (dense-backed on TPU — ndarray/sparse.py)."""
    if stype == "default":
        if hasattr(data, "tostype"):
            return data.tostype("default")
        return _apply(lambda a: a, [data])
    if stype in ("row_sparse", "csr"):
        from ..ndarray import sparse as _sparse
        dense = data.asnumpy()
        return (_sparse.row_sparse_array(dense) if stype == "row_sparse"
                else _sparse.csr_matrix(dense))
    from ..base import MXNetError
    raise MXNetError(f"cast_storage: unknown stype {stype!r}")


def tile(data, reps):
    return data.tile(reps)


def repeat(data, repeats, axis=None):
    return data.repeat(repeats, axis)


def pad(data, mode="constant", pad_width=None, constant_value=0):
    """Reference pad: pad_width is a flat tuple of (before, after) per axis."""
    pw = tuple(pad_width)
    pairs = tuple((pw[i], pw[i + 1]) for i in range(0, len(pw), 2))
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]

    def fn(a, _p=pairs, _m=jmode, _v=constant_value):
        if _m == "constant":
            return jnp.pad(a, _p, mode=_m, constant_values=_v)
        return jnp.pad(a, _p, mode=_m)
    return _apply(fn, [data])


def slice(data, begin, end, step=None):
    import builtins
    steps = step if step is not None else [None] * len(begin)
    idx = tuple(builtins.slice(b, e, s) for b, e, s in zip(begin, end, steps))
    return _apply(lambda a, _i=idx: a[_i], [data])


def slice_axis(data, axis, begin, end):
    return data.slice_axis(axis, begin, end)


def slice_like(data, shape_like, axes=None):
    def fn(a, b, _axes=tuple(axes) if axes else None):
        axes_ = _axes if _axes is not None else range(b.ndim)
        import builtins
        idx = [builtins.slice(None)] * a.ndim
        for ax in axes_:
            idx[ax] = builtins.slice(0, b.shape[ax])
        return a[tuple(idx)]
    return _apply(fn, [data, shape_like])


def flip(data, axis):
    return _apply(lambda a, _ax=axis: jnp.flip(a, _ax), [data])


reverse = flip


def moveaxis(data, source, destination):
    return _apply(lambda a: jnp.moveaxis(a, source, destination), [data])


def rollaxis(data, axis, start=0):
    return _apply(lambda a: jnp.rollaxis(a, axis, start), [data])


def array_split(data, indices_or_sections, axis=0):
    """numpy array_split semantics: an int gives that many (possibly
    unequal) parts; a tuple gives split points."""
    secs = indices_or_sections
    if isinstance(secs, int):
        n_out = secs
    else:
        secs = tuple(int(i) for i in secs)
        n_out = len(secs) + 1

    def fn(a, _s=secs, _ax=axis):
        return tuple(jnp.array_split(a, _s, _ax))
    out = _apply(fn, [data], n_out=n_out)
    return list(out) if isinstance(out, tuple) else [out]


def swapaxes(data, dim1, dim2):
    return data.swapaxes(dim1, dim2)


SwapAxis = swapaxes


def depth_to_space(data, block_size):
    def fn(a, _b=block_size):
        n, c, h, w = a.shape
        a = a.reshape(n, _b, _b, c // (_b * _b), h, w)
        a = a.transpose(0, 3, 4, 1, 5, 2)
        return a.reshape(n, c // (_b * _b), h * _b, w * _b)
    return _apply(fn, [data])


def space_to_depth(data, block_size):
    def fn(a, _b=block_size):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // _b, _b, w // _b, _b)
        a = a.transpose(0, 3, 5, 1, 2, 4)
        return a.reshape(n, c * _b * _b, h // _b, w // _b)
    return _apply(fn, [data])


# -- indexing / selection ----------------------------------------------------
def take(a, indices, axis=0, mode="clip"):
    return a.take(indices, axis=axis)


def pick(data, index, axis=-1, keepdims=False):
    return data.pick(index, axis=axis, keepdims=keepdims)


def gather_nd(data, indices):
    idx = _lift(indices)
    return _apply(lambda a, i: a[tuple(i.astype(jnp.int32))], [data, idx])


def scatter_nd(data, indices, shape):
    idx = _lift(indices)
    return _apply(lambda d, i, _s=tuple(shape):
                  jnp.zeros(_s, d.dtype).at[tuple(i.astype(jnp.int32))].set(d),
                  [data, idx])


def where(condition, x, y):
    return _apply(lambda c, a, b: jnp.where(c.astype(bool), a, b),
                  [_lift(condition), _lift(x), _lift(y)])


def boolean_mask(data, index, axis=0):
    """Dynamic-shape op: computed on host side via numpy (documented
    divergence — data-dependent shapes don't exist under XLA)."""
    import numpy as np
    from ..ndarray.ndarray import array as _array
    mask = np.asarray(index.asnumpy(), dtype=bool)
    return _array(np.compress(mask, data.asnumpy(), axis=axis))


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype=None):
    return indices.one_hot(depth, on_value, off_value)


def topk(data, k=1, axis=-1, ret_typ="indices", is_ascend=False):
    return data.topk(k=k, axis=axis, ret_typ=ret_typ, is_ascend=is_ascend)


def sort(data, axis=-1, is_ascend=True):
    return data.sort(axis=axis, is_ascend=is_ascend)


def argsort(data, axis=-1, is_ascend=True):
    return data.argsort(axis=axis, is_ascend=is_ascend)


def shuffle(data):
    from ..random import _next_key
    key = _next_key()
    return _apply(lambda a, _k=key: jax.random.permutation(_k, a, axis=0), [data])


def diag(data, k=0):
    return _apply(lambda a, _k=k: jnp.diag(a, _k) if a.ndim <= 2
                  else jnp.diagonal(a, _k, -2, -1), [data])


# -- linear algebra entry points --------------------------------------------
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    def fn(a, b, _ta=transpose_a, _tb=transpose_b):
        if _ta:
            a = a.T
        if _tb:
            b = b.T
        return jnp.dot(a, b)
    return _apply(fn, [lhs, _lift(rhs)])


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    def fn(a, b, _ta=transpose_a, _tb=transpose_b):
        if _ta:
            a = jnp.swapaxes(a, -1, -2)
        if _tb:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)
    return _apply(fn, [lhs, _lift(rhs)])


def add_n(*args):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return _apply(lambda *xs: functools_reduce(xs), list(args))


def functools_reduce(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


ElementWiseSum = add_n


def cast(data, dtype):
    return data.astype(dtype)


Cast = cast


def zeros_like(data, **kwargs):
    return _apply(jnp.zeros_like, [data])


def ones_like(data, **kwargs):
    return _apply(jnp.ones_like, [data])


def shape_array(data):
    from ..ndarray.ndarray import array as _array
    return _array(jnp.asarray(data.shape, dtype=jnp.int32))


def size_array(data):
    from ..ndarray.ndarray import array as _array
    return _array(jnp.asarray([data.size], dtype=jnp.int32))


# upstream registers the capitalized spelling too (pad.cc)
Pad = pad
