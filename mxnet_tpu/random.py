"""Global RNG (reference: python/mxnet/random.py + mshadow RandomState).

TPU-native design: a process-global, thread-safe JAX PRNG key chain.
`mx.random.seed(n)` resets the chain; every random op folds in a fresh
subkey, so imperative randomness is reproducible yet side-effect free at the
XLA level (each op's key is captured as a constant on the autograd tape, so
tape replay is deterministic).
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from .base import _np_dtype

__all__ = ["seed", "uniform", "normal", "randn", "randint", "gamma",
           "exponential", "poisson", "negative_binomial",
           "generalized_negative_binomial", "multinomial", "shuffle",
           "bernoulli"]

_lock = threading.Lock()
# Created on first use, NOT at import: building a PRNGKey runs a jit and
# initialises the XLA backend, which would make `import mxnet_tpu` grab the
# TPU and break jax.distributed.initialize-after-import (multi-host).
_key = None


def seed(seed_state, ctx="all"):
    """Seed the global RNG chain."""
    global _key
    with _lock:
        _key = jax.random.PRNGKey(int(seed_state))


def _next_key():
    global _key
    with _lock:
        if _key is None:
            _key = jax.random.PRNGKey(0)
        _key, sub = jax.random.split(_key)
        return sub


def _place(val, ctx, dtype=None):
    from .ndarray.ndarray import NDArray
    from .context import Context, current_context
    ctx = Context(ctx) if ctx is not None else current_context()
    if dtype is not None:
        val = val.astype(_np_dtype(dtype))
    return NDArray(jax.device_put(val, ctx.jax_device))


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, **kwargs):
    k = _next_key()
    return _place(jax.random.uniform(k, _shape(shape), minval=low, maxval=high),
                  ctx, dtype)


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, **kwargs):
    k = _next_key()
    return _place(loc + scale * jax.random.normal(k, _shape(shape)), ctx, dtype)


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None):
    return normal(loc, scale, shape, dtype, ctx)


def randint(low, high=None, shape=None, dtype="int32", ctx=None):
    if high is None:
        low, high = 0, low
    k = _next_key()
    return _place(jax.random.randint(k, _shape(shape), low, high), ctx, dtype)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None):
    k = _next_key()
    return _place(jax.random.gamma(k, alpha, _shape(shape)) * beta, ctx, dtype)


def exponential(scale=1.0, shape=None, dtype=None, ctx=None):
    k = _next_key()
    return _place(jax.random.exponential(k, _shape(shape)) * scale, ctx, dtype)


def poisson(lam=1.0, shape=None, dtype=None, ctx=None):
    k = _next_key()
    return _place(jax.random.poisson(k, lam, _shape(shape)).astype(jnp.float32),
                  ctx, dtype)


def negative_binomial(k=1, p=1.0, shape=None, dtype=None, ctx=None):
    key = _next_key()
    g = jax.random.gamma(key, k, _shape(shape)) * ((1 - p) / p)
    key2 = _next_key()
    return _place(jax.random.poisson(key2, g).astype(jnp.float32), ctx, dtype)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None,
                                  ctx=None):
    k = 1.0 / alpha
    p = k / (k + mu)
    return negative_binomial(k, p, shape, dtype, ctx)


def bernoulli(prob=0.5, shape=None, dtype=None, ctx=None):
    k = _next_key()
    return _place(jax.random.bernoulli(k, prob, _shape(shape)).astype(jnp.float32),
                  ctx, dtype)


def multinomial(data, shape=1, get_prob=False, dtype="int32"):
    """Sample category indices from probability rows (reference semantics)."""
    from .ndarray.ndarray import NDArray
    k = _next_key()
    logits = jnp.log(jnp.maximum(data._data, 1e-30))
    n = shape if isinstance(shape, int) else shape[0]
    if data._data.ndim == 1:
        out = jax.random.categorical(k, logits, shape=(n,))
    else:
        out = jax.random.categorical(k, logits[:, None, :],
                                     shape=(logits.shape[0], n), axis=-1)
        if n == 1:
            out = out[:, 0]
    return NDArray(out.astype(_np_dtype(dtype)))


def shuffle(data):
    from .ops.tensor_ops import shuffle as _shuf
    return _shuf(data)
