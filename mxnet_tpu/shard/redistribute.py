"""Portable collective-based array redistribution for mesh→mesh moves
(arXiv:2112.01075: memory-efficient redistribution through portable
collectives — never materialise the full logical array on one host).

`redistribute(x, target_sharding)` moves one (possibly sharded) array
onto a target `NamedSharding`:

  * same device set, different layout — a jitted identity with
    `out_shardings` pinned, so XLA lowers the move to its collective
    repertoire (all-gather / all-to-all / collective-permute) and the
    data rides the interconnect;
  * different device set (elastic shrink/grow after a preemption) —
    `jax.device_put` onto the target sharding, which transfers PER
    SHARD device-to-device; no step of either path ever gathers the
    full value to host memory (`shard_host_gather_bytes` exists to
    prove the claim: this module never increments it).

`redistribute_tree` maps a pytree of arrays onto a pytree (or dict) of
shardings in one call — what `Trainer.resize_mesh` and the resharded
checkpoint restore use to move params + optimizer state as a unit.

Accounting: every moved array counts its LOGICAL bytes into the
``shard_resharded_bytes`` counter (and a move that is already in the
target layout counts nothing and returns the input unchanged).
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import NamedSharding, Sharding

from ..base import MXNetError
from ..observability import registry as _obs_registry
from ..observability import tracer as _tracer

__all__ = ["redistribute", "redistribute_tree", "resharded_bytes"]

_reg = _obs_registry()
_resharded = _reg.counter("shard_resharded_bytes")
_reshards = _reg.counter("shard_reshards")
# intentionally never incremented by this module: the zero IS the
# "no full host gather" guarantee tests pin (tests/test_shard.py)
_host_gather = _reg.counter("shard_host_gather_bytes")

# jitted identity per target sharding — the collective reshard program.
# Bounded FIFO: elastic resize cycles (shrink on preemption, grow on
# capacity return) would otherwise pin every old Mesh and its compiled
# executables forever.
_respec_cache = {}
_RESPEC_CACHE_MAX = 32


def resharded_bytes():
    """Logical bytes moved through `redistribute` since process start
    (or the registry's last reset)."""
    return _resharded.value


def _nbytes(a):
    return int(np.prod(tuple(a.shape) or (1,))) * np.dtype(a.dtype).itemsize


def _same_device_set(a, target):
    sh = getattr(a, "sharding", None)
    if sh is None:
        return False
    try:
        return set(sh.device_set) == set(target.device_set)
    except Exception:
        return False


def redistribute(x, target):
    """Move one array onto `target` (a `Sharding`). Returns `x` unchanged
    when it already carries the target sharding. See module docstring for
    the collective vs device-to-device path split."""
    if not isinstance(target, Sharding):
        raise MXNetError(f"redistribute target must be a jax Sharding, "
                         f"got {type(target).__name__}")
    data = getattr(x, "_data", x)   # NDArray leaves contribute their array
    if getattr(data, "sharding", None) == target:
        return x
    nbytes = _nbytes(data)
    _resharded.inc(nbytes)
    _reshards.inc()
    # an NDArray caller rebinds to the output and drops the source, so
    # the source shards may be DONATED — no transient 2x per array at
    # exactly the memory-constrained moment (post-preemption resize) the
    # module exists for; a raw-array caller keeps its input alive
    donate = hasattr(x, "_rebind")

    def _move():
        if _same_device_set(data, target):
            # same devices, new layout: ONE compiled identity whose
            # out_shardings force the move — XLA picks the collectives
            key = (target, data.shape, str(data.dtype), donate)
            fn = _respec_cache.get(key)
            if fn is None:
                while len(_respec_cache) >= _RESPEC_CACHE_MAX:
                    _respec_cache.pop(next(iter(_respec_cache)))
                fn = _respec_cache[key] = jax.jit(
                    lambda v: v, out_shardings=target,
                    donate_argnums=(0,) if donate else ())
            import warnings as _warnings
            with _warnings.catch_warnings():
                # donation is a no-op on CPU test meshes; jax warns at
                # compile time — scope the suppression to this call
                _warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not")
                return fn(data)
        # different device set (mesh shrink/grow): shard-wise
        # device-to-device placement; never a host gather of the whole
        return jax.device_put(data, target)

    if _tracer.ACTIVE:
        with _tracer.span("shard.redistribute", cat="shard",
                          args={"bytes": nbytes,
                                "target": str(getattr(target, "spec", ""))}):
            out = _move()
    else:
        out = _move()
    if hasattr(x, "_rebind"):
        x._rebind(out)
        return x
    return out


def redistribute_tree(tree, shardings):
    """`redistribute` over a pytree. `shardings` is either a matching
    pytree of Shardings or a single Sharding applied to every leaf."""
    if isinstance(shardings, Sharding):
        return jax.tree_util.tree_map(
            lambda a: redistribute(a, shardings), tree)
    return jax.tree_util.tree_map(redistribute, tree, shardings)
