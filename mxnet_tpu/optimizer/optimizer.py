"""Optimizers (reference: python/mxnet/optimizer/optimizer.py).

TPU-native design: every update rule is a *pure jitted function*
`(weight, grad, *state, lr, wd) -> (new_weight, *new_state)` over jax arrays.
Imperative `update()` rebinds the weight NDArray; inside a pjit-compiled
train step the same pure rules are applied functionally (see
parallel/data_parallel.py), so there is exactly one implementation of each
rule. Multi-precision keeps an fp32 master copy for bf16 weights
(reference: update_multi_precision / momentum in fp32).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "Adamax", "Nadam",
           "AdaGrad", "AdaDelta", "RMSProp", "Ftrl", "Ftml", "LAMB", "LARS",
           "Signum", "SGLD", "DCASGD", "create", "register",
           "fused_sgd_mom_kernel", "multi_sgd_mom_update",
           "multi_sgd_update", "AdaBelief"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    name = name.lower()
    if name not in _REGISTRY:
        raise MXNetError(f"unknown optimizer {name!r}: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


class Optimizer:
    """Base optimizer with per-parameter state, lr scaling and schedulers."""

    # True when `apply` is elementwise over the weight tensor (no
    # whole-tensor reductions like LAMB/LARS trust ratios, no RNG): the
    # rule then commutes with dim-0 sharding, which is what the captured
    # step's `sharded_update` mode (cachedop.py, arXiv:2004.13336) needs
    # to update each replica's weight shard independently. Conservative
    # default: subclasses opt in.
    elementwise = False

    def __init__(self, learning_rate=0.01, wd=0.0, rescale_grad=1.0,
                 clip_gradient=None, lr_scheduler=None, param_dict=None,
                 multi_precision=False, **kwargs):
        self.lr = learning_rate
        self.wd = wd
        self.rescale_grad = rescale_grad
        self.clip_gradient = clip_gradient
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.multi_precision = multi_precision
        self.num_update = 0
        self._index_update_count = {}
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}
        self.idx2name = {}

    # -- bookkeeping ------------------------------------------------------
    def _update_count(self, index):
        self._index_update_count.setdefault(index, 0)
        self._index_update_count[index] += 1
        self.num_update = max(self.num_update, self._index_update_count[index])

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= getattr(self.param_dict[index], "lr_mult", 1.0)
        lr *= self.lr_mult.get(index, self.lr_mult.get(
            self.idx2name.get(index, index), 1.0))
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= getattr(self.param_dict[index], "wd_mult", 1.0)
        wd *= self.wd_mult.get(index, self.wd_mult.get(
            self.idx2name.get(index, index), 1.0))
        return wd

    def set_learning_rate(self, lr):
        self.lr = lr
        if self.lr_scheduler is not None:
            self.lr_scheduler.base_lr = lr

    @property
    def learning_rate(self):
        return self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    # -- functional API (shared with pjit train steps) --------------------
    def init_state(self, weight_value):
        """Pure: weight jax.Array -> tuple of state arrays."""
        return ()

    def apply(self, weight, grad, state, lr, wd):
        """Pure update rule: -> (new_weight, new_state_tuple)."""
        raise NotImplementedError

    def _preprocess(self, grad):
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    # -- imperative API (reference signature) -----------------------------
    def create_state(self, index, weight):
        from ..ndarray.ndarray import NDArray
        return tuple(NDArray(s) for s in self.init_state(weight._data))

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype != np.float32:
            from ..ndarray.ndarray import NDArray
            # state derives from the MASTER: momentum/variance live in fp32
            # (reference semantics), and state dtypes stay stable across
            # updates — the first apply() would promote low-precision zero
            # states to fp32 anyway, which also defeated buffer donation in
            # the fused kernel; going through create_state keeps subclass
            # overrides of that extension point honored
            master = NDArray(weight._data.astype(jnp.float32))
            return (master,) + tuple(self.create_state(index, master))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        from .. import profiler
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad._data.astype(jnp.float32)
                             if grad.dtype != np.float32 else grad._data)
        if g.dtype != weight._data.dtype:
            # cast back to the weight dtype only when they differ — for the
            # common all-fp32 case the old unconditional astype chained a
            # no-op convert onto every gradient
            g = g.astype(weight._data.dtype)
        svals = tuple(s._data for s in state) if isinstance(state, tuple) else \
            ((state._data,) if state is not None else ())
        profiler.record_dispatch("opt_update")
        new_w, new_s = self.apply(weight._data, g, svals, lr, wd)
        weight._rebind(new_w)
        states = state if isinstance(state, tuple) else \
            ((state,) if state is not None else ())
        for s_nd, s_val in zip(states, new_s):
            s_nd._rebind(s_val)

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype != np.float32:
            from .. import profiler
            master, rest = state[0], state[1:]
            self._update_count(index)
            lr, wd = self._get_lr(index), self._get_wd(index)
            g = self._preprocess(grad._data.astype(jnp.float32))
            profiler.record_dispatch("opt_update_mp")
            new_m, new_s = self.apply(master._data, g,
                                      tuple(s._data for s in rest), lr, wd)
            master._rebind(new_m)
            weight._rebind(new_m.astype(weight._data.dtype))
            for s_nd, s_val in zip(rest, new_s):
                s_nd._rebind(s_val)
        else:
            self.update(index, weight, grad, state)

    def __repr__(self):
        return f"{type(self).__name__}(lr={self.lr}, wd={self.wd})"


@register
class SGD(Optimizer):
    """SGD with momentum and weight decay (reference sgd_mom_update)."""

    elementwise = True

    def __init__(self, learning_rate=0.01, momentum=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def init_state(self, w):
        return (jnp.zeros_like(w),) if self.momentum else ()

    def apply(self, w, g, state, lr, wd):
        g = g + wd * w
        if self.momentum:
            m = state[0] * self.momentum + g
            return w - lr * m, (m,)
        return w - lr * g, ()


@register
class NAG(SGD):
    """Nesterov accelerated SGD."""

    def apply(self, w, g, state, lr, wd):
        g = g + wd * w
        if self.momentum:
            m = state[0] * self.momentum + g
            return w - lr * (g + self.momentum * m), (m,)
        return w - lr * g, ()


@register
class Adam(Optimizer):
    elementwise = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, w):
        return (jnp.zeros_like(w), jnp.zeros_like(w),
                jnp.zeros((), jnp.int32))

    def apply(self, w, g, state, lr, wd):
        m, v, t = state
        t = t + 1
        g = g + wd * w
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        tf = t.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** tf)
        vhat = v / (1 - self.beta2 ** tf)
        return w - lr * mhat / (jnp.sqrt(vhat) + self.epsilon), (m, v, t)


@register
class AdaBelief(Adam):
    """AdaBelief (Zhuang et al. 2020, upstream contrib): Adam with the
    second moment over the PREDICTION ERROR (g - m) instead of g —
    adapts the step to the gradient's deviation from its own trend."""

    def apply(self, w, g, state, lr, wd):
        m, s, t = state
        t = t + 1
        g = g + wd * w
        m = self.beta1 * m + (1 - self.beta1) * g
        diff = g - m
        s = self.beta2 * s + (1 - self.beta2) * diff * diff + self.epsilon
        tf = t.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** tf)
        shat = s / (1 - self.beta2 ** tf)
        return w - lr * mhat / (jnp.sqrt(shat) + self.epsilon), (m, s, t)


@register
class AdamW(Adam):
    """Adam with decoupled weight decay (reference: contrib adamw)."""

    def apply(self, w, g, state, lr, wd):
        m, v, t = state
        t = t + 1
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        tf = t.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** tf)
        vhat = v / (1 - self.beta2 ** tf)
        upd = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w
        return w - lr * upd, (m, v, t)


@register
class Adamax(Adam):
    def apply(self, w, g, state, lr, wd):
        m, u, t = state
        t = t + 1
        g = g + wd * w
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        tf = t.astype(jnp.float32)
        return w - lr / (1 - self.beta1 ** tf) * m / (u + self.epsilon), (m, u, t)


@register
class Nadam(Adam):
    def apply(self, w, g, state, lr, wd):
        m, v, t = state
        t = t + 1
        g = g + wd * w
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        tf = t.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** tf)
        vhat = v / (1 - self.beta2 ** tf)
        mbar = self.beta1 * mhat + (1 - self.beta1) * g / (1 - self.beta1 ** tf)
        return w - lr * mbar / (jnp.sqrt(vhat) + self.epsilon), (m, v, t)


@register
class AdaGrad(Optimizer):
    elementwise = True

    def __init__(self, learning_rate=0.01, eps=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def init_state(self, w):
        return (jnp.zeros_like(w),)

    def apply(self, w, g, state, lr, wd):
        g = g + wd * w
        h = state[0] + g * g
        return w - lr * g / (jnp.sqrt(h) + self.float_stable_eps), (h,)


@register
class AdaDelta(Optimizer):
    elementwise = True

    def __init__(self, learning_rate=1.0, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.epsilon = rho, epsilon

    def init_state(self, w):
        return (jnp.zeros_like(w), jnp.zeros_like(w))

    def apply(self, w, g, state, lr, wd):
        acc_g, acc_d = state
        g = g + wd * w
        acc_g = self.rho * acc_g + (1 - self.rho) * g * g
        d = jnp.sqrt(acc_d + self.epsilon) / jnp.sqrt(acc_g + self.epsilon) * g
        acc_d = self.rho * acc_d + (1 - self.rho) * d * d
        return w - lr * d, (acc_g, acc_d)


@register
class RMSProp(Optimizer):
    elementwise = True

    def __init__(self, learning_rate=0.001, rho=0.9, momentum=0.9,
                 epsilon=1e-8, centered=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.momentum = rho, momentum
        self.epsilon, self.centered = epsilon, centered

    def init_state(self, w):
        if self.centered:
            return (jnp.zeros_like(w), jnp.zeros_like(w), jnp.zeros_like(w))
        return (jnp.zeros_like(w),)

    def apply(self, w, g, state, lr, wd):
        g = g + wd * w
        if self.centered:
            n, mg, mom = state
            n = self.rho * n + (1 - self.rho) * g * g
            mg = self.rho * mg + (1 - self.rho) * g
            mom = self.momentum * mom \
                - lr * g / jnp.sqrt(n - mg * mg + self.epsilon)
            return w + mom, (n, mg, mom)
        n = self.rho * state[0] + (1 - self.rho) * g * g
        return w - lr * g / (jnp.sqrt(n) + self.epsilon), (n,)


@register
class Ftrl(Optimizer):
    elementwise = True

    def __init__(self, learning_rate=0.1, lamda1=0.01, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def init_state(self, w):
        return (jnp.zeros_like(w), jnp.zeros_like(w))

    def apply(self, w, g, state, lr, wd):
        z, n = state
        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n + g * g
        new_w = jnp.where(
            jnp.abs(z) > self.lamda1,
            -(z - jnp.sign(z) * self.lamda1)
            / ((self.beta + jnp.sqrt(n)) / lr + wd),
            0.0).astype(w.dtype)
        return new_w, (z, n)


@register
class Ftml(Optimizer):
    """Follow The Moving Leader (reference: optimizer.Ftml,
    ftml_update.cc): adaptive per-coordinate learning rates with a
    shifting regularizer — Adam-like state (v, z, d) plus the step
    counter for bias correction."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, w):
        return (jnp.zeros_like(w), jnp.zeros_like(w), jnp.zeros_like(w),
                jnp.zeros((), jnp.int32))

    def apply(self, w, g, state, lr, wd):
        v, z, d_prev, t = state
        t = t + 1
        tf = t.astype(jnp.float32)
        g = g + wd * w
        v = self.beta2 * v + (1 - self.beta2) * g * g
        d = (1 - self.beta1 ** tf) / lr * (
            jnp.sqrt(v / (1 - self.beta2 ** tf)) + self.epsilon)
        sigma = d - self.beta1 * d_prev
        z = self.beta1 * z + (1 - self.beta1) * g - sigma * w
        return (-z / d).astype(w.dtype), (v, z, d, t)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments (reference: contrib lamb_update) — the
    large-batch BERT optimizer."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def init_state(self, w):
        return (jnp.zeros_like(w), jnp.zeros_like(w), jnp.zeros((), jnp.int32))

    def apply(self, w, g, state, lr, wd):
        m, v, t = state
        t = t + 1
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        if self.bias_correction:
            tf = t.astype(jnp.float32)
            mhat = m / (1 - self.beta1 ** tf)
            vhat = v / (1 - self.beta2 ** tf)
        else:
            mhat, vhat = m, v
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w
        w_norm = jnp.linalg.norm(w)
        r_norm = jnp.linalg.norm(r)
        ratio = jnp.where(jnp.logical_and(w_norm > 0, r_norm > 0),
                          w_norm / r_norm, 1.0)
        if self.lower_bound is not None:
            ratio = jnp.maximum(ratio, self.lower_bound)
        if self.upper_bound is not None:
            ratio = jnp.minimum(ratio, self.upper_bound)
        return w - lr * ratio * r, (m, v, t)


@register
class LARS(SGD):
    """Layer-wise adaptive rate scaling for large-batch SGD."""
    elementwise = False    # whole-tensor trust ratio

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, momentum=momentum,
                         **kwargs)
        self.eta, self.epsilon = eta, epsilon

    def apply(self, w, g, state, lr, wd):
        w_norm = jnp.linalg.norm(w)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where(
            jnp.logical_and(w_norm > 0, g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon), 1.0)
        return super().apply(w, g, state, lr * trust, wd)


@register
class Signum(Optimizer):
    elementwise = True

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def init_state(self, w):
        return (jnp.zeros_like(w),) if self.momentum else ()

    def apply(self, w, g, state, lr, wd):
        if self.momentum:
            m = self.momentum * state[0] - (1 - self.momentum) * (g + wd * w)
            return (1 - lr * self.wd_lh) * w + lr * jnp.sign(m), (m,)
        return (1 - lr * self.wd_lh) * w - lr * jnp.sign(g + wd * w), ()


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics."""

    def init_state(self, w):
        from .. import random as _random
        return (_random._next_key(),)

    def apply(self, w, g, state, lr, wd):
        key, sub = jax.random.split(state[0])
        noise = jnp.sqrt(lr) * jax.random.normal(sub, w.shape, jnp.float32)
        return w - lr / 2 * (g + wd * w) + noise.astype(w.dtype), (key,)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: DCASGD)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.lamda = momentum, lamda

    def init_state(self, w):
        return (jnp.zeros_like(w), w)

    def apply(self, w, g, state, lr, wd):
        mom, prev_w = state
        g = g + wd * w
        g = g + self.lamda * g * g * (w - prev_w)
        mom = self.momentum * mom - lr * g
        return w + mom, (mom, w)


# ---------------------------------------------------------------------------
# multi-tensor (fused) updates (reference: src/operator/optimizer_op.cc
# multi_sgd_update / multi_sgd_mom_update / multi_mp_sgd_update — one kernel
# over many parameters to kill per-tensor launch overhead). TPU-native
# design: flatten every tensor into ONE vector per dtype-role, update once,
# split back; under jit the concat/update/split compiles to a single fused
# region instead of num_params small ones.
# ---------------------------------------------------------------------------
def fused_sgd_mom_kernel(params, moms, grads, lr, momentum=0.9, wd=0.0,
                         rescale_grad=1.0):
    """Pure arrays version used inside jitted train steps: params/grads
    (and moms, or None for momentum-free) are matching lists; returns
    (new_params, new_moms). The math runs on ONE flattened fp32 vector
    (m = mu*m + g + wd*w; w -= lr*m, the reference update); outputs cast
    back to each input's own dtype. lr/momentum/wd/rescale_grad are traced
    scalars — schedules do NOT retrace."""
    import jax.numpy as jnp
    from .multi_tensor import split_flat
    shapes = [p.shape for p in params]
    pdt = [p.dtype for p in params]
    flat_p = jnp.concatenate([p.ravel().astype(jnp.float32) for p in params])
    flat_g = jnp.concatenate([g.ravel().astype(jnp.float32) for g in grads])
    flat_g = flat_g * rescale_grad + wd * flat_p
    if moms is not None:
        mdt = [m.dtype for m in moms]
        flat_m = jnp.concatenate([m.ravel().astype(jnp.float32)
                                  for m in moms])
        flat_m = momentum * flat_m + flat_g
        upd = flat_m
    else:
        upd = flat_g
    flat_p = flat_p - lr * upd

    def split(flat, dts):
        return [a.astype(dt)
                for a, dt in zip(split_flat(flat, shapes), dts)]

    if moms is None:
        return split(flat_p, pdt), None
    return split(flat_p, pdt), split(flat_m, mdt)


_fused_sgd_jit = None


def _fused_jit():
    # one module-level jitted entry: retraces per pytree/shape signature
    # via jit's own cache; lr/momentum/wd stay traced so schedules reuse
    # the compiled program
    global _fused_sgd_jit
    if _fused_sgd_jit is None:
        _fused_sgd_jit = jax.jit(fused_sgd_mom_kernel)
    return _fused_sgd_jit


def multi_sgd_mom_update(weights, grads, moms, lr, momentum=0.9, wd=0.0,
                         rescale_grad=1.0):
    """Imperative multi-tensor SGD-momentum (reference:
    mx.nd.multi_sgd_mom_update): updates every weight/mom NDArray in one
    fused dispatch. Momentum buffers keep their own dtype."""
    pv = [w._data for w in weights]
    mv = [m._data for m in moms]
    gv = [g._data for g in grads]
    new_p, new_m = _fused_jit()(pv, mv, gv, lr, momentum, wd, rescale_grad)
    for w, np_ in zip(weights, new_p):
        w._rebind(np_)
    for m, nm in zip(moms, new_m):
        m._rebind(nm)
    return weights


def multi_sgd_update(weights, grads, lr, wd=0.0, rescale_grad=1.0):
    """Momentum-free variant (reference: mx.nd.multi_sgd_update) — no
    momentum buffers are materialised at all."""
    pv = [w._data for w in weights]
    gv = [g._data for g in grads]
    new_p, _ = _fused_jit()(pv, None, gv, lr, 0.0, wd, rescale_grad)
    for w, np_ in zip(weights, new_p):
        w._rebind(np_)
    return weights
