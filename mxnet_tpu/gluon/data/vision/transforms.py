"""Vision transforms (reference: gluon/data/vision/transforms.py).

Transforms operate on HWC uint8/float NDArrays (reference convention) and
compose via `Compose`. ToTensor converts HWC->CHW float32/255.
"""
from __future__ import annotations

import builtins
import numpy as np

from ....ndarray.ndarray import NDArray, array, _apply
from ...block import Block, HybridBlock

__all__ = ["Rotate",
           "Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomCrop", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomLighting",
           "RandomColorJitter"]


class Compose(Block):
    def __init__(self, transforms):
        super().__init__()
        self._transforms = transforms

    def forward(self, x):
        for t in self._transforms:
            x = t(x)
        return x


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        def fn(a):
            a = a.astype(jnp.float32) / 255.0
            if a.ndim == 3:
                return jnp.transpose(a, (2, 0, 1))
            return jnp.transpose(a, (0, 3, 1, 2))
        return _apply(fn, [x])


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32)
        self._std = np.asarray(std, np.float32)

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        mean, std = self._mean, self._std

        def fn(a, _m=mean, _s=std):
            m = jnp.asarray(_m).reshape(-1, 1, 1) if _m.ndim else _m
            s = jnp.asarray(_s).reshape(-1, 1, 1) if _s.ndim else _s
            return (a - m) / s
        return _apply(fn, [x])


def _resize_hwc(a, size):
    import jax.image
    h, w = (size, size) if isinstance(size, int) else (size[1], size[0])
    return jax.image.resize(a, (h, w, a.shape[2]), method="bilinear")


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size

    def forward(self, x):
        return _apply(lambda a, _s=self._size: _resize_hwc(
            a.astype("float32"), _s), [x])


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[0], x.shape[1]
        y0, x0 = max((H - h) // 2, 0), max((W - w) // 2, 0)
        return x[y0:y0 + h, x0:x0 + w, :]


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad

    def forward(self, x):
        import numpy as _np
        w, h = self._size
        a = x.asnumpy()
        if self._pad:
            p = self._pad
            a = _np.pad(a, ((p, p), (p, p), (0, 0)), mode="constant")
        H, W = a.shape[:2]
        y0 = _np.random.randint(0, max(H - h, 0) + 1)
        x0 = _np.random.randint(0, max(W - w, 0) + 1)
        return array(a[y0:y0 + h, x0:x0 + w])


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import numpy as _np
        H, W = x.shape[0], x.shape[1]
        area = H * W
        for _ in range(10):
            target = area * _np.random.uniform(*self._scale)
            ar = _np.random.uniform(*self._ratio)
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if w <= W and h <= H:
                x0 = _np.random.randint(0, W - w + 1)
                y0 = _np.random.randint(0, H - h + 1)
                crop = x[y0:y0 + h, x0:x0 + w, :]
                return _apply(lambda a, _s=self._size: _resize_hwc(
                    a.astype("float32"), _s), [crop])
        return _apply(lambda a, _s=self._size: _resize_hwc(
            a.astype("float32"), _s), [x])


class RandomFlipLeftRight(Block):
    def forward(self, x):
        import numpy as _np
        if _np.random.rand() < 0.5:
            return x[:, ::-1, :]
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        import numpy as _np
        if _np.random.rand() < 0.5:
            return x[::-1, :, :]
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        import numpy as _np
        alpha = 1.0 + _np.random.uniform(-self._b, self._b)
        return x.astype("float32") * alpha


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        import numpy as _np
        alpha = 1.0 + _np.random.uniform(-self._c, self._c)
        xf = x.astype("float32")
        mean = xf.mean()
        return xf * alpha + mean * (1 - alpha)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        import numpy as _np
        alpha = 1.0 + _np.random.uniform(-self._s, self._s)
        xf = x.astype("float32")
        gray = xf.mean(axis=2, keepdims=True)
        return xf * alpha + gray * (1 - alpha)


class RandomHue(Block):
    """Hue jitter by YIQ rotation (reference: image.HueJitterAug): rotate
    the chroma plane by a random angle in [-hue, hue]*pi."""
    _t_yiq = np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], np.float32)
    _t_rgb = np.array([[1.0, 0.956, 0.621],
                       [1.0, -0.272, -0.647],
                       [1.0, -1.107, 1.705]], np.float32)

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        import numpy as _np
        alpha = _np.random.uniform(-self._h, self._h)
        u, w = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        rot = _np.array([[1, 0, 0], [0, u, -w], [0, w, u]], _np.float32)
        m = self._t_rgb @ rot @ self._t_yiq
        xf = x.astype("float32")
        return xf.dot(array(m.T.astype(_np.float32)))


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise."""
    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        import numpy as _np
        alpha = _np.random.normal(0, self._alpha, 3).astype(_np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return x.astype("float32") + array(rgb.reshape(1, 1, 3))


class Rotate(Block):
    """Rotate an (H, W, C) image by a fixed angle in degrees
    (reference: transforms.Rotate). zoom_in crops to the largest
    axis-aligned rectangle with no border; zoom_out keeps every source
    pixel (pads with zeros). Bilinear sampling through the same
    grid-sample kernel the SpatialTransformer op uses."""

    def __init__(self, rotation_degrees, zoom_in=False, zoom_out=False):
        super().__init__()
        if zoom_in and zoom_out:
            raise ValueError("Rotate: zoom_in and zoom_out are exclusive")
        self._deg = float(rotation_degrees)
        self._zoom_in = zoom_in
        self._zoom_out = zoom_out
        self._grids = {}    # (h, w) -> sampling grid (angle is fixed)

    def _grid(self, h, w):
        """Pixel-space rotation grid in the sampler's per-axis [-1, 1]
        coords — correct for non-square images (normalized-space
        rotation would shear them)."""
        import math
        import numpy as _np
        if (h, w) in self._grids:
            return self._grids[(h, w)]
        rad = math.radians(self._deg)
        c, s = math.cos(rad), math.sin(rad)
        ca, sa = builtins.abs(c), builtins.abs(s)
        zx = zy = 1.0
        if self._zoom_out:
            # scale so every source pixel fits in the frame
            zx = zy = builtins.max((w * ca + h * sa) / w,
                                   (h * ca + w * sa) / h)
        elif self._zoom_in:
            # largest same-aspect rectangle inscribed in the rotation
            # (the classic inscribed-rect formula)
            long_s, short_s = builtins.max(w, h), builtins.min(w, h)
            if short_s <= 2.0 * sa * ca * long_s or                     builtins.abs(sa - ca) < 1e-10:
                half = 0.5 * short_s
                wr, hr = (half / sa, half / ca) if w >= h                     else (half / ca, half / sa)
            else:
                cos2 = ca * ca - sa * sa
                wr = (w * ca - h * sa) / cos2
                hr = (h * ca - w * sa) / cos2
            zx, zy = wr / w, hr / h
        # output pixel centres -> rotate in PIXEL units around the centre
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        gy, gx = _np.meshgrid(_np.arange(h), _np.arange(w), indexing="ij")
        px = (gx - cx) * zx
        py = (gy - cy) * zy
        sx_pix = c * px - s * py + cx
        sy_pix = s * px + c * py + cy
        # per-axis normalization for the [-1, 1] bilinear sampler
        sx = (2.0 * sx_pix / (w - 1) - 1.0).astype(_np.float32)
        sy = (2.0 * sy_pix / (h - 1) - 1.0).astype(_np.float32)
        grid = _np.stack([sx, sy])[None]        # (1, 2, H, W)
        self._grids[(h, w)] = grid
        return grid

    def forward(self, x):
        from ....ops.extra_ops import bilinear_sampler_k
        from ....ndarray.ndarray import _apply as _ap
        grid = self._grid(x.shape[0], x.shape[1])
        import jax.numpy as jnp

        def fn(img):
            chw = jnp.moveaxis(img.astype(jnp.float32), -1, 0)[None]
            out = bilinear_sampler_k(chw, jnp.asarray(grid))
            return jnp.moveaxis(out[0], 0, -1).astype(img.dtype)
        return _ap(fn, [x])


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        import numpy as _np
        order = _np.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x
