"""Graft-lint unit tests (ISSUE 13): a true-positive AND a clean
fixture per AST rule, suppression + baseline semantics, the pure
graph-text analyzers, and one graphlint run against a tiny captured
step. The AST half is parse-only (no jax work) per the tier-1 time
budget."""
import json

import pytest

from mxnet_tpu.analysis import astlint, graphlint


def _rules(findings, suppressed=False):
    return [f.rule for f in findings
            if suppressed or not f.suppressed]


def lint(src, path="mxnet_tpu/_fix.py"):
    return astlint.lint_source(src, path=path, relpath=path)


# ------------------------------------------------------------- MXTPU-E01
def test_e01_fires_on_raw_env_numeric_parse():
    fs = lint("import os\n"
              "x = int(os.environ.get('MXTPU_A_MS', '5'))\n"
              "y = float(os.getenv('MXTPU_B', '1.5'))\n")
    assert _rules(fs) == ["MXTPU-E01", "MXTPU-E01"]


def test_e01_fires_through_local_dataflow():
    fs = lint("import os\n"
              "def f():\n"
              "    raw = os.environ.get('MXTPU_N')\n"
              "    if raw is not None:\n"
              "        return int(raw)\n")
    assert _rules(fs) == ["MXTPU-E01"]


def test_e01_clean_when_routed_through_env_module():
    fs = lint("from . import _env\n"
              "x = _env.env_int('MXTPU_A_MS', 5)\n"
              "import os\n"
              "s = os.environ.get('MXTPU_NAME', 'x')\n")   # string read ok
    assert _rules(fs) == []


def test_e01_exempts_the_env_module_itself():
    fs = lint("import os\nx = int(os.environ.get('K', '1'))\n",
              path="mxnet_tpu/_env.py")
    assert _rules(fs) == []


# ------------------------------------------------------------- MXTPU-E02
def test_e02_fires_in_engine_task_and_traced_scopes():
    fs = lint("import numpy as np\n"
              "import engine\n"
              "def stage(arr, dev):\n"
              "    def task():\n"
              "        a = arr.asnumpy()\n"
              "        b = dev.item()\n"
              "        return np.asarray(a)\n"
              "    engine.push(task)\n")
    assert _rules(fs) == ["MXTPU-E02"] * 3
    fs = lint("import jax\n"
              "def step(x):\n"
              "    return x.tolist()\n"
              "j = jax.jit(step)\n")
    assert _rules(fs) == ["MXTPU-E02"]


def test_e02_clean_outside_hot_scopes_and_for_jnp():
    fs = lint("import numpy as np\n"
              "import jax.numpy as jnp\n"
              "def host_helper(arr):\n"
              "    return arr.asnumpy()\n"       # not hot: fine
              "import jax\n"
              "def step(x):\n"
              "    return jnp.asarray(x)\n"       # device-side asarray
              "j = jax.jit(step)\n")
    assert _rules(fs) == []


# ------------------------------------------------------------- MXTPU-E03
def test_e03_fires_on_direct_metric_instantiation():
    fs = lint("from ..observability.metrics_registry import Counter\n"
              "c = Counter('x', ())\n")
    assert _rules(fs) == ["MXTPU-E03"]


def test_e03_clean_for_registry_memo_and_collections_counter():
    fs = lint("from collections import Counter\n"
              "from ..observability import registry\n"
              "c1 = Counter()\n"                  # collections: fine
              "c2 = registry().counter('x')\n")   # the memo: fine
    assert _rules(fs) == []


def test_e03_skips_the_registry_module_itself():
    fs = lint("c = Counter('x', ())\n",
              path="mxnet_tpu/observability/metrics_registry.py")
    assert _rules(fs) == []


# ------------------------------------------------------------- MXTPU-E04
def test_e04_fires_on_swallowed_base_exception_in_serve():
    fs = lint("def cb():\n"
              "    try:\n"
              "        work()\n"
              "    except BaseException:\n"
              "        pass\n",
              path="mxnet_tpu/serve/x.py")
    assert _rules(fs) == ["MXTPU-E04"]


def test_e04_accepts_reraise_set_exception_and_sibling_guard():
    clean = ("def cb(f):\n"
             "    try:\n"
             "        work()\n"
             "    except BaseException as e:\n"
             "        f.set_exception(e)\n"       # stored, not swallowed
             "def cb2(e):\n"
             "    try:\n"
             "        work()\n"
             "    except BaseException as exc:\n"
             "        _reraise_unless_cancelled(exc)\n"
             "def cb3():\n"
             "    try:\n"
             "        work()\n"
             "    except (KeyboardInterrupt, SystemExit):\n"
             "        raise\n"
             "    except BaseException:\n"        # KI/SE already escape
             "        pass\n")
    fs = lint(clean, path="mxnet_tpu/serve/x.py")
    assert _rules(fs) == []


def test_e04_scope_limited_to_engine_serve_or_engine_tasks():
    src = ("def helper():\n"
           "    try:\n"
           "        work()\n"
           "    except BaseException:\n"
           "        pass\n")
    assert _rules(lint(src, path="mxnet_tpu/io.py")) == []
    assert _rules(lint(src, path="mxnet_tpu/engine.py")) == \
        ["MXTPU-E04"]


# ------------------------------------------------------------- MXTPU-E05
def test_e05_fires_on_naked_fault_point():
    fs = lint("from .fault import injection as _finj\n"
              "def hot():\n"
              "    _finj.check('io.read', context='r')\n")
    assert _rules(fs) == ["MXTPU-E05"]


def test_e05_clean_under_try_or_retry_wrapper():
    fs = lint("from .fault import injection as _finj\n"
              "def guarded():\n"
              "    try:\n"
              "        _finj.check('io.read')\n"
              "    except Exception:\n"
              "        recover()\n"
              "def attempt():\n"
              "    _finj.check('io.decode')\n"
              "    return read()\n"
              "def outer(policy):\n"
              "    return policy.call(attempt)\n")
    assert _rules(fs) == []


# ------------------------------------------------------------- MXTPU-E06
def test_e06_fires_on_wall_clock_and_rng_in_traced_code():
    fs = lint("import time, random\n"
              "import numpy as np\n"
              "import jax\n"
              "def step(x):\n"
              "    t = time.time()\n"
              "    r = random.random()\n"
              "    z = np.random.randn(3)\n"
              "    return x + t + r\n"
              "j = jax.jit(step)\n")
    assert _rules(fs) == ["MXTPU-E06"] * 3


def test_e06_clean_outside_trace_and_for_seeded_rng():
    fs = lint("import time, random\n"
              "import jax\n"
              "def host_loop():\n"
              "    return time.time()\n"          # host code: fine
              "def step(x, rng):\n"
              "    return x + rng.normal()\n"     # passed-in RNG: fine
              "j = jax.jit(step)\n")
    assert _rules(fs) == []


# ----------------------------------------------------------- suppression
def test_inline_suppression_same_line_and_line_above():
    fs = lint("import os\n"
              "a = int(os.environ.get('A', '1'))"
              "  # mxtpu: disable=E01 bootstrap\n"
              "# mxtpu: disable=MXTPU-E01 second form\n"
              "b = int(os.environ.get('B', '2'))\n")
    assert len(fs) == 2 and all(f.suppressed for f in fs)


def test_suppression_is_rule_specific():
    fs = lint("import os\n"
              "a = int(os.environ.get('A', '1'))"
              "  # mxtpu: disable=E05 wrong rule\n")
    assert _rules(fs) == ["MXTPU-E01"]


# -------------------------------------------------------------- baseline
def test_baseline_matches_marks_and_reports_stale(tmp_path):
    src = ("import os\n"
           "a = int(os.environ.get('A', '1'))\n")
    findings = lint(src)
    entry = {"rule": "MXTPU-E01", "path": "mxnet_tpu/_fix.py",
             "scope": "", "snippet": "a = int(os.environ.get('A', '1'))",
             "why": "test"}
    stale_entry = {"rule": "MXTPU-E01", "path": "mxnet_tpu/_fix.py",
                   "scope": "gone", "snippet": "x = 1", "why": "old"}
    new, matched, stale = astlint.apply_baseline(
        findings, [entry, stale_entry])
    assert new == [] and len(matched) == 1 and matched[0].baselined
    assert stale == [stale_entry]
    # load_baseline round-trip + missing file
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"ast": [entry]}))
    loaded = astlint.load_baseline(str(p))
    assert loaded["ast"] == [entry] and loaded["graph"] == []
    assert astlint.load_baseline(str(tmp_path / "none.json")) == \
        {"ast": [], "graph": []}


def test_lint_tree_scans_the_package_and_head_is_clean():
    findings, scanned = astlint.lint_tree(astlint.package_root())
    assert scanned > 100
    live = [f for f in findings if not f.suppressed]
    # HEAD carries exactly the baselined acceptances (ISSUE 13: E01
    # runs baseline-free — zero raw numeric env parses remain)
    assert [f.rule for f in live if f.rule == "MXTPU-E01"] == []
    baseline = astlint.load_baseline(
        astlint.package_root() + "/../tools/static_baseline.json")
    new, _, stale = astlint.apply_baseline(live, baseline["ast"])
    assert new == [] and stale == []


# ---------------------------------------------------- graph text analyzers
def test_find_copies_attributes_sources():
    txt = ('HloModule m\n'
           '  %p = f32[8]{0} parameter(0)\n'
           '  %c1 = f32[8]{0} copy(%p), metadata={op_name="jit(s)/tr"}\n'
           '  %c2 = f32[8]{0} copy(%c1), metadata={op_name="jit(s)/tr"}\n'
           '  %c3 = f32[8]{0} copy(%c2)\n'
           '  ROOT %r = f32[8]{0} add(%c3, %c3)\n')
    assert graphlint.find_copies(txt) == [("jit(s)/tr", 2),
                                          ("<unattributed>", 1)]


def test_dead_and_duplicate_collectives():
    txt = ('HloModule m\n'
           '  %p = f32[8]{0} parameter(0)\n'
           '  %a1 = f32[8]{0} all-reduce(%p), replica_groups={{0,1}}\n'
           '  %a2 = f32[8]{0} all-reduce(%p), replica_groups={{0,1}}\n'
           '  %dead = f32[16]{0} all-gather(%p), dimensions={0}\n'
           '  ROOT %r = f32[8]{0} add(%a1, %a2)\n')
    out = graphlint.find_dead_or_dup_collectives(txt)
    kinds = {(d["kind"], d["op"]) for d in out}
    assert kinds == {("duplicate", "all-reduce"),
                     ("dead", "all-gather")}


def test_root_tuple_consumption_counts_as_use():
    """The 8-device sharded step's ROOT tuple overflows any line-level
    instruction regex — usage must fall back to whole-text reference
    counting, or every output-feeding collective reads as dead (the
    false positive the first graphlint sweep hit)."""
    txt = ('HloModule m\n'
           '  %p = f32[8]{0} parameter(0)\n'
           '  %ag = f32[16]{0} all-gather(%p), dimensions={0}\n'
           '  ROOT %t = (f32[], /*index=5*/f32[16]{0}) '
           'tuple(f32[] %x, f32[16]{0} %ag)\n')
    assert graphlint.find_dead_or_dup_collectives(txt) == []


def test_unconstrained_args_require_a_real_plan():
    # maximal (single-device commit) annotations are NOT a plan
    single = ('func.func public @main(%arg0: tensor<64x64xf32> '
              '{mhlo.sharding = "{maximal device=0}"}, '
              '%arg1: tensor<64x64xf32>) -> tensor<64x64xf32>')
    assert graphlint.find_unconstrained_args(single) == []
    planned = ('func.func public @main(%arg0: tensor<64x64xf32> '
               '{mhlo.sharding = "{devices=[2,1]0,1}"}, '
               '%arg1: tensor<64x64xf32>, '
               '%arg2: tensor<f32>) -> tensor<64x64xf32>\n'
               'func.func private @helper(%arg0: tensor<64x64xf32>) '
               '-> tensor<64x64xf32>')
    out = graphlint.find_unconstrained_args(planned, min_bytes=1024)
    # arg1 flagged; the scalar arg2 is under threshold; the PRIVATE
    # helper's annotation-free %arg0 must not count as an entry input
    assert out == [(1, 64 * 64 * 4)]
    # an explicit replicated annotation is a constrained choice
    repl = ('func.func public @main(%arg0: tensor<64x64xf32> '
            '{mhlo.sharding = "{devices=[2,1]0,1}"}, '
            '%arg1: tensor<64x64xf32> '
            '{mhlo.sharding = "{replicated}"}) -> tensor<64x64xf32>')
    assert graphlint.find_unconstrained_args(repl) == []


# ------------------------------------------------------- graphlint (live)
def test_donation_leak_and_strong_const_fire_live():
    import jax
    import jax.numpy as jnp

    j = jax.jit(lambda x, dead: x + 1.0, donate_argnums=(1,))
    fs = graphlint.lint_jit(j, jnp.ones(4, jnp.float32),
                            jnp.ones((8, 8), jnp.float32),
                            executable="ctl", copies_allow=64)
    assert any(f.rule == "MXTPU-G01" for f in fs)
    c = jnp.float32(3.0)
    fs = graphlint.lint_jit(jax.jit(lambda x: x * c),
                            jnp.ones(4, jnp.float32),
                            executable="ctl", copies_allow=64)
    assert [f.rule for f in fs] == ["MXTPU-G05"]
    # a weak python-float capture is the FIX — and lints clean
    fs = graphlint.lint_jit(jax.jit(lambda x: x * 3.0),
                            jnp.ones(4, jnp.float32),
                            executable="ctl", copies_allow=64)
    assert fs == []


def test_graphlint_on_a_tiny_captured_step():
    """ISSUE 13 satellite: a real captured training step lints clean
    under its copy allowance — donation fully aliased, no dead/dup
    collectives, no strong scalar consts (per-step lr/wd ride as
    weak-typed args by the PR 4 design)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.observability import compilex

    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(8, 16).astype(np.float32))
    y = nd.array(rng.randint(0, 4, 8).astype(np.float32))
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    mx.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(X)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    step = tr.capture(lambda a, b: lossf(net(a), b).mean())
    step(X, y)
    ij = compilex.instrumented().get("captured_step")
    assert ij is not None and ij.last_abstract is not None
    fs = graphlint.lint_instrumented(ij, copies_allow=12)
    assert fs == [], [str(f) for f in fs]
    # and the donation accounting itself is visible: >0 donated leaves,
    # all aliased
    args, kwargs = ij.last_abstract
    traced = ij._jfn.trace(*args, **kwargs)
    donated, aliased = graphlint.find_donation_leaks(
        traced.lower().args_info, traced.lower().compile().as_text())
    assert donated > 0 and aliased >= donated


def test_graph_baseline_semantics():
    f = graphlint.GraphFinding("MXTPU-G02", "captured_step",
                               "copies>0", "msg")
    entry = {"rule": "MXTPU-G02", "executable": "captured_step",
             "key": "copies>0", "why": "test"}
    stale = {"rule": "MXTPU-G03", "executable": "gone", "key": "k",
             "why": "old"}
    new, matched, stale_out = graphlint.apply_graph_baseline(
        [f], [entry, stale])
    assert new == [] and matched == [f] and f.baselined
    assert stale_out == [stale]
