"""Inception V3 (reference: gluon/model_zoo/vision/inception.py)."""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["Inception3", "inception_v3"]


def _make_basic_conv(channels, kernel_size, strides=1, padding=0,
                     layout="NCHW"):
    ax = 1 if layout == "NCHW" else 3
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(channels, kernel_size, strides, padding,
                      use_bias=False, layout=layout))
    out.add(nn.BatchNorm(axis=ax, epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _Branches(HybridBlock):
    def __init__(self, branches, axis, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        for i, b in enumerate(branches):
            self.register_child(b, f"branch{i}")

    def hybrid_forward(self, F, x):
        return F.concat(*[b(x) for b in self._children.values()],
                      dim=self._axis)


def _make_A(pool_features, layout):
    ax = 1 if layout == "NCHW" else 3
    b1 = _make_basic_conv(64, 1, layout=layout)
    b2 = nn.HybridSequential(prefix="")
    b2.add(_make_basic_conv(48, 1, layout=layout))
    b2.add(_make_basic_conv(64, 5, padding=2, layout=layout))
    b3 = nn.HybridSequential(prefix="")
    b3.add(_make_basic_conv(64, 1, layout=layout))
    b3.add(_make_basic_conv(96, 3, padding=1, layout=layout))
    b3.add(_make_basic_conv(96, 3, padding=1, layout=layout))
    b4 = nn.HybridSequential(prefix="")
    b4.add(nn.AvgPool2D(3, 1, 1, layout=layout))
    b4.add(_make_basic_conv(pool_features, 1, layout=layout))
    return _Branches([b1, b2, b3, b4], ax)


def _make_B(layout):
    ax = 1 if layout == "NCHW" else 3
    b1 = _make_basic_conv(384, 3, 2, layout=layout)
    b2 = nn.HybridSequential(prefix="")
    b2.add(_make_basic_conv(64, 1, layout=layout))
    b2.add(_make_basic_conv(96, 3, padding=1, layout=layout))
    b2.add(_make_basic_conv(96, 3, 2, layout=layout))
    b3 = nn.HybridSequential(prefix="")
    b3.add(nn.MaxPool2D(3, 2, layout=layout))
    return _Branches([b1, b2, b3], ax)


def _make_C(channels_7x7, layout):
    ax = 1 if layout == "NCHW" else 3
    b1 = _make_basic_conv(192, 1, layout=layout)
    c = channels_7x7
    b2 = nn.HybridSequential(prefix="")
    b2.add(_make_basic_conv(c, 1, layout=layout))
    b2.add(_make_basic_conv(c, (1, 7), padding=(0, 3), layout=layout))
    b2.add(_make_basic_conv(192, (7, 1), padding=(3, 0), layout=layout))
    b3 = nn.HybridSequential(prefix="")
    b3.add(_make_basic_conv(c, 1, layout=layout))
    b3.add(_make_basic_conv(c, (7, 1), padding=(3, 0), layout=layout))
    b3.add(_make_basic_conv(c, (1, 7), padding=(0, 3), layout=layout))
    b3.add(_make_basic_conv(c, (7, 1), padding=(3, 0), layout=layout))
    b3.add(_make_basic_conv(192, (1, 7), padding=(0, 3), layout=layout))
    b4 = nn.HybridSequential(prefix="")
    b4.add(nn.AvgPool2D(3, 1, 1, layout=layout))
    b4.add(_make_basic_conv(192, 1, layout=layout))
    return _Branches([b1, b2, b3, b4], ax)


def _make_D(layout):
    ax = 1 if layout == "NCHW" else 3
    b1 = nn.HybridSequential(prefix="")
    b1.add(_make_basic_conv(192, 1, layout=layout))
    b1.add(_make_basic_conv(320, 3, 2, layout=layout))
    b2 = nn.HybridSequential(prefix="")
    b2.add(_make_basic_conv(192, 1, layout=layout))
    b2.add(_make_basic_conv(192, (1, 7), padding=(0, 3), layout=layout))
    b2.add(_make_basic_conv(192, (7, 1), padding=(3, 0), layout=layout))
    b2.add(_make_basic_conv(192, 3, 2, layout=layout))
    b3 = nn.HybridSequential(prefix="")
    b3.add(nn.MaxPool2D(3, 2, layout=layout))
    return _Branches([b1, b2, b3], ax)


class _BranchE2(HybridBlock):
    def __init__(self, layout, **kwargs):
        super().__init__(**kwargs)
        self._axis = 1 if layout == "NCHW" else 3
        self.stem = _make_basic_conv(384, 1, layout=layout)
        self.a = _make_basic_conv(384, (1, 3), padding=(0, 1), layout=layout)
        self.b = _make_basic_conv(384, (3, 1), padding=(1, 0), layout=layout)

    def hybrid_forward(self, F, x):
        x = self.stem(x)
        return F.concat(self.a(x), self.b(x), dim=self._axis)


class _BranchE3(HybridBlock):
    def __init__(self, layout, **kwargs):
        super().__init__(**kwargs)
        self._axis = 1 if layout == "NCHW" else 3
        self.stem = nn.HybridSequential(prefix="")
        self.stem.add(_make_basic_conv(448, 1, layout=layout))
        self.stem.add(_make_basic_conv(384, 3, padding=1, layout=layout))
        self.a = _make_basic_conv(384, (1, 3), padding=(0, 1), layout=layout)
        self.b = _make_basic_conv(384, (3, 1), padding=(1, 0), layout=layout)

    def hybrid_forward(self, F, x):
        x = self.stem(x)
        return F.concat(self.a(x), self.b(x), dim=self._axis)


def _make_E(layout):
    ax = 1 if layout == "NCHW" else 3
    b1 = _make_basic_conv(320, 1, layout=layout)
    b2 = _BranchE2(layout)
    b3 = _BranchE3(layout)
    b4 = nn.HybridSequential(prefix="")
    b4.add(nn.AvgPool2D(3, 1, 1, layout=layout))
    b4.add(_make_basic_conv(192, 1, layout=layout))
    return _Branches([b1, b2, b3, b4], ax)


class Inception3(HybridBlock):
    def __init__(self, classes=1000, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(_make_basic_conv(32, 3, 2, layout=layout))
            self.features.add(_make_basic_conv(32, 3, layout=layout))
            self.features.add(_make_basic_conv(64, 3, padding=1,
                                               layout=layout))
            self.features.add(nn.MaxPool2D(3, 2, layout=layout))
            self.features.add(_make_basic_conv(80, 1, layout=layout))
            self.features.add(_make_basic_conv(192, 3, layout=layout))
            self.features.add(nn.MaxPool2D(3, 2, layout=layout))
            self.features.add(_make_A(32, layout))
            self.features.add(_make_A(64, layout))
            self.features.add(_make_A(64, layout))
            self.features.add(_make_B(layout))
            self.features.add(_make_C(128, layout))
            self.features.add(_make_C(160, layout))
            self.features.add(_make_C(160, layout))
            self.features.add(_make_C(192, layout))
            self.features.add(_make_D(layout))
            self.features.add(_make_E(layout))
            self.features.add(_make_E(layout))
            self.features.add(nn.AvgPool2D(8, layout=layout))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(**kwargs):
    return Inception3(**kwargs)
