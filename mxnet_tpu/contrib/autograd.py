"""mx.contrib.autograd (reference: python/mxnet/contrib/autograd.py —
the pre-1.0 experimental API): thin aliases over the stable mx.autograd
so ancient scripts import-port cleanly."""
from __future__ import annotations

from ..autograd import (record as train_section,  # noqa: F401
                        pause as test_section)
from ..autograd import backward, grad, mark_variables  # noqa: F401

__all__ = ["train_section", "test_section", "backward", "grad",
           "mark_variables", "grad_and_loss"]


def grad_and_loss(func, argnum=None):
    """Return a function computing both gradients and loss (reference:
    contrib.autograd.grad_and_loss)."""
    from .. import autograd as ag
    from ..ndarray.ndarray import NDArray

    def wrapped(*args):
        ins = list(args)
        which = range(len(ins)) if argnum is None else (
            [argnum] if isinstance(argnum, int) else list(argnum))
        for i in which:
            if isinstance(ins[i], NDArray):
                ins[i].attach_grad()
        with ag.record():
            out = func(*ins)
        out.backward()
        return [ins[i].grad for i in which], out
    return wrapped
