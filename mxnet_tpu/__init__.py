"""mxnet_tpu: a TPU-native deep learning framework with MXNet's user surface.

This is NOT a port of Apache MXNet — it is a from-scratch framework built on
JAX/XLA/Pallas that exposes the same capabilities the reference
(juanluisrosaramos/incubator-mxnet) ships: imperative NDArray with contexts,
autograd, a lazy Symbol graph, Gluon (Blocks, Trainer, data), KVStore-style
distributed training, optimizers/metrics/initializers, and a model zoo.

Conventions:
  import mxnet_tpu as mx
  x = mx.nd.zeros((2, 3), ctx=mx.tpu())

Architecture (see SURVEY.md §1): NDArray wraps `jax.Array`; imperative ops are
XLA primitives dispatched asynchronously; `HybridBlock.hybridize()` compiles
the forward to a single XLA executable via `jax.jit`; distributed training
lowers KVStore push/pull to `psum`/`all_gather` over a `jax.sharding.Mesh`.
"""

__version__ = "0.1.0"

from . import base
from .base import MXNetError
from . import context
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import engine
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import numpy as np            # mx.np: numpy front end
from . import numpy_extension as npx  # mx.npx: np-mode switches + nn ops
from . import random
from . import autograd
from . import initializer
from .initializer import init
from . import optimizer
from .optimizer import opt
from . import lr_scheduler
from . import metric
from . import io
from . import recordio
from . import image
from . import image as img             # reference alias (mx.img)
from . import registry
from . import log
from . import kvstore
from . import kvstore as kv            # reference alias (mx.kv)
from .kvstore import KVStore
from . import gluon
from . import symbol
from . import symbol as sym
from . import module
from . import module as mod
from . import model
from . import rnn
from . import operator
from . import name
from . import test_utils
from . import attribute
from .attribute import AttrScope
from . import callback
from . import rtc
from . import monitor
from . import observability
from .observability import set_compilation_cache
from . import tune
from .tune import set_autotune
from . import analysis
from . import fault
from . import profiler
from . import amp
from . import upstream
from . import utils
from . import visualization as viz
from . import runtime
from . import checkpoint
from . import parallel
from . import models
from . import serve
from . import contrib
from . import prefetch
from .prefetch import DevicePrefetcher
from . import shard
from . import cachedop
from .cachedop import jit_step, CachedStep
from .util import waitall

mon = monitor
