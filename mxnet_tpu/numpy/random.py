"""mx.np.random — numpy-named sampling over the global RNG key chain
(reference: python/mxnet/numpy/random.py). Shares the seed/key state with
mx.random so `mx.random.seed` and `np.random.seed` are one stream."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _onp

from .. import random as _mxrand
from ..ndarray.ndarray import NDArray, _np_dtype

__all__ = ["seed", "uniform", "normal", "randint", "rand", "randn",
           "choice", "shuffle", "permutation", "multinomial", "gamma",
           "exponential", "beta", "chisquare", "laplace", "gumbel",
           "logistic", "lognormal", "pareto", "power", "rayleigh",
           "weibull"]


def seed(seed_state):
    _mxrand.seed(seed_state)


def _np(val, dtype=None):
    from . import ndarray
    if dtype is not None:
        val = val.astype(_np_dtype(dtype))
    return ndarray(val)


def _size(size):
    if size is None:
        return ()
    return (size,) if isinstance(size, int) else tuple(size)


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None):
    k = _mxrand._next_key()
    return _np(jax.random.uniform(k, _size(size), minval=low, maxval=high),
               dtype)


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    k = _mxrand._next_key()
    return _np(loc + scale * jax.random.normal(k, _size(size)), dtype)


def randint(low, high=None, size=None, dtype="int32", ctx=None):
    if high is None:
        low, high = 0, low
    k = _mxrand._next_key()
    return _np(jax.random.randint(k, _size(size), low, high), dtype)


def rand(*shape):
    return uniform(size=shape or None)


def randn(*shape):
    return normal(size=shape or None)


def choice(a, size=None, replace=True, p=None):
    k = _mxrand._next_key()
    arr = a._data if isinstance(a, NDArray) else (
        jnp.arange(a) if isinstance(a, int) else jnp.asarray(a))
    pv = None if p is None else (p._data if isinstance(p, NDArray)
                                 else jnp.asarray(p))
    return _np(jax.random.choice(k, arr, _size(size), replace=replace, p=pv))


def shuffle(x):
    """In-place permutation along axis 0 (numpy contract: mutates x)."""
    k = _mxrand._next_key()
    x._assign_value(jax.random.permutation(k, x._data, axis=0))


def permutation(x):
    k = _mxrand._next_key()
    arr = jnp.arange(x) if isinstance(x, int) else (
        x._data if isinstance(x, NDArray) else jnp.asarray(x))
    return _np(jax.random.permutation(k, arr, axis=0))


def multinomial(n, pvals, size=None):
    """Counts over `len(pvals)` categories from n draws."""
    k = _mxrand._next_key()
    pv = pvals._data if isinstance(pvals, NDArray) else jnp.asarray(pvals)
    draws = jax.random.categorical(
        k, jnp.log(pv), shape=_size(size) + (int(n),))
    counts = jax.nn.one_hot(draws, pv.shape[-1], dtype=jnp.int32).sum(-2)
    return _np(counts)


def gamma(shape, scale=1.0, size=None, dtype=None, ctx=None):
    k = _mxrand._next_key()
    return _np(jax.random.gamma(k, shape, _size(size)) * scale, dtype)


def exponential(scale=1.0, size=None):
    k = _mxrand._next_key()
    return _np(jax.random.exponential(k, _size(size)) * scale)


def beta(a, b, size=None):
    k = _mxrand._next_key()
    return _np(jax.random.beta(k, a, b, _size(size)))


def chisquare(df, size=None):
    k = _mxrand._next_key()
    return _np(jax.random.chisquare(k, df, shape=_size(size)))


def laplace(loc=0.0, scale=1.0, size=None):
    k = _mxrand._next_key()
    return _np(loc + scale * jax.random.laplace(k, _size(size)))


def gumbel(loc=0.0, scale=1.0, size=None):
    k = _mxrand._next_key()
    return _np(loc + scale * jax.random.gumbel(k, _size(size)))


def logistic(loc=0.0, scale=1.0, size=None):
    k = _mxrand._next_key()
    return _np(loc + scale * jax.random.logistic(k, _size(size)))


def lognormal(mean=0.0, sigma=1.0, size=None):
    k = _mxrand._next_key()
    return _np(jnp.exp(mean + sigma * jax.random.normal(k, _size(size))))


def pareto(a, size=None):
    # numpy.random.pareto is the LOMAX (Pareto II, support [0, inf)):
    # classical Pareto minus 1 (numpy docs call this out explicitly)
    k = _mxrand._next_key()
    return _np(jax.random.pareto(k, a, shape=_size(size)) - 1.0)


def power(a, size=None):
    k = _mxrand._next_key()
    return _np(jax.random.uniform(k, _size(size)) ** (1.0 / a))


def rayleigh(scale=1.0, size=None):
    k = _mxrand._next_key()
    return _np(jax.random.rayleigh(k, shape=_size(size)) * scale)


def weibull(a, size=None):
    k = _mxrand._next_key()
    return _np(jax.random.weibull_min(k, scale=1.0, concentration=a,
                                      shape=_size(size)))
