"""Loss tests (reference model: tests/python/unittest/test_loss.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import loss as gloss


def test_l2_l1():
    pred = nd.array([[1.0, 2.0]])
    label = nd.array([[2.0, 4.0]])
    l2 = gloss.L2Loss()(pred, label)
    assert np.allclose(l2.asnumpy(), [(1 + 4) / 2 / 2])
    l1 = gloss.L1Loss()(pred, label)
    assert np.allclose(l1.asnumpy(), [1.5])


def test_softmax_ce_sparse_and_dense():
    pred = nd.array([[10.0, 0.0, 0.0], [0.0, 10.0, 0.0]])
    label = nd.array([0, 1])
    l = gloss.SoftmaxCrossEntropyLoss()(pred, label)
    assert l.asnumpy().max() < 1e-3
    dense = nd.array([[1.0, 0, 0], [0, 1.0, 0]])
    l2 = gloss.SoftmaxCrossEntropyLoss(sparse_label=False)(pred, dense)
    assert np.allclose(l.asnumpy(), l2.asnumpy(), atol=1e-5)


def test_sigmoid_bce_matches_manual():
    pred = nd.array([[0.5, -0.5]])
    label = nd.array([[1.0, 0.0]])
    l = gloss.SigmoidBCELoss()(pred, label)
    p = 1 / (1 + np.exp(-np.array([0.5, -0.5])))
    manual = -(np.log(p[0]) + np.log(1 - p[1])) / 2
    assert np.allclose(l.asnumpy(), [manual], atol=1e-4)


def test_sigmoid_bce_pos_weight():
    pred = nd.array([[0.3]])
    label = nd.array([[1.0]])
    base = gloss.SigmoidBCELoss()(pred, label).asnumpy()
    weighted = gloss.SigmoidBCELoss()(pred, label, None,
                                      nd.array([2.0])).asnumpy()
    assert np.allclose(weighted, 2 * base, atol=1e-5)


def test_kl_huber_hinge():
    pred = nd.array([[0.0, 0.0]])
    label = nd.array([[0.5, 0.5]])
    kl = gloss.KLDivLoss(from_logits=False)(pred, label)
    assert kl.asnumpy()[0] < 1e-5  # uniform vs uniform
    h = gloss.HuberLoss(rho=1.0)(nd.array([[3.0]]), nd.array([[0.0]]))
    assert np.allclose(h.asnumpy(), [2.5])
    hg = gloss.HingeLoss()(nd.array([[0.5]]), nd.array([[1.0]]))
    assert np.allclose(hg.asnumpy(), [0.5])


def test_losses_backward():
    for L in [gloss.L2Loss(), gloss.L1Loss(), gloss.SoftmaxCrossEntropyLoss(),
              gloss.SigmoidBCELoss(), gloss.HuberLoss()]:
        pred = nd.array([[0.4, 0.6]])
        pred.attach_grad()
        label = nd.array([0]) if isinstance(L, gloss.SoftmaxCrossEntropyLoss) \
            else nd.array([[1.0, 0.0]])
        with autograd.record():
            l = L(pred, label)
        l.backward()
        assert np.isfinite(pred.grad.asnumpy()).all()


def test_ctc_loss_basic():
    t, n, c = 8, 2, 5
    np.random.seed(0)
    pred = nd.array(np.random.randn(n, t, c).astype(np.float32))
    label = nd.array([[1, 2, 0], [3, 0, 0]])
    l = gloss.CTCLoss()(pred, label,
                        nd.array([8, 8]), nd.array([2, 1]))
    v = l.asnumpy()
    assert v.shape == (n,)
    assert np.isfinite(v).all() and (v > 0).all()


def test_ctc_loss_length_sensitivity():
    """Padded labels must not change the loss when label_lengths given."""
    t, c = 6, 4
    np.random.seed(1)
    logits = np.random.randn(1, t, c).astype(np.float32)
    l_short = gloss.CTCLoss()(nd.array(logits), nd.array([[1, 2]]),
                              nd.array([6]), nd.array([2]))
    padded = gloss.CTCLoss()(nd.array(logits), nd.array([[1, 2, 0, 0]]),
                             nd.array([6]), nd.array([2]))
    assert np.allclose(l_short.asnumpy(), padded.asnumpy(), atol=1e-4)


def test_triplet_cosine():
    a = nd.array([[1.0, 0.0]])
    p = nd.array([[1.0, 0.1]])
    n_ = nd.array([[-1.0, 0.0]])
    tl = gloss.TripletLoss()(a, p, n_)
    assert tl.asnumpy()[0] >= 0
    ce = gloss.CosineEmbeddingLoss()(a, p, nd.array([1.0]))
    assert ce.asnumpy()[0] < 0.01


def test_poisson_nll_loss():
    """Rate-1 prediction at label k: L = exp(logp) - k*logp (from_logits)."""
    pred = nd.array([[0.0], [0.0]])       # log-rate 0 -> rate 1
    label = nd.array([[1.0], [2.0]])
    l = gloss.PoissonNLLLoss(from_logits=True)(pred, label)
    np.testing.assert_allclose(l.asnumpy(), [1.0], rtol=1e-5)
    # torch parity on a random case (log_input=True, reduction='mean')
    torch = pytest.importorskip("torch")
    p = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    y = np.random.RandomState(1).poisson(2.0, (4, 3)).astype(np.float32)
    ours = gloss.PoissonNLLLoss(from_logits=True)(nd.array(p), nd.array(y))
    ref = torch.nn.functional.poisson_nll_loss(
        torch.tensor(p), torch.tensor(y), log_input=True,
        reduction="mean")
    np.testing.assert_allclose(float(ours.asnumpy()), float(ref), rtol=1e-5)


def test_gaussian_nll_loss_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(2)
    p = rng.randn(5, 3).astype(np.float32)
    y = rng.randn(5, 3).astype(np.float32)
    v = rng.rand(5, 3).astype(np.float32) + 0.1
    ours = gloss.GaussianNLLLoss()(nd.array(p), nd.array(y), nd.array(v))
    ref = torch.nn.functional.gaussian_nll_loss(
        torch.tensor(p), torch.tensor(y), torch.tensor(v),
        full=False, reduction="none").mean(-1).numpy()
    np.testing.assert_allclose(ours.asnumpy(), ref, rtol=1e-4, atol=1e-5)
