"""Operator library (reference: src/operator/*).

All ops are pure XLA-traceable functions over `jax.Array`s, exposed
imperatively through the NDArray dispatch in mxnet_tpu.ndarray and
symbolically through mxnet_tpu.symbol. Hot fused kernels live in
pallas_kernels.py.
"""
from . import tensor_ops
from . import nn_ops
from . import linalg_ops
from . import pallas_kernels
