"""Captured-step dispatch budget wired into tier-1 (ISSUE 4 acceptance):
a warm captured step must stay within <=2 trainer-issued dispatches and
match the imperative path's numerics (same pattern as chaos_check /
check_trace)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import check_dispatch  # noqa: E402


def test_captured_dispatch_budget_and_parity():
    res = check_dispatch.run(steps=4)
    assert res["ok"], res["errors"]
    assert res["captured_dispatches_per_step"] <= res["budget"] == 2
    # the captured step really is ONE launch in steady state
    assert set(res["captured_per_step"]) == {1}
    assert res["max_rel_dev"] < 1e-3
    # ISSUE 5: the warm-step budget also covers the input side — the
    # device prefetcher makes synchronous H2D exactly zero, and the
    # detector provably fires on the host-path control
    assert res["prefetch_sync_h2d_per_step"] == 0
    assert res["prefetch_detector_fires"] is True
    # conftest forks 8 CPU devices, so the MESH placement path is what
    # ran (the configuration where the per-step device_put used to live)
    assert res["prefetch_mesh"] is True
    # ISSUE 8: the rule-sharded (2,2) captured step stays within the
    # same budget, feeds transfer-free from the device prefetcher, and
    # genuinely shrinks per-device parameter bytes
    assert res["shard_mesh"] is True
    assert res["shard_dispatches_per_step"] <= res["budget"]
    assert res["shard_sync_h2d_per_step"] == 0
    assert res["shard_param_bytes_frac"] < 1.0
    # ISSUE 15: the sharded-embedding captured step (DLRM, vocab >>
    # batch) holds the same budget warm, stages integer index batches
    # transfer-free, shrinks per-device embedding bytes to ~1/tp, and
    # its backward temp allocation fits far under one dense (V, D)
    # table gradient — the no-O(vocab)-gradient proof
    assert res["embed_mesh"] is True
    assert res["embed_dispatches_per_step"] <= res["budget"]
    assert res["embed_sync_h2d_per_step"] == 0
    assert res["embed_param_bytes_frac"] <= 0.5 + 1e-9
    assert res["embed_backward_temp_frac"] < 1.0
    # ISSUE 16: the expert-parallel MoE captured step (Dense stem +
    # ShardedMoE on (2,2)) holds the same warm budget and stages its
    # batches transfer-free through the device prefetcher
    assert res["moe_mesh"] is True
    assert res["moe_dispatches_per_step"] <= res["budget"]
    assert res["moe_sync_h2d_per_step"] == 0
    # ISSUE 19: the TIERED embedding captured step (host-resident cold
    # rows + device hot cache, RowPrefetcher-fed) holds the same warm
    # budget on an all-hit step with ZERO synchronous H2D, and its
    # forced-miss async staging moved — bounded — row bytes
    assert res["tiered_mesh"] is True
    assert res["tiered_dispatches_per_step"] <= res["budget"]
    assert res["tiered_sync_h2d_per_step"] == 0
    assert res["tiered_async_h2d_bytes"] > 0
    # ISSUE 6: the serve decode loop is ONE dispatch per warm decode
    # turn, never retraces across varying slot occupancy, and returns
    # every KV page when the traffic drains
    assert res["serve_decode_dispatches_per_step"] <= 1
    assert res["serve_decode_retraces"] == 0
    assert res["serve_pages_leaked"] == 0
    assert res["serve_decode_steps_measured"] > 0
    # ISSUE 12: the serving fast path — speculative decode holds the
    # same one-dispatch/zero-retrace budget while draft acceptance
    # varies (and genuinely accepts drafts), the prefix cache strictly
    # reduces prefill dispatches vs the cold control while the cache-
    # disabled control shows no reduction, and refcounted pages all
    # come home
    assert res["serve_spec_dispatches_per_turn"] <= 1
    assert res["serve_spec_retraces"] == 0
    assert res["serve_spec_accept_rate"] > 0
    assert res["serve_prefix_warm_turns"] < res["serve_prefix_cold_turns"]
    assert res["serve_prefix_nocache_turns"] >= \
        res["serve_prefix_cold_turns"]
    assert res["serve_fastpath_pages_leaked"] == 0
    # ISSUE 14: the QUANTIZED serve path — int8-KV decode turns hold
    # the same one-dispatch/zero-retrace budget, a fixed HBM byte
    # budget holds >= 1.9x the fp32 pool's tokens, and the page
    # accounting stays exact at that capacity (zero leaked pages)
    assert res["serve_int8_dispatches_per_step"] <= 1
    assert res["serve_int8_retraces"] == 0
    assert res["serve_int8_capacity_ratio"] >= 1.9
    assert res["serve_int8_pages_leaked"] == 0


def test_check_dispatch_cli_smoke():
    assert callable(check_dispatch.main)
    assert check_dispatch.DISPATCH_BUDGET == 2
