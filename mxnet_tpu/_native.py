"""ctypes binding for the native C++ dependency engine (cpp/engine.cc).

Builds the shared library on first import (g++, repo-local output); raises on
any failure so mxnet_tpu.engine can fall back to the pure-Python engine with
identical semantics. Exception propagation matches _PyEngine: once an op
touching a var raises, every later op depending on that var re-raises the
same error (var poisoning — the C++ side schedules but does not know about
Python exceptions; MXNet's ThreadedEngine likewise rethrows stored
exception_ptrs on WaitForVar/WaitAll).
"""
from __future__ import annotations

import atexit
import contextlib
import ctypes
import itertools
import os
import subprocess
import threading
from concurrent.futures import Future, InvalidStateError
from pathlib import Path

from ._engine_common import (FailureLog, failure_site,
                             reraise_unless_cancelled, set_exc as _set_exc)
from .base import MXNetError

__all__ = ["NativeEngine"]

_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_U64A = ctypes.POINTER(ctypes.c_uint64)


def _build_lib():
    root = Path(__file__).resolve().parent.parent
    src = root / "cpp" / "engine.cc"
    out = root / "cpp" / "build" / "libmxtpu_engine.so"
    if out.exists() and out.stat().st_mtime >= src.stat().st_mtime:
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    tmp = out.with_suffix(f".so.tmp{os.getpid()}")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
         str(src), "-o", str(tmp)],
        check=True, capture_output=True)
    os.replace(tmp, out)
    return out


def _load():
    lib = ctypes.CDLL(str(_build_lib()))
    lib.MXTPUEngineCreate.restype = ctypes.c_void_p
    lib.MXTPUEngineCreate.argtypes = [ctypes.c_int]
    lib.MXTPUEngineDelete.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineNewVar.restype = ctypes.c_uint64
    lib.MXTPUEngineNewVar.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineDelVar.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.MXTPUEnginePush.argtypes = [ctypes.c_void_p, _CB, ctypes.c_void_p,
                                    _U64A, ctypes.c_int, _U64A, ctypes.c_int]
    lib.MXTPUEnginePushPri.argtypes = [ctypes.c_void_p, _CB, ctypes.c_void_p,
                                       _U64A, ctypes.c_int, _U64A,
                                       ctypes.c_int, ctypes.c_int]
    lib.MXTPUEngineSetAgingMs.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.MXTPUEngineGetAgingMs.restype = ctypes.c_int
    lib.MXTPUEngineGetAgingMs.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineWaitForVar.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.MXTPUEngineWaitAll.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineWaitAllFor.restype = ctypes.c_int
    lib.MXTPUEngineWaitAllFor.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.MXTPUEngineNumWorkers.restype = ctypes.c_int
    lib.MXTPUEngineNumWorkers.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineSetDebug.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.MXTPUEngineDebugEnabled.restype = ctypes.c_int
    lib.MXTPUEngineDebugEnabled.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineDebugCheck.restype = ctypes.c_int
    lib.MXTPUEngineDebugCheck.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineLastError.restype = ctypes.c_char_p
    lib.MXTPUEngineLastError.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineClearError.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineDebugBypassPush.argtypes = [
        ctypes.c_void_p, _CB, ctypes.c_void_p, _U64A, ctypes.c_int, _U64A,
        ctypes.c_int]
    return lib


class NativeEngine:
    def __init__(self, workers=None):
        if workers is None:
            # floor at the _PyEngine default (4): engine tasks are host-
            # side and frequently BLOCK (gate waits, checkpoint IO, a
            # prefetch stage waiting on its source) — sizing purely by
            # cpu_count gave a 1-worker engine on 1-CPU machines, where
            # one blocking task wedges every other push (the watchdog's
            # "slow but moving queue" contract, DevicePrefetcher's
            # depth<=workers-1 clamp, and async saves all assume a second
            # worker exists)
            workers = min(8, max(4, os.cpu_count() or 4))
        self._lib = _load()
        self._h = self._lib.MXTPUEngineCreate(workers)
        self.workers = workers
        self._tasks = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._poisoned = {}          # native var id -> exception
        self._pending = set()        # futures not yet completed
        self._failures = FailureLog()
        self._hcv = threading.Condition(threading.Lock())
        self._inflight = 0           # C calls currently holding the handle
        self._trampoline = _CB(self._run)  # must outlive all pushes
        atexit.register(self._shutdown)

    # -- C++ worker thread enters Python here (ctypes grabs the GIL) --------
    def _run(self, key):
        with self._lock:
            fn, fut, read_ids, write_ids = self._tasks.pop(key)
        if fut.cancelled():
            return  # externally cancelled before running: skip, no poison
        try:
            with self._lock:
                for v in read_ids + write_ids:
                    if v in self._poisoned:
                        raise self._poisoned[v]
        except BaseException as exc:   # dependency re-raise: NOT a root cause
            with self._lock:
                for v in write_ids:
                    self._poisoned[v] = exc
            _set_exc(fut, exc)
            return
        try:
            res = fn()
        except BaseException as exc:  # noqa: BLE001 — stored, not swallowed
            self._record_failure(fn, exc)
            with self._lock:
                for v in write_ids:
                    self._poisoned[v] = exc
            _set_exc(fut, exc)
        else:
            try:
                fut.set_result(res)
            except InvalidStateError:
                pass                   # raced an external cancel

    # sticky per-instance failure report: ROOT-CAUSE task errors only
    # (dependency re-raises recorded once at the source; cancelled /
    # skipped tasks never run fn, so they cannot appear here) — parity
    # with engine._PyEngine.failures()
    def _record_failure(self, fn, exc):
        self._failures.record(failure_site(fn), exc)

    def failures(self):
        return self._failures.list()

    def clear_failures(self):
        return self._failures.clear()

    @contextlib.contextmanager
    def _live(self):
        """Hold the native handle across ONE C call. close()/_shutdown
        null `_h` and then WAIT for in-flight holders before deleting the
        engine, so use-after-close raises MXNetError instead of handing a
        freed (or null) Engine* to native code — including the race where
        close() lands between the handle read and the C call."""
        with self._hcv:
            h = self._h
            if not h:
                raise MXNetError("NativeEngine is closed")
            self._inflight += 1
        try:
            yield h
        finally:
            with self._hcv:
                self._inflight -= 1
                if not self._inflight:
                    self._hcv.notify_all()

    def _var_id(self, var):
        vid = getattr(var, "_native_id", None)
        if vid is None:
            with self._live() as h:
                vid = self._lib.MXTPUEngineNewVar(h)
            var._native_id = vid
        return vid

    def del_var(self, nid):
        """Release one native var id (facade file-var eviction). A closed
        engine already freed every native var — soft no-op."""
        try:
            with self._live() as h:
                self._lib.MXTPUEngineDelVar(h, nid)
        except MXNetError:
            pass

    def _push_impl(self, fn, read_vars, write_vars, dedup, native_push,
                   priority=None):
        """Shared body of push and the debug push variants: task + future
        bookkeeping, per-var future mirroring (so wait_* rethrow semantics
        match _PyEngine — failed readers included), then the C call."""
        with self._live() as h:   # held across the bookkeeping + C call:
            # a concurrent close() cannot delete the engine mid-push
            read_ids = list(dict.fromkeys(self._var_id(v)
                                          for v in read_vars))
            write_ids = list(dict.fromkeys(self._var_id(v)
                                           for v in write_vars))
            if dedup:
                read_ids = [v for v in read_ids if v not in write_ids]
            fut = Future()
            key = next(self._ids)
            with self._lock:
                self._tasks[key] = (fn, fut, read_ids, write_ids)
                self._pending.add(fut)
            fut.add_done_callback(self._discard)
            for v in read_vars:
                with v._lock:
                    v._reads.append(fut)
            for v in write_vars:
                with v._lock:
                    v._last_write = fut
                    v._reads = []
            ra = (ctypes.c_uint64 * len(read_ids))(*read_ids)
            wa = (ctypes.c_uint64 * len(write_ids))(*write_ids)
            if priority is None:
                native_push(h, self._trampoline, ctypes.c_void_p(key),
                            ra, len(read_ids), wa, len(write_ids))
            else:
                native_push(h, self._trampoline, ctypes.c_void_p(key),
                            ra, len(read_ids), wa, len(write_ids),
                            int(priority))
        return fut

    def push(self, fn, read_vars=(), write_vars=(), priority=1):
        return self._push_impl(fn, read_vars, write_vars, dedup=True,
                               native_push=self._lib.MXTPUEnginePushPri,
                               priority=priority)

    def set_aging_ms(self, ms):
        """Starvation-aging interval: a queued op's effective priority
        class drops by one per `ms` waited (0 disables aging)."""
        with self._live() as h:
            self._lib.MXTPUEngineSetAgingMs(h, int(ms))

    def get_aging_ms(self):
        with self._live() as h:
            return int(self._lib.MXTPUEngineGetAgingMs(h))

    def _discard(self, fut):
        with self._lock:
            self._pending.discard(fut)

    def wait_for_var(self, var):
        vid = getattr(var, "_native_id", None)
        if vid is not None:
            try:
                with self._live() as h:
                    self._lib.MXTPUEngineWaitForVar(h, vid)
            except MXNetError:
                pass   # closed: _shutdown's WaitAll already drained
        with var._lock:
            futs = list(var._reads)
            if var._last_write is not None:
                futs.append(var._last_write)
        for f in futs:
            reraise_unless_cancelled(f)

    def wait_for_all(self):
        # Snapshot before the native wait, exactly like _PyEngine snapshots
        # _pending: failures in flight at call time are rethrown.
        with self._lock:
            futs = list(self._pending)
        try:
            with self._live() as h:
                self._lib.MXTPUEngineWaitAll(h)
        except MXNetError:
            pass       # closed: _shutdown's WaitAll already drained
        for f in futs:
            reraise_unless_cancelled(f)

    # -- debug / race-detector surface (MXTPU_ENGINE_DEBUG=1) ---------------
    def set_debug(self, on):
        with self._live() as h:
            self._lib.MXTPUEngineSetDebug(h, 1 if on else 0)

    def debug_enabled(self):
        with self._live() as h:
            return bool(self._lib.MXTPUEngineDebugEnabled(h))

    def debug_check(self):
        """Returns 0 if per-var invariants hold, 1 if a hazard was found
        (details in last_error)."""
        with self._live() as h:
            return int(self._lib.MXTPUEngineDebugCheck(h))

    def last_error(self):
        with self._live() as h:
            return (self._lib.MXTPUEngineLastError(h) or b"").decode()

    def clear_error(self):
        with self._live() as h:
            self._lib.MXTPUEngineClearError(h)

    def wait_for_all_timeout(self, timeout_ms):
        """0 = drained; 1 = stall/deadlock suspected (work still pending)."""
        with self._live() as h:
            return int(self._lib.MXTPUEngineWaitAllFor(h, timeout_ms))

    def _debug_push_raw(self, fn, read_vars=(), write_vars=()):
        """TEST ONLY: push without the Python-side reads/writes dedup so
        the native self-dependency (deadlock) detector can be exercised."""
        return self._push_impl(fn, read_vars, write_vars, dedup=False,
                               native_push=self._lib.MXTPUEnginePush)

    def _debug_bypass_push(self, fn, read_vars=(), write_vars=()):
        """TEST ONLY: schedule fn WITHOUT dependency admission — simulates
        a scheduler bug so the hazard detector can be provoked."""
        return self._push_impl(
            fn, read_vars, write_vars, dedup=False,
            native_push=self._lib.MXTPUEngineDebugBypassPush)

    def _shutdown(self):
        with self._hcv:
            h, self._h = self._h, None
            while self._inflight:      # wait out in-flight C calls: the
                self._hcv.wait()       # handle must not be freed under them
        if h:
            self._lib.MXTPUEngineWaitAll(h)
            self._lib.MXTPUEngineDelete(h)

    def close(self):
        """Drain and stop the native worker threads (parity with
        _PyEngine.close for transient instances; also runs at exit)."""
        self._shutdown()
