"""Shared owner-bucketing + static-shape all-to-all exchange core.

ISSUE 15's sharded embedding lookup and ISSUE 16's MoE token routing
are ONE communication skeleton with two heads:

  group ids by owner shard  ->  static-capacity buffer  ->  all-to-all
  ->  local compute on the owner  ->  all-to-all back  ->  un-permute

(for embeddings the "id" is a table row and the owner is
``row // rows_per_shard``; for MoE the "id" is an expert index and the
owner is ``expert // experts_per_shard`` — Switch Transformer
arXiv:2101.03961 / GShard arXiv:2006.16668 dispatch). This module holds
the pieces both heads share so the bucket math and the exchange
primitive cannot drift apart:

  * `group_ranks` — the stable-sort + searchsorted rank-within-group
    kernel. Every static-capacity scatter (bucket slotting, expert
    capacity assignment) is "rank of this element within its group",
    and rank order IS the drop priority when capacity truncates.
  * `plan_buckets` — owner-bucketed ``(n_shards, U)`` layout of a
    deduped id vector (moved here from shard/embedding.py, which
    re-exports it unchanged).
  * `exchange` — the one-line tiled ``all_to_all`` wrapper. Each call
    is exactly ONE collective in the lowered HLO; the per-step pins in
    tools/check_fusion.py (`A2A_PER_TABLE`, `A2A_PER_LAYER`) count
    calls to this function per traced pass.

Everything here is shape-static: buffer capacities come from trace-time
Python ints, never from data — the captured step re-lowers on shape
change only, not on index distribution change.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["group_ranks", "plan_buckets", "exchange", "local_offsets"]


def group_ranks(ids, n_groups):
    """Stable rank-within-group of an int vector.

    Returns ``(order, sorted_ids, rank_sorted)``: ``order`` stably
    sorts ``ids`` ascending, ``sorted_ids = ids[order]``, and
    ``rank_sorted[j]`` is the rank of sorted element ``j`` within its
    group (0 for the first occurrence of each id value, counting up in
    original-order priority). Ids must lie in ``[0, n_groups)`` for the
    ranks to be meaningful; callers clip/sentinel out-of-range ids
    before or after."""
    n = ids.shape[0]
    order = jnp.argsort(ids, stable=True)
    sorted_ids = ids[order]
    start = jnp.searchsorted(sorted_ids, jnp.arange(n_groups))
    rank_sorted = jnp.arange(n) - start[sorted_ids]
    return order, sorted_ids, rank_sorted


def plan_buckets(uniq, n_shards, rows_per_shard, vocab):
    """Owner-bucketed static layout of a deduped id vector.

    Returns ``(buckets, sorted_owner, rank, order)`` where ``buckets``
    is ``(n_shards, U)`` int32 — row ``j`` holds the ids owned by shard
    ``j`` (front-packed, ``vocab`` sentinel pads; the sentinel is
    out-of-range on every shard, so downstream scatters drop it) — and
    ``(sorted_owner, rank, order)`` address each original slot's bucket
    position for the un-permute after the vector return."""
    U = uniq.shape[0]
    owner = jnp.clip(uniq // rows_per_shard, 0, n_shards - 1)
    order, sorted_owner, rank = group_ranks(owner, n_shards)
    sorted_ids = uniq[order]
    buckets = jnp.full((n_shards, U), vocab, dtype=uniq.dtype)
    buckets = buckets.at[sorted_owner, rank].set(sorted_ids, mode="drop")
    return buckets, sorted_owner, rank, order


def local_offsets(ids, rank, rows_per_shard):
    """Owner-local scatter offsets for one shard of a row-sharded table:
    ``(safe, own)`` where ``own`` marks the ids this ``rank`` owns and
    ``safe`` is their shard-local row (non-owned and sentinel ids map to
    ``rows_per_shard`` — out of range, so an ``.at[safe]`` write with
    ``mode='drop'`` discards them). The one place the "a shard never
    writes rows it does not own" rule is computed, shared by the
    sparse scatter-add update and the tiered-cache scatter-in
    (shard/embedding.py `sparse_row_update` / `scatter_rows`)."""
    loc = ids - rank * rows_per_shard
    own = (loc >= 0) & (loc < rows_per_shard)
    return jnp.where(own, loc, rows_per_shard), own


def exchange(buf, axis):
    """ONE tiled all-to-all over named mesh ``axis`` inside a
    `shard_map` body: ``buf`` is ``(n_shards, ...)`` — block ``j`` goes
    to peer ``j``; the result's block ``i`` is what peer ``i`` sent
    here. Static shape in == static shape out; this is the single
    collective the a2a budget pins count."""
    return jax.lax.all_to_all(buf, axis, 0, 0, tiled=True)
