"""BERT MLM+NSP pretraining on synthetic data — flash attention + bf16.

Usage: python examples/bert_pretrain.py [--smoke]
The attention path rides the Pallas flash kernels on TPU (padding masks
as per-row kv lengths). Matches bench_bert.py's step construction.
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.batch_size, args.seq_len, args.steps = 2, 64, 2

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.models.bert import BERTForPretraining, BERTModel

    mx.random.seed(0)
    vocab = 1000
    model = BERTForPretraining(BERTModel(
        vocab_size=vocab, units=128, hidden_size=256, num_layers=2,
        num_heads=4, max_length=args.seq_len, dropout=0.1))
    model.initialize()

    rng = np.random.RandomState(0)
    B, S, P = args.batch_size, args.seq_len, max(args.seq_len // 8, 1)
    tok = nd.array(rng.randint(0, vocab, (B, S)).astype(np.int32))
    seg = nd.array(np.zeros((B, S), np.int32))
    vl = nd.array(rng.randint(S // 2, S + 1, (B,)).astype(np.int32))
    pos = nd.array(rng.randint(0, S, (B, P)).astype(np.int32))
    mlm_y = nd.array(rng.randint(0, vocab, (B, P)).astype(np.int32))
    nsp_y = nd.array(rng.randint(0, 2, (B,)).astype(np.int32))

    trainer = mx.gluon.Trainer(model.collect_params(), "adam",
                               {"learning_rate": 1e-4})
    ce = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    for i in range(args.steps):
        with autograd.record():
            mlm, nsp = model(tok, seg, vl, pos)
            loss = ce(mlm.reshape((-1, vocab)),
                      mlm_y.reshape((-1,))).mean() + ce(nsp, nsp_y).mean()
        loss.backward()
        trainer.step(B)
        print(f"step {i}: loss={float(loss.asnumpy()):.4f}")


if __name__ == "__main__":
    main()
