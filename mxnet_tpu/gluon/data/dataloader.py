"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py).

TPU-native design: the reference forks worker *processes* that serialise
batches over shared-memory recordio. Here batches are assembled by the native
engine's threadpool (numpy staging, GIL released inside numpy/jax) and
prefetched ahead of consumption, overlapping host batching + H2D transfer
with device compute — the same pipeline role as the reference's
multi-worker loader, without pickling overhead.

Device mode (`prefetch_to_device=`): batches additionally stage through a
`mxnet_tpu.prefetch.DevicePrefetcher` — double-buffered engine tasks that
issue the committed (optionally mesh-sharded) `jax.device_put` while the
previous step computes, so a captured step (`Trainer.capture`) performs
zero synchronous H2D on its critical path. Pass True (default device), a
Context/device, a Mesh, or a KVStore/Trainer/CachedStep to match a
captured step's sharding. `pin_memory=True` maps onto this staging path
(the TPU runtime has no pinned-host allocator; a one-time warning
documents the mapping — see docs/PERFORMANCE.md, "The input pipeline").
"""
from __future__ import annotations

import warnings
from collections import deque

import numpy as np

from ... import engine
from ...ndarray.ndarray import NDArray, array
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(s)) for s in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return array(arr)


_PIN_MEMORY_WARNED = False


def _warn_pin_memory_once():
    global _PIN_MEMORY_WARNED
    if not _PIN_MEMORY_WARNED:
        _PIN_MEMORY_WARNED = True
        warnings.warn(
            "DataLoader(pin_memory=True): the TPU runtime has no pinned-"
            "host allocator — mapping it to prefetch_to_device staging "
            "(engine-prefetched async device_put; docs/PERFORMANCE.md "
            "'The input pipeline'). Pass prefetch_to_device=... "
            "explicitly to silence this.", UserWarning, stacklevel=3)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, timeout=120, prefetch_to_device=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * max(num_workers, 1))
        if pin_memory and prefetch_to_device is None:
            # reference parity: accepted, not ignored — pinning exists to
            # make H2D async, and the staging-slot path IS that here.
            # An EXPLICIT prefetch_to_device=False stays on the host path
            # (the documented opt-out).
            _warn_pin_memory_once()
            prefetch_to_device = True
        self._prefetch_to_device = prefetch_to_device

    def __len__(self):
        return len(self._batch_sampler)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def _host_iter(self):
        """Host-batch pipeline: up to `prefetch` batchify tasks in flight
        on the engine pool. Abandoning the generator mid-epoch (early
        break / GC) cancels queued tasks and no-ops in-flight ones — an
        abandoned epoch must not keep consuming the dataset."""
        if self._prefetch == 0:
            yield from self._plain_iter()
            return
        state = {"closed": False}
        pending = deque()
        it = iter(self._batch_sampler)
        group = engine.TaskGroup("dataloader")

        def submit():
            try:
                indices = next(it)
            except StopIteration:
                return False

            def make_batch(idx=indices):
                if state["closed"]:
                    return None
                return self._make_batch(idx)
            try:
                fut = group.push(make_batch,
                                 priority=engine.PRIORITY_BACKGROUND)
            except engine.EngineQueueFull:
                # bounded background class under the `reject` policy:
                # backpressure must not crash the epoch — the skipped
                # path below batchifies inline, same as a shed task
                fut = engine.skipped_future()
            pending.append((fut, indices))
            return True

        try:
            for _ in range(self._prefetch):
                if not submit():
                    break
            while pending:
                fut, indices = pending.popleft()
                submit()
                batch = fut.result()
                if engine.skipped(batch):
                    # the batchify task was SHED by a bounded background
                    # queue (engine.set_queue_limit) before it ran: its
                    # sampler indices are known, so batchify inline —
                    # backpressure must not drop training batches
                    batch = self._make_batch(indices)
                yield batch
        finally:
            state["closed"] = True
            # TaskGroup cancel works on BOTH engines: queued batchify
            # tasks never run (futures resolve to engine.CANCELLED);
            # in-flight ones no-op via the closed flag
            group.cancel()
            pending.clear()

    def _plain_iter(self):
        """Unpipelined batchify (also the prefetch=0 host path): runs in
        whichever thread iterates it — the consumer, or a staging task."""
        for indices in self._batch_sampler:
            yield self._make_batch(indices)

    def _device_iter(self):
        """Device pipeline: the host-batch generator above feeds a
        DevicePrefetcher whose staging slots overlap the committed
        (mesh-sharded) device_put with the consumer's compute.

        Handing the loader itself to DevicePrefetcher routes the
        engine-backed host generator through the global blocking-slot
        ledger (mxnet_tpu/prefetch.py): at least one pool worker stays
        free across every concurrent device pipeline, and a pipeline
        granted no slots — 1-worker engine, workers already spoken for,
        or prefetch=0 — batchifies inline in its staging task instead."""
        from ...prefetch import DevicePrefetcher
        pf = DevicePrefetcher(self, device=self._prefetch_to_device)
        try:
            yield from pf
        finally:
            pf.close()

    def __iter__(self):
        if self._prefetch_to_device not in (None, False):
            return self._device_iter()
        return self._host_iter()
