#!/usr/bin/env python
"""Multi-process / multi-host launcher (reference parity: tools/launch.py
+ dmlc_tracker — VERDICT r3 item 6).

Spawns N copies of a training command with the coordinator/rank
environment wired for `mxnet_tpu.kvstore.init_distributed`, streams each
worker's output with a rank prefix, and propagates failures (first
non-zero exit kills the rest).

Usage:
    python tools/launch.py -n 2 python examples/train_mnist.py \
        --kv-store dist --smoke
    python tools/launch.py -n 4 -H hostfile --launcher ssh python train.py

Exported env (both spellings, so either bootstrap path works):
    MXTPU_COORDINATOR=host:port   MXTPU_NUM_WORKERS=N   MXTPU_WORKER_ID=i
    DMLC_PS_ROOT_URI=host  DMLC_PS_ROOT_PORT=port
    DMLC_NUM_WORKER=N      DMLC_WORKER_ID=i   DMLC_ROLE=worker

TPU-first design note: upstream's launcher starts a ps-lite tracker plus
scheduler/server/worker roles. Here there are only WORKERS — the XLA
distributed runtime does rendezvous at MXTPU_COORDINATOR (rank 0 binds
it) and the gradient reductions are XLA collectives over ICI/DCN, so no
tracker process exists to launch.
"""
from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
import threading


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(base, coord_host, coord_port, n, rank):
    env = dict(base)
    env.update({
        "MXTPU_COORDINATOR": f"{coord_host}:{coord_port}",
        "MXTPU_NUM_WORKERS": str(n),
        "MXTPU_WORKER_ID": str(rank),
        "DMLC_PS_ROOT_URI": coord_host,
        "DMLC_PS_ROOT_PORT": str(coord_port),
        "DMLC_NUM_WORKER": str(n),
        "DMLC_WORKER_ID": str(rank),
        "DMLC_ROLE": "worker",
    })
    return env


def _stream(prefix, pipe, out):
    for line in iter(pipe.readline, b""):
        out.write(f"{prefix}{line.decode(errors='replace')}")
        out.flush()
    pipe.close()


def _read_hostfile(path, n):
    with open(path) as f:
        hosts = [ln.strip().split()[0] for ln in f
                 if ln.strip() and not ln.startswith("#")]
    if not hosts:
        raise SystemExit(f"hostfile {path} is empty")
    # round-robin over hosts, upstream-style
    return [hosts[i % len(hosts)] for i in range(n)]


def launch(n, command, launcher="local", hostfile=None, env=None):
    """Spawn the workers; returns the first non-zero exit code (0 if all
    succeed). Importable for tests."""
    base_env = dict(os.environ if env is None else env)
    port = _free_port()
    hosts = _read_hostfile(hostfile, n) if hostfile else ["127.0.0.1"] * n
    coord_host = hosts[0] if launcher == "ssh" else "127.0.0.1"

    procs = []
    threads = []
    for rank in range(n):
        wenv = _worker_env(base_env, coord_host, port, n, rank)
        if launcher == "ssh" and hosts[rank] not in ("127.0.0.1",
                                                     "localhost"):
            exports = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in wenv.items()
                if k.startswith(("MXTPU_", "DMLC_", "JAX_", "XLA_",
                                 "PYTHONPATH")))
            remote = f"cd {shlex.quote(os.getcwd())} && {exports} " \
                + " ".join(shlex.quote(c) for c in command)
            p = subprocess.Popen(["ssh", "-o", "BatchMode=yes",
                                  hosts[rank], remote],
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
        else:
            p = subprocess.Popen(command, env=wenv,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
        procs.append(p)
        t = threading.Thread(target=_stream, args=(f"[worker {rank}] ",
                                                   p.stdout, sys.stdout),
                             daemon=True)
        t.start()
        threads.append(t)

    rc = 0
    try:
        # propagate the FIRST failure: poll until any worker exits non-zero
        import time
        pending = set(range(n))
        while pending:
            for i in list(pending):
                r = procs[i].poll()
                if r is None:
                    continue
                pending.discard(i)
                if r != 0 and rc == 0:
                    rc = r
                    print(f"[launch] worker {i} exited rc={r}; "
                          "terminating the rest", file=sys.stderr)
                    for j in pending:
                        procs[j].terminate()
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=5)
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job",
        usage="launch.py -n N [-H hostfile] [--launcher local|ssh] "
              "command ...")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("--launcher", choices=("local", "ssh"), default="local")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    if args.launcher == "ssh" and not args.hostfile:
        ap.error("--launcher ssh needs -H hostfile")
    return launch(args.num_workers, args.command, launcher=args.launcher,
                  hostfile=args.hostfile)


if __name__ == "__main__":
    sys.exit(main())
