"""The bench supervisor protocol (bench.py supervise + bench_util.sweep):
the driver's measurement of record must survive a dead tunnel (window
hunting via cheap probes), crashing workers, hanging workers (stdout
salvage), and flaky candidates. These pin the exact failure modes the
axon tunnel produces (VERDICT r2 item 1, r3 item 1)."""
import json
import subprocess
import sys

import pytest

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))
import bench           # noqa: E402
import bench_util      # noqa: E402


def _ok(stdout):
    return subprocess.CompletedProcess([], 0, stdout=stdout)


def _run_supervise(monkeypatch, probes, workers, tick=1.0):
    """Run supervise() with scripted probe results (bools; exhausting the
    list repeats the last entry) and per-window worker behaviors (each a
    CompletedProcess or TimeoutExpired). A fake clock advances `tick`
    seconds per probe/sleep so deadline logic is testable without wall
    time. Returns (rc, printed_json_lines, n_probes_used)."""
    probe_iter = {"i": 0}
    worker_iter = iter(workers)
    clock = {"t": 0.0}

    def fake_probe():
        i = min(probe_iter["i"], len(probes) - 1)
        probe_iter["i"] += 1
        clock["t"] += tick
        return probes[i]

    def fake_run(cmd, stdout=None, stderr=None, timeout=None):
        b = next(worker_iter)
        if isinstance(b, BaseException):
            raise b
        return b

    printed = []
    monkeypatch.setattr(bench, "probe_tunnel", fake_probe)
    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: clock.__setitem__("t", clock["t"] + s))
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock["t"])
    real_print = print

    def capture(*args, **kwargs):
        if args and isinstance(args[0], str) and args[0].startswith("{"):
            printed.append(args[0])
        else:
            real_print(*args, **{k: v for k, v in kwargs.items()
                                 if k != "file"}, file=sys.stderr)
    monkeypatch.setattr("builtins.print", capture)
    rc = bench.supervise()
    return rc, printed, probe_iter["i"]


def test_supervisor_happy_path(monkeypatch):
    line = json.dumps({"metric": "m", "value": 1.0})
    rc, printed, _ = _run_supervise(monkeypatch, [True], [_ok(line.encode())])
    assert rc == 0 and printed == [line]


def test_supervisor_hunts_through_dead_window(monkeypatch):
    """THE round-2/3 failure mode: tunnel dead for a while, then a
    window opens. Dead probes must cost probe+sleep time, not 600s
    worker timeouts, and the worker runs exactly once."""
    line = json.dumps({"metric": "m", "value": 2.0})
    rc, printed, n_probes = _run_supervise(
        monkeypatch, [False, False, False, True], [_ok(line.encode())])
    assert rc == 0 and printed == [line]
    assert n_probes == 4


def test_supervisor_retries_after_worker_crash(monkeypatch):
    """UNAVAILABLE-style crash (rc!=0, no JSON) sends the supervisor
    back to probing; the reopened window succeeds."""
    line = json.dumps({"metric": "m", "value": 2.0})
    crash = subprocess.CompletedProcess([], 1, stdout=b"boom\n")
    rc, printed, _ = _run_supervise(
        monkeypatch, [True, True], [crash, _ok(line.encode())])
    assert rc == 0 and printed == [line]


def test_supervisor_salvages_hung_worker_stdout(monkeypatch):
    """The tunnel's PJRT-teardown hang: worker prints its JSON then
    wedges; the supervisor must salvage the line from TimeoutExpired."""
    line = json.dumps({"metric": "m", "value": 3.0})
    hung = subprocess.TimeoutExpired(cmd=[], timeout=600,
                                     output=(line + "\n").encode())
    rc, printed, _ = _run_supervise(monkeypatch, [True], [hung])
    assert rc == 0 and printed == [line]


def test_supervisor_takes_last_checkpoint_line(monkeypatch):
    """Sweep checkpoints print interim JSON lines; the LAST parseable
    line (the merged/most-complete one) is the measurement of record."""
    l1 = json.dumps({"metric": "m", "value": 1.0})
    l2 = json.dumps({"metric": "m", "value": 2.0,
                     "extra_metrics": [{"metric": "b"}]})
    out = (l1 + "\n[noise] not json\n" + l2 + "\n").encode()
    rc, printed, _ = _run_supervise(monkeypatch, [True], [_ok(out)])
    assert rc == 0 and printed == [l2]


def test_supervisor_dead_tunnel_returns_rc1_inside_deadline(monkeypatch):
    """Tunnel dead the whole window: rc=1 must come back (never a hang /
    driver-side rc=124), with probes spaced PROBE_SLEEP_S apart so the
    deadline buys ~deadline/(probe+sleep) windows — AND the output
    contract still holds: one parseable JSON line (`ok: false,
    tunnel_dead`) so the driver's parse never lands on nothing (the
    BENCH_r05 `parsed: null` failure)."""
    monkeypatch.setenv("BENCH_DEADLINE_S", "1200")
    rc, printed, n_probes = _run_supervise(
        monkeypatch, [False], [], tick=float(bench.PROBE_TIMEOUT_S))
    assert rc == 1 and len(printed) == 1
    rec = json.loads(printed[0])
    assert rec["ok"] is False and rec["reason"] == "tunnel_dead"
    assert rec["probes"] == n_probes and rec["worker_runs"] == 0
    # each dead cycle costs <= PROBE_TIMEOUT_S + PROBE_SLEEP_S = 135s
    # -> at least 8 windows inside 1200s (vs round 3's 3 blind attempts)
    assert n_probes >= 8


def test_supervisor_respects_env_deadline(monkeypatch):
    monkeypatch.setenv("BENCH_DEADLINE_S", "100")
    rc, printed, n_probes = _run_supervise(
        monkeypatch, [False], [], tick=float(bench.PROBE_TIMEOUT_S))
    assert rc == 1
    assert n_probes <= 2
    assert json.loads(printed[-1])["reason"] == "tunnel_dead"


def test_supervisor_emits_json_on_crash(monkeypatch):
    """An unexpected supervisor crash (not a worker failure) must still
    land the one-JSON-line contract: ok=false, reason=supervisor_error."""
    printed = []
    monkeypatch.setattr(bench, "probe_tunnel",
                        lambda: (_ for _ in ()).throw(OSError("boom")))
    real_print = print

    def capture(*args, **kwargs):
        if args and isinstance(args[0], str) and args[0].startswith("{"):
            printed.append(args[0])
        else:
            real_print(*args, **{k: v for k, v in kwargs.items()
                                 if k != "file"}, file=sys.stderr)
    monkeypatch.setattr("builtins.print", capture)
    rc = bench.supervise()
    assert rc == 1 and len(printed) == 1
    rec = json.loads(printed[0])
    assert rec["ok"] is False and rec["reason"] == "supervisor_error"
    assert "boom" in rec["error"]


def test_probe_tunnel_timeout_is_dead(monkeypatch):
    """A hanging backend init (the observed DOWN mode) reads as dead."""
    def hang(cmd, stdout=None, stderr=None, timeout=None):
        raise subprocess.TimeoutExpired(cmd=cmd, timeout=timeout)
    monkeypatch.setattr(bench.subprocess, "run", hang)
    assert bench.probe_tunnel() is False


def test_probe_tunnel_success(monkeypatch):
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: subprocess.CompletedProcess([], 0))
    assert bench.probe_tunnel() is True


# ------------------------------------------------------------- sweep unit
def test_sweep_skips_failures_and_reports_best():
    seen = []
    results = {8: 10.0, 16: RuntimeError("oom"), 32: 30.0}

    def run_one(c):
        r = results[c]
        if isinstance(r, Exception):
            raise r
        return r
    best, cand = bench_util.sweep([8, 16, 32], 1e9, run_one,
                                  on_best=seen.append)
    assert (best, cand) == (30.0, 32)
    assert seen == [10.0, 30.0]       # checkpoint per improvement

def test_sweep_budget_gates_later_candidates(monkeypatch):
    clock = {"t": 0.0}
    monkeypatch.setattr(bench_util.time, "monotonic",
                        lambda: clock["t"])

    def run_one(c):
        clock["t"] += 400.0           # each candidate is slow
        return float(c)
    best, cand = bench_util.sweep([1, 2, 3], 300.0, run_one)
    assert (best, cand) == (1.0, 1)   # 2 and 3 never start


def test_sweep_raises_when_nothing_lands():
    def always_fail(c):
        raise ValueError("x")
    with pytest.raises(RuntimeError, match="no sweep candidate"):
        bench_util.sweep([1, 2], 1e9, always_fail)


def test_supervisor_keeps_stage1_line_when_full_compile_flaps(monkeypatch):
    """The staged worker (VERDICT r4 item 1b) prints a fast unroll=1
    checkpoint BEFORE the ~7min unroll=8 compile; if the tunnel flaps
    mid-compile (worker killed, rc!=0, or wedged), that stage-1 line IS
    the measurement of record — a short window can no longer yield
    nothing."""
    stage1 = json.dumps({"metric": "resnet50_train_throughput",
                         "value": 2434.05, "vs_baseline": 0.9736})
    # crash mid-compile after printing stage-1
    crashed = subprocess.CompletedProcess([], 137,
                                          stdout=(stage1 + "\n").encode())
    rc, printed, _ = _run_supervise(monkeypatch, [True], [crashed])
    assert rc == 0 and printed == [stage1]
    # ...and the wedged variant (TimeoutExpired mid-compile)
    wedged = subprocess.TimeoutExpired(cmd=[], timeout=900,
                                       output=(stage1 + "\n").encode())
    rc2, printed2, _ = _run_supervise(monkeypatch, [True], [wedged])
    assert rc2 == 0 and printed2 == [stage1]
