"""Vision transforms (reference: gluon/data/vision/transforms.py).

Transforms operate on HWC uint8/float NDArrays (reference convention) and
compose via `Compose`. ToTensor converts HWC->CHW float32/255.
"""
from __future__ import annotations

import numpy as np

from ....ndarray.ndarray import NDArray, array, _apply
from ...block import Block, HybridBlock

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomCrop", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomLighting",
           "RandomColorJitter"]


class Compose(Block):
    def __init__(self, transforms):
        super().__init__()
        self._transforms = transforms

    def forward(self, x):
        for t in self._transforms:
            x = t(x)
        return x


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        def fn(a):
            a = a.astype(jnp.float32) / 255.0
            if a.ndim == 3:
                return jnp.transpose(a, (2, 0, 1))
            return jnp.transpose(a, (0, 3, 1, 2))
        return _apply(fn, [x])


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32)
        self._std = np.asarray(std, np.float32)

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        mean, std = self._mean, self._std

        def fn(a, _m=mean, _s=std):
            m = jnp.asarray(_m).reshape(-1, 1, 1) if _m.ndim else _m
            s = jnp.asarray(_s).reshape(-1, 1, 1) if _s.ndim else _s
            return (a - m) / s
        return _apply(fn, [x])


def _resize_hwc(a, size):
    import jax.image
    h, w = (size, size) if isinstance(size, int) else (size[1], size[0])
    return jax.image.resize(a, (h, w, a.shape[2]), method="bilinear")


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size

    def forward(self, x):
        return _apply(lambda a, _s=self._size: _resize_hwc(
            a.astype("float32"), _s), [x])


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[0], x.shape[1]
        y0, x0 = max((H - h) // 2, 0), max((W - w) // 2, 0)
        return x[y0:y0 + h, x0:x0 + w, :]


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad

    def forward(self, x):
        import numpy as _np
        w, h = self._size
        a = x.asnumpy()
        if self._pad:
            p = self._pad
            a = _np.pad(a, ((p, p), (p, p), (0, 0)), mode="constant")
        H, W = a.shape[:2]
        y0 = _np.random.randint(0, max(H - h, 0) + 1)
        x0 = _np.random.randint(0, max(W - w, 0) + 1)
        return array(a[y0:y0 + h, x0:x0 + w])


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import numpy as _np
        H, W = x.shape[0], x.shape[1]
        area = H * W
        for _ in range(10):
            target = area * _np.random.uniform(*self._scale)
            ar = _np.random.uniform(*self._ratio)
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if w <= W and h <= H:
                x0 = _np.random.randint(0, W - w + 1)
                y0 = _np.random.randint(0, H - h + 1)
                crop = x[y0:y0 + h, x0:x0 + w, :]
                return _apply(lambda a, _s=self._size: _resize_hwc(
                    a.astype("float32"), _s), [crop])
        return _apply(lambda a, _s=self._size: _resize_hwc(
            a.astype("float32"), _s), [x])


class RandomFlipLeftRight(Block):
    def forward(self, x):
        import numpy as _np
        if _np.random.rand() < 0.5:
            return x[:, ::-1, :]
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        import numpy as _np
        if _np.random.rand() < 0.5:
            return x[::-1, :, :]
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        import numpy as _np
        alpha = 1.0 + _np.random.uniform(-self._b, self._b)
        return x.astype("float32") * alpha


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        import numpy as _np
        alpha = 1.0 + _np.random.uniform(-self._c, self._c)
        xf = x.astype("float32")
        mean = xf.mean()
        return xf * alpha + mean * (1 - alpha)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        import numpy as _np
        alpha = 1.0 + _np.random.uniform(-self._s, self._s)
        xf = x.astype("float32")
        gray = xf.mean(axis=2, keepdims=True)
        return xf * alpha + gray * (1 - alpha)


class RandomHue(Block):
    """Hue jitter by YIQ rotation (reference: image.HueJitterAug): rotate
    the chroma plane by a random angle in [-hue, hue]*pi."""
    _t_yiq = np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], np.float32)
    _t_rgb = np.array([[1.0, 0.956, 0.621],
                       [1.0, -0.272, -0.647],
                       [1.0, -1.107, 1.705]], np.float32)

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        import numpy as _np
        alpha = _np.random.uniform(-self._h, self._h)
        u, w = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        rot = _np.array([[1, 0, 0], [0, u, -w], [0, w, u]], _np.float32)
        m = self._t_rgb @ rot @ self._t_yiq
        xf = x.astype("float32")
        return xf.dot(array(m.T.astype(_np.float32)))


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise."""
    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        import numpy as _np
        alpha = _np.random.normal(0, self._alpha, 3).astype(_np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return x.astype("float32") + array(rgb.reshape(1, 1, 3))


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        import numpy as _np
        order = _np.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x
