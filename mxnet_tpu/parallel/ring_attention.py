"""Ring attention: sequence/context parallelism over the 'sp' mesh axis.

First-class per the build brief (long-context training). Each device holds a
sequence shard of Q/K/V; K/V blocks rotate around the ring with
`lax.ppermute` while the local Q accumulates an online-softmax partial — the
blockwise/flash combine — so attention over sequence length S costs O(S/P)
memory per chip and the K/V transfers ride ICI neighbour links, overlapping
with the block matmuls (Liu et al., Ring Attention; PAPERS.md).

Causal masking uses the global block indices so the rotated source shard is
masked correctly at every step.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

__all__ = ["ring_attention", "ring_attention_sharded"]


def _as_varying(x, axis_name):
    """lax.pcast(..., 'varying') where available; no-op off shard_map."""
    try:
        from jax.lax import pcast
        return pcast(x, to="varying", axes=axis_name)
    except Exception:
        try:
            return jax.lax.pvary(x, axis_name)
        except Exception:
            return x


def _block_attn(q, k, v, mask):
    """Partial attention stats for one K/V block.
    q: (B,H,Sq,D) k,v: (B,H,Sk,D). Returns (m, l, o_unnorm)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = jnp.where(mask, s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, -1e30)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    return m, l, o.astype(jnp.float32)


def ring_attention(q, k, v, axis_name="sp", causal=False, sm_scale=None):
    """Call INSIDE shard_map with q,k,v sequence-sharded: (B,H,S/P,D)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    q = q * sm_scale
    n_dev = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[2]
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    qi = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)
    kj = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 1)

    def step(carry, i):
        k_cur, v_cur, m_acc, l_acc, o_acc = carry
        src = (my_idx - i) % n_dev      # which shard this K/V block is
        if causal:
            # global positions: my rows = my_idx*s_loc + qi ; cols = src*s_loc + kj
            mask = (my_idx * s_loc + qi)[None, None] >= \
                   (src * s_loc + kj)[None, None]
        else:
            mask = jnp.ones((1, 1, s_loc, s_loc), bool)
        m_b, l_b, o_b = _block_attn(q, k_cur, v_cur, mask)
        m_new = jnp.maximum(m_acc, m_b)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_b - m_new)
        l_new = l_acc * alpha + l_b * beta
        o_new = o_acc * alpha + o_b * beta
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, o_new), None

    b, h, _, d = q.shape
    m0 = jnp.full((b, h, s_loc, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    # mark the accumulators device-varying so the scan carry types agree
    # under shard_map's VMA checking (the k/v carries vary via ppermute)
    m0, l0, o0 = (_as_varying(t, axis_name) for t in (m0, l0, o0))
    carry, _ = jax.lax.scan(step, (k, v, m0, l0, o0), jnp.arange(n_dev))
    _, _, m, l, o = carry
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False):
    """Convenience wrapper: shard (B,H,S,D) arrays over S and run the ring."""
    spec = P(None, None, axis_name, None)
    f = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return f(q, k, v)
