"""Continuous (inflight) batching scheduler (ISSUE 6).

Every `step()` is one turn of the serving crank:

  1. ADMIT — pop queued requests into free decode slots while pages are
     available (all-or-nothing first-page grant), running the cached
     prefill executable per admission;
  2. DECODE — one shared decode dispatch for ALL active slots (mixed
     lengths share the ragged-paged-attention launch), growing each
     active request by one token and one cache position, allocating a
     fresh page exactly when a request crosses a page boundary;
  3. EVICT — requests that emitted EOS or hit their token budget leave
     their slot and return every page to the pool immediately, so the
     NEXT step can admit into the freed capacity. No drain barriers:
     short requests never wait for long ones (`static_batching=True`
     flips exactly this off — admission only into an EMPTY batch — and is
     the baseline `bench_serve.py` beats).

Backpressure: the admission queue is bounded (`max_queue`); a submit into
a full queue raises `ServeOverloaded` (counted) instead of buffering
unboundedly. A request that cannot get its next page mid-decode is
PREEMPTED — pages freed, requeued at the front — rather than deadlocking
the pool (`serve_page_preemptions`).

Fault discipline (fault/injection.py points `serve.admit` /
`serve.decode`): an admit-time fault fails ONLY the request being
admitted. A decode-time fault kills the whole in-flight batch — every
active request frees its pages and is retried from scratch (bounded by
`max_retries`) or failed cleanly; either way `kv_pages_in_use` returns to
baseline (the chaos test asserts this). An error raised by the decode
executable itself additionally resets the page pools (their contents are
no longer trustworthy after a partial in-place step).
"""
from __future__ import annotations

import collections
import threading
import time

from ..base import MXNetError
from ..fault import injection as _finj
from ..observability import registry as _obs_registry
from ..observability import tracer as _tracer
from .decode import MemoryStateLost
from .kv_pages import NULL_PAGE, PageAllocError

__all__ = ["Request", "Scheduler", "ServeError", "ServeOverloaded",
           "ServeDeadlineExceeded", "StepResult"]

_STREAM_END = object()


class ServeError(MXNetError):
    """A request failed inside the serving engine."""


class ServeOverloaded(ServeError):
    """Admission queue full — backpressure; retry later."""


class ServeDeadlineExceeded(ServeError):
    """The request's `deadline_ms` elapsed before it finished: it was
    evicted (queued or mid-decode), its pages freed, and
    `serve_deadline_expired` counted it."""


class Request:
    """One inference request + its result/stream plumbing. Create via
    `Server.submit`; consume via `.result()` / `.stream()` / `.tokens`."""

    def __init__(self, rid, src, max_new_tokens, deadline_ms=None):
        self.id = rid
        self.src = src
        self.max_new_tokens = int(max_new_tokens)
        # absolute monotonic deadline: survives retries/preemptions (the
        # budget is end-to-end, not per-attempt)
        self.deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        self.state = "queued"       # queued|running|done|failed
        self.tokens = []            # generated ids (EOS included if hit)
        self.error = None
        self._exc = None            # typed failure (ServeDeadlineExceeded)
        self.retries = 0            # fault retries (budget: max_retries)
        self.preemptions = 0        # page-pressure requeues (own budget)
        self.t_submit = time.perf_counter()
        self.t_first_token = None
        self.t_done = None
        self._slot = None
        self._pages = []
        self._cur_tok = None
        self._done = threading.Event()
        self._chunks = collections.deque()  # streamed tokens + sentinel
        self._chunk_cv = threading.Condition()
        self._inline_sched = None   # set by Server(engine_driven=False)
        self._on_finish = None      # one-shot scheduler bookkeeping hook

    # ------------------------------------------------------- consumer
    @property
    def ttft(self):
        """Seconds from submit to first generated token (None until)."""
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def latency(self):
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until the request finishes; returns the generated token
        list, or raises `ServeError` if it failed. In inline mode
        (Server(engine_driven=False)) this call cranks the scheduler,
        still honouring the deadline."""
        wait_timeout = timeout
        if self._inline_sched is not None:
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while not self._done.is_set():
                if deadline is not None and time.monotonic() > deadline:
                    break
                self._inline_sched.step()
            if deadline is not None:
                # the crank spent (part of) the budget; only the
                # remainder may be slept away below
                wait_timeout = max(0.0, deadline - time.monotonic())
        if not self._done.wait(wait_timeout):
            raise ServeError(f"request {self.id} timed out after "
                             f"{timeout}s")
        if self.state == "failed":
            if self._exc is not None:
                raise self._exc
            raise ServeError(f"request {self.id} failed: {self.error}")
        return list(self.tokens)

    def stream(self, timeout=None):
        """Yield generated token ids as they are produced; raises
        `ServeError` at the end if the request failed. `timeout` bounds
        the wait for EACH token (inline mode cranks the scheduler up to
        that per-token deadline)."""
        while True:
            with self._chunk_cv:
                item = self._chunks.popleft() if self._chunks else None
            if item is None:
                if self._inline_sched is not None:
                    deadline = None if timeout is None \
                        else time.monotonic() + timeout
                    while True:
                        with self._chunk_cv:
                            if self._chunks:
                                break
                        if deadline is not None and \
                                time.monotonic() > deadline:
                            raise ServeError(
                                f"request {self.id}: no token within "
                                f"{timeout}s")
                        self._inline_sched.step()
                    continue
                with self._chunk_cv:
                    while not self._chunks:
                        if not self._chunk_cv.wait(timeout):
                            raise ServeError(
                                f"request {self.id}: no token within "
                                f"{timeout}s")
                    item = self._chunks.popleft()
            if item is _STREAM_END:
                if self.state == "failed":
                    if self._exc is not None:
                        raise self._exc
                    raise ServeError(
                        f"request {self.id} failed: {self.error}")
                return
            yield item

    # ------------------------------------------------------- producer
    def _emit(self, tok):
        self.tokens.append(tok)
        with self._chunk_cv:
            self._chunks.append(tok)
            self._chunk_cv.notify_all()

    def _finish(self, state, error=None):
        self.state = state
        self.error = error
        self.t_done = time.perf_counter()
        cb, self._on_finish = self._on_finish, None
        if cb is not None:
            cb()
        with self._chunk_cv:
            self._chunks.append(_STREAM_END)
            self._chunk_cv.notify_all()
        self._done.set()


class StepResult:
    """What one scheduler turn did (truthy = progress was made)."""
    __slots__ = ("admitted", "decoded", "completed", "preempted", "retried")

    def __init__(self, admitted=0, decoded=0, completed=0, preempted=0,
                 retried=0):
        self.admitted = admitted
        self.decoded = decoded
        self.completed = completed
        self.preempted = preempted
        self.retried = retried

    def __bool__(self):
        return bool(self.admitted or self.decoded)


class Scheduler:
    def __init__(self, runtime, pool, bos_id=2, eos_id=3, max_queue=64,
                 max_retries=1, max_preemptions=8, static_batching=False):
        import numpy as np
        self._np = np
        self._rt = runtime
        self._pool = pool
        self.bos_id = int(bos_id)
        self.eos_id = int(eos_id)
        self.max_queue = int(max_queue)
        self.max_retries = int(max_retries)
        # page-pressure preemptions are legitimate queueing, not faults —
        # they get their own (laxer) restart budget so transient capacity
        # pressure cannot burn a request's fault retries
        self.max_preemptions = int(max_preemptions)
        self.static_batching = bool(static_batching)
        s = runtime.slots
        self._slots = [None] * s                       # Request per slot
        self._page_tables = np.full(
            (s, runtime.max_pages_per_slot), NULL_PAGE, np.int32)
        self._lens = np.zeros((s,), np.int32)
        self._queue = collections.deque()
        self._lock = threading.Lock()
        # live admitted requests carrying a deadline — gates the per-turn
        # expiry sweep so deadline-free workloads never pay the O(queue)
        # scan (same idiom as engine._admit's _deadline_queued gate)
        self._deadline_live = 0
        self._deadline_lock = threading.Lock()
        # serialises whole turns: step() (engine loop or inline result()
        # cranks from several threads), defrag()'s device remap, and
        # shutdown() must never interleave mid-turn
        self._step_lock = threading.Lock()
        self._next_id = 0
        self.tokens_generated = 0   # per-instance (the registry counter
                                    # below is process-global)
        reg = _obs_registry()
        self._m_queue = reg.gauge("serve_queue_depth")
        self._m_queue.set(0)
        self._m_active = reg.gauge("serve_active_slots")
        self._m_active.set(0)
        self._m_tokens = reg.counter("serve_tokens")
        self._m_ok = reg.counter("serve_requests", result="ok")
        self._m_failed = reg.counter("serve_requests", result="failed")
        self._m_rejected = reg.counter("serve_requests", result="rejected")
        self._m_retries = reg.counter("serve_decode_retries")
        self._m_preempt = reg.counter("serve_page_preemptions")
        self._m_deadline = reg.counter("serve_deadline_expired")
        self._m_ttft = reg.histogram("serve_ttft_seconds")
        self._m_latency = reg.histogram("serve_request_seconds")
        self._m_step = reg.histogram("serve_decode_step_seconds")

    # ------------------------------------------------------------ API
    def submit(self, src_tokens, max_new_tokens, deadline_ms=None):
        """Enqueue a request; returns the `Request` handle. Raises
        `ServeOverloaded` when the bounded admission queue is full and
        `ServeError` when the `serve.admit` fault point fires.
        `deadline_ms` bounds the request END-TO-END (queue wait included):
        once it elapses the request is evicted wherever it is — queued or
        mid-decode — with `ServeDeadlineExceeded`, its pages freed and
        `serve_deadline_expired` counting the eviction."""
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if max_new > self._rt.max_pages_per_slot * self._rt.page_size:
            raise MXNetError(
                f"max_new_tokens {max_new} exceeds the per-slot page "
                f"budget ({self._rt.max_pages_per_slot} pages x "
                f"{self._rt.page_size})")
        need = self._pool.pages_for(max_new)
        if need > self._pool.capacity:
            # doomed even with the pool to itself: reject at submit time
            # instead of burning prefills + retries on guaranteed
            # mid-decode page exhaustion
            raise MXNetError(
                f"max_new_tokens {max_new} needs {need} pages but the "
                f"pool only has {self._pool.capacity} total")
        src = self._np.asarray(src_tokens, self._np.int32).reshape(-1)
        if src.size == 0:
            raise MXNetError("src_tokens must be non-empty (an empty "
                             "source has no cross-attention context)")
        if src.size > self._rt.max_src_len:
            raise MXNetError(f"source length {src.size} exceeds the "
                             f"server's max_src_len "
                             f"{self._rt.max_src_len}")
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        req = Request(rid, src, max_new, deadline_ms=deadline_ms)
        try:
            if _finj.ENABLED:
                _finj.check("serve.admit", context=f"request {rid}")
        except Exception as e:
            self._m_failed.inc()
            req._finish("failed", f"admit fault: {e!r}")
            raise ServeError(f"request {rid} rejected at admission: "
                             f"{e}") from e
        with self._lock:
            if len(self._queue) >= self.max_queue:
                self._m_rejected.inc()
                req._finish("failed", "admission queue full")
                raise ServeOverloaded(
                    f"admission queue full ({self.max_queue}); retry "
                    "later")
            self._queue.append(req)
            self._m_queue.set(len(self._queue))
            if req.deadline is not None:
                with self._deadline_lock:
                    self._deadline_live += 1
                req._on_finish = self._dec_deadline_live
        if _tracer.ACTIVE:
            _tracer.instant("serve.submit", args={"id": rid})
        return req

    def pending_work(self):
        with self._lock:
            return bool(self._queue) or any(
                r is not None for r in self._slots)

    def active_count(self):
        return sum(1 for r in self._slots if r is not None)

    # ----------------------------------------------------------- step
    def step(self):
        """One serving turn: admit -> decode -> evict. Returns a
        `StepResult` (truthy when any progress was made). Turns are
        serialised on an internal lock (inline handles may crank from
        several threads; `defrag`/`shutdown` take the same lock)."""
        with self._step_lock:
            return self._step_locked()

    def _step_locked(self):
        res = StepResult()
        self._expire_deadlines()
        res.admitted = self._admit(res)
        active = [(s, r) for s, r in enumerate(self._slots)
                  if r is not None]
        if not active:
            self._m_active.set(0)
            return res
        t0 = time.perf_counter()
        try:
            if _finj.ENABLED:
                _finj.check("serve.decode",
                            context=f"{len(active)} active")
            self._grow_pages(active, res)
            active = [(s, r) for s, r in enumerate(self._slots)
                      if r is not None]
            if not active:
                return res
            next_tok = self._decode(active)
        except _finj.FaultInjected as e:
            self._fail_inflight(active, res, e, reset_pages=False)
            return res
        except Exception as e:  # executable error: pages untrustworthy
            self._fail_inflight(active, res, e, reset_pages=True)
            return res
        self._m_step.observe(time.perf_counter() - t0)
        res.decoded = len(active)
        now = time.perf_counter()
        for s, r in active:
            tok = int(next_tok[s])
            if r.t_first_token is None:
                r.t_first_token = now
            r._emit(tok)
            r._cur_tok = tok
            self._lens[s] += 1
            if tok == self.eos_id or len(r.tokens) >= r.max_new_tokens:
                self._evict(s, r, "done")
                res.completed += 1
        self._m_active.set(self.active_count())
        return res

    def defrag(self):
        """Compact the page pool: renumber live pages into the low ids,
        remap the device pools (one gather dispatch) and every active
        slot's page table + request page list. Takes the step lock, so
        it is safe to call from any thread while the engine loop is
        decoding; a no-op when the pool is already compact. Returns the
        number of pages that moved."""
        with self._step_lock:
            return self._defrag_locked()

    def _defrag_locked(self):
        mapping = self._pool.defrag()
        if not mapping:
            return 0
        self._rt.remap_pages(mapping)
        np = self._np
        remap = np.arange(self._rt.num_pages)
        for old, new in mapping.items():
            remap[old] = new
        self._page_tables = remap[self._page_tables].astype(np.int32)
        for r in self._slots:
            if r is not None:
                r._pages = [mapping.get(p, p) for p in r._pages]
        return len(mapping)

    def shutdown(self, reason="server closed"):
        """Fail every queued and in-flight request (pages freed, events
        set) — `Server.close()` calls this so held handles can never
        block forever on a stopped loop."""
        with self._step_lock:
            self._shutdown_locked(reason)

    def _shutdown_locked(self, reason):
        with self._lock:
            queued = list(self._queue)
            self._queue.clear()
            self._m_queue.set(0)
        for r in queued:
            self._m_failed.inc()
            r._finish("failed", reason)
        for s, r in enumerate(self._slots):
            if r is not None:
                self._release_slot(s, r)
                self._m_failed.inc()
                r._finish("failed", reason)
        self._m_active.set(0)

    def run_until_idle(self, max_steps=100000):
        """Drive `step()` until queue and slots drain (tests/bench)."""
        for _ in range(max_steps):
            if not self.pending_work():
                return
            self.step()
        raise MXNetError("scheduler failed to drain")

    # ------------------------------------------------------- internals
    def _dec_deadline_live(self):
        with self._deadline_lock:
            self._deadline_live -= 1

    def _expire_deadlines(self):
        """Evict every request whose end-to-end deadline has elapsed —
        queued requests leave the admission queue, running ones leave
        their slot with pages freed — finishing each with a clean
        `ServeDeadlineExceeded` (serve_deadline_expired counts them).
        Gated on the live deadline count: a deadline-free workload pays
        one lock acquire per turn, not an O(queue) sweep."""
        with self._deadline_lock:
            if not self._deadline_live:
                return
        now = time.monotonic()
        expired = []
        with self._lock:
            stale = [r for r in self._queue
                     if r.deadline is not None and now > r.deadline]
            if stale:
                stale_ids = {id(r) for r in stale}   # O(n) rebuild, not
                keep = collections.deque(r for r in self._queue  # O(n*k)
                                         if id(r) not in stale_ids)
                self._queue = keep
                self._m_queue.set(len(keep))
                expired.extend(stale)
        for s, r in enumerate(self._slots):
            if r is not None and r.deadline is not None \
                    and now > r.deadline:
                self._release_slot(s, r)
                expired.append(r)
        for r in expired:
            self._m_deadline.inc()
            self._m_failed.inc()
            r._exc = ServeDeadlineExceeded(
                f"request {r.id} exceeded its deadline "
                f"({len(r.tokens)} token(s) generated)")
            r._finish("failed", "deadline exceeded")
            if _tracer.ACTIVE:
                _tracer.instant("serve.deadline_expired",
                                args={"id": r.id})
        if expired:
            self._m_active.set(self.active_count())

    def _admit(self, res=None):
        admitted = 0
        while True:
            # static mode: admit only into an EMPTY batch — but fill the
            # whole batch in that one turn (requests admitted THIS call
            # don't close the window, or "static" would degenerate to
            # sequential batch-size-1 decoding)
            if self.static_batching and self.active_count() > admitted:
                break
            free = [s for s, r in enumerate(self._slots) if r is None]
            if not free:
                break
            with self._lock:
                if not self._queue:
                    break
                req = self._queue.popleft()
                self._m_queue.set(len(self._queue))
            try:
                pages = self._pool.alloc(1)
            except PageAllocError:
                # no first page -> push back and stop admitting; decode
                # progress on the current actives will free pages
                with self._lock:
                    self._queue.appendleft(req)
                    self._m_queue.set(len(self._queue))
                break
            s = free[0]
            try:
                self._rt.prefill(s, req.src)
            except Exception as e:
                self._pool.free(pages)
                self._m_failed.inc()
                req._finish("failed", f"prefill error: {e!r}")
                if isinstance(e, MemoryStateLost):
                    # the donated memory buffers died: EVERY in-flight
                    # slot lost its encoder state (the runtime already
                    # rebuilt zeroed buffers) — restart those requests
                    # from scratch; re-admission re-prefills each slot
                    self._fail_inflight(
                        [(s2, r2) for s2, r2 in enumerate(self._slots)
                         if r2 is not None],
                        res if res is not None else StepResult(), e,
                        reset_pages=False)
                    break
                continue
            req.state = "running"
            req._slot = s
            req._pages = pages
            req._cur_tok = self.bos_id
            self._slots[s] = req
            self._page_tables[s, :] = NULL_PAGE
            self._page_tables[s, 0] = pages[0]
            self._lens[s] = 0
            admitted += 1
        if admitted:
            self._m_active.set(self.active_count())
        return admitted

    def _grow_pages(self, active, res):
        """Allocate the next page for any active slot whose NEXT cached
        position crosses a page boundary; preempt (free + requeue) the
        request when the pool is dry instead of wedging the batch."""
        psize = self._rt.page_size
        for s, r in active:
            pos = int(self._lens[s])
            if pos == 0 or pos % psize:
                continue        # current page still has room
            slot_page = pos // psize
            try:
                page = self._pool.alloc(1)[0]
            except PageAllocError:
                self._m_preempt.inc()
                self._requeue(s, r, "page pool exhausted mid-decode",
                              preempted=True)
                res.preempted += 1
                continue
            r._pages.append(page)
            self._page_tables[s, slot_page] = page

    def _decode(self, active):
        mask = self._np.zeros((self._rt.slots,), self._np.int32)
        toks = self._np.zeros((self._rt.slots,), self._np.int32)
        for s, r in active:
            mask[s] = 1
            toks[s] = r._cur_tok
        if _tracer.ACTIVE:
            with _tracer.span("serve.decode_step", cat="serve",
                              args={"active": len(active)}):
                out, _ = self._rt.decode(self._page_tables, self._lens,
                                         toks, mask)
        else:
            out, _ = self._rt.decode(self._page_tables, self._lens,
                                     toks, mask)
        return out

    def _release_slot(self, s, r):
        if r._pages:
            self._pool.free(r._pages)
        r._pages = []
        r._slot = None
        self._slots[s] = None
        self._page_tables[s, :] = NULL_PAGE
        self._lens[s] = 0

    def _evict(self, s, r, state):
        self._release_slot(s, r)
        self._m_ok.inc()
        # token/TTFT metrics land ONCE, at completion — per-step counting
        # would double-report any request a fault or preemption restarted
        self._m_tokens.inc(len(r.tokens))
        self.tokens_generated += len(r.tokens)
        if r.ttft is not None:
            self._m_ttft.observe(r.ttft)
        self._m_latency.observe(time.perf_counter() - r.t_submit)
        r._finish(state)
        if _tracer.ACTIVE:
            _tracer.instant("serve.request_done", args={
                "id": r.id, "tokens": len(r.tokens),
                "ttft_ms": round((r.ttft or 0) * 1e3, 3)})

    def _requeue(self, s, r, why, preempted=False):
        """Restart a request from scratch (pages freed, queued at the
        front); fail it cleanly when the relevant restart budget is
        spent (fault retries and page preemptions count separately). The
        stream restarts too: undelivered chunks from the aborted attempt
        are dropped and TTFT re-arms, so consumers see one clean token
        sequence (tokens a live streamer already pulled before the fault
        are superseded by the retry — inherent to streaming + retry)."""
        self._release_slot(s, r)
        if preempted:
            r.preemptions += 1
            exhausted = r.preemptions > self.max_preemptions
        else:
            r.retries += 1
            exhausted = r.retries > self.max_retries
        r.tokens = []
        r._cur_tok = None
        r.t_first_token = None
        with r._chunk_cv:
            r._chunks.clear()
        if exhausted:
            self._m_failed.inc()
            r._finish("failed", why)
            return False
        r.state = "queued"
        with self._lock:
            self._queue.appendleft(r)
            self._m_queue.set(len(self._queue))
        return True

    def _fail_inflight(self, active, res, exc, reset_pages):
        """A decode-time fault killed the whole in-flight batch: every
        active request retries from scratch or fails cleanly; page
        accounting returns to baseline either way."""
        self._m_retries.inc()
        for s, r in active:
            if self._requeue(s, r, f"decode fault: {exc!r}"):
                res.retried += 1
        if reset_pages:
            self._rt.reset_pages()
        self._m_active.set(self.active_count())
