"""Profiler (reference: python/mxnet/profiler.py).

`set_config/start/stop/dumps/dump` map onto THREE recorders at once:

  * `jax.profiler` — the XLA/TPU device trace (TensorBoard/Perfetto).
  * `observability.tracer` — the host-side Chrome-trace span recorder
    (engine tasks, KVStore collectives, Trainer steps, sampled op
    dispatch). `dump()` writes its `profile.json`, reference-style.
  * `observability.metrics_registry` — the always-on dispatch/jit-cache/
    bucket telemetry the fused-Trainer subsystem (PR 1) keys off. The
    public counter API below (`record_dispatch`/`dispatch_count`/...) is
    unchanged; the storage moved from an ad-hoc `_state` dict into the
    labelled registry so `mx.observability.summary()` and the JSONL sink
    see the same numbers.

pause()/resume() genuinely suspend/restart both the jax device trace and
the host tracer (each resume opens a fresh jax trace session in the same
directory — the XLA profiler has no native pause).
"""
from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict

import jax

from .observability import tracer as _tracer
from .observability import registry as _registry

__all__ = ["set_config", "start", "stop", "pause", "resume", "dumps",
           "dump", "Scope", "record_op", "record_dispatch", "dispatch_count",
           "reset_dispatches", "record_jit_cache", "jit_cache_stats",
           "record_buckets", "bucket_sizes"]

_state = {"dir": "/tmp/mxtpu_profile", "filename": None, "running": False,
          "ops": defaultdict(lambda: [0, 0.0]), "t0": None,
          "paused": False,         # pause() called on a live session
          "jax_trace": False,      # a jax.profiler trace session is open
          "jax_paused": False}     # pause() closed one; resume() reopens

# registry handles are cached — reset() zeroes values but keeps handles,
# so these references stay valid for the life of the process
_reg = _registry()
_dispatch = {}                          # site -> Counter
_jit_hit = _reg.counter("jit_cache", result="hit")
_jit_miss = _reg.counter("jit_cache", result="miss")
_buckets_gauge = _reg.gauge("fused_bucket_sizes_bytes")


def set_config(profile_all=False, profile_symbolic=True,
               profile_imperative=True, profile_memory=True, profile_api=True,
               filename=None, **kwargs):
    """`filename` is the Chrome-trace target `dump()` writes (full path
    preserved — reference `profile.json` behavior); its directory is also
    where `jax.profiler` drops the device trace."""
    if filename:
        _state["filename"] = filename
        _state["dir"] = os.path.dirname(filename) or "."


def _start_jax_trace():
    try:
        jax.profiler.start_trace(_state["dir"])
        _state["jax_trace"] = True
        return
    except Exception:
        pass
    # start_trace raises if a session is already open (double start(), or
    # a crashed earlier capture). Close the stray session and retry once —
    # swallowing without this would leak it and silently break every
    # later capture in the process.
    try:
        jax.profiler.stop_trace()
        jax.profiler.start_trace(_state["dir"])
        _state["jax_trace"] = True
    except Exception:
        _state["jax_trace"] = False


def _stop_jax_trace():
    if not _state["jax_trace"]:
        return
    _state["jax_trace"] = False
    try:
        jax.profiler.stop_trace()
    except Exception:
        pass


def start():
    _state["running"] = True
    _state["paused"] = False
    _state["t0"] = time.time()
    _tracer.start()
    _start_jax_trace()
    # interleave: host spans also annotate the device trace while one is
    # being captured
    _tracer.set_jax_annotation(_state["jax_trace"])


def stop():
    if not _state["running"] and not _state["jax_paused"]:
        return
    # a PAUSED session must also finalize here: leaving jax_paused set
    # would let a later resume() reopen recording the caller believes
    # stopped (and leak a half-open jax trace session)
    _state["running"] = False
    _state["paused"] = False
    _tracer.set_jax_annotation(False)
    _stop_jax_trace()
    _state["jax_paused"] = False
    _tracer.stop()      # buffer is kept for dump()


def pause():
    """Suspend profiling: the host tracer stops recording (buffer kept)
    and the jax device-trace session is closed — work done while paused
    appears in NEITHER trace. resume() restarts both."""
    if not _state["running"]:
        return
    _state["running"] = False
    _state["paused"] = True
    _tracer.pause()
    if _state["jax_trace"]:
        _tracer.set_jax_annotation(False)
        _stop_jax_trace()
        _state["jax_paused"] = True


def resume():
    # only a PAUSED session resumes; after stop() this is a no-op (stop
    # finalized — reopening recording behind the caller's back would
    # leave span overhead on indefinitely)
    if not _state["paused"]:
        return
    _state["paused"] = False
    _state["running"] = True
    _tracer.resume()
    if _state["jax_paused"]:
        _state["jax_paused"] = False
        _start_jax_trace()
        _tracer.set_jax_annotation(_state["jax_trace"])


def record_op(name, seconds):
    if _state["running"]:
        entry = _state["ops"][name]
        entry[0] += 1
        entry[1] += seconds


def record_dispatch(name="dispatch", n=1):
    """Count a device dispatch issued from the imperative training hot path
    (one jitted-executable launch / collective). Always on — the fused
    Trainer path and its regression tests key off this counter."""
    c = _dispatch.get(name)
    if c is None:
        c = _dispatch[name] = _reg.counter("dispatch", site=name)
    c.inc(n)


def dispatch_count(name=None):
    """Total device dispatches recorded since the last reset, or the count
    for one named dispatch site."""
    if name is not None:
        c = _dispatch.get(name)
        return c.value if c is not None else 0
    return sum(c.value for c in _dispatch.values())


def reset_dispatches():
    """Zero the fused-path telemetry as a unit: the dispatch counters AND
    the jit-cache hit/miss tallies (a dispatch window always starts with a
    fresh compile picture; `dumps(reset=True)` calls this too)."""
    for c in _dispatch.values():
        c.reset()
    _jit_hit.reset()
    _jit_miss.reset()


def record_jit_cache(hit):
    """Tally a fused-kernel jit cache lookup (hit=True) or compile (miss)."""
    (_jit_hit if hit else _jit_miss).inc()


def jit_cache_stats():
    """(hits, misses) of the fused-update kernel cache."""
    return (_jit_hit.value, _jit_miss.value)


def record_buckets(sizes_bytes):
    """Record the byte sizes of the fused path's gradient buckets."""
    _buckets_gauge.set([int(s) for s in sizes_bytes])


def bucket_sizes():
    return list(_buckets_gauge.value or [])


def dumps(reset=False):
    lines = [f"{'op':<40}{'calls':>10}{'total_ms':>14}"]
    for name, (calls, total) in sorted(_state["ops"].items(),
                                       key=lambda kv: -kv[1][1]):
        lines.append(f"{name:<40}{calls:>10}{total * 1e3:>14.3f}")
    if dispatch_count():
        lines.append(f"[dispatch] total={dispatch_count()}")
        for name in sorted(_dispatch):
            if _dispatch[name].value:
                lines.append(f"[dispatch] {name}={_dispatch[name].value}")
    hits, misses = jit_cache_stats()
    if hits or misses:
        lines.append(f"[jit-cache] hits={hits} misses={misses}")
    if bucket_sizes():
        lines.append(f"[buckets] sizes_bytes={bucket_sizes()}")
    # compile observatory (observability/compilex.py): per-executable
    # compile counts/seconds (p95 from the histogram) + last-inspected
    # HLO fusion count, and the persistent-cache outcome totals
    fus_by_ex = {dict(g.labels).get("executable"): g.snapshot()
                 for g in _reg.series("hlo_fusions")}
    for h in _reg.series("compile_seconds"):
        snap = h.snapshot()
        if not snap["count"]:
            continue
        ex = dict(h.labels).get("executable", "?")
        line = (f"[compile] {ex}: n={snap['count']} "
                f"total={snap['sum']:.3f}s p95={snap['p95']:.3f}s")
        if fus_by_ex.get(ex) is not None:
            line += f" hlo_fusions={fus_by_ex[ex]}"
        lines.append(line)
    from .observability import compilex as _compilex
    c_hits, c_misses = _compilex.compile_cache_stats()
    if c_hits or c_misses:
        lines.append(f"[compile-cache] hits={c_hits} misses={c_misses} "
                     f"dir={_compilex.compilation_cache_dir()}")
    # compile-space autotuner (ISSUE 20): winner applications, stale
    # rejections by reason, store corruption — the apply-side health of
    # the measure->decide->apply loop (docs/PERFORMANCE.md "Autotuning")
    from . import tune as _tune
    t_applied = _tune.applied_count()
    t_stale = {dict(c.labels).get("reason"): int(c.value)
               for c in _reg.series("tune_stale") if c.value}
    t_corrupt = next((int(c.value) for c in
                      _reg.series("tune_store_corrupt")), 0)
    if t_applied or t_stale or t_corrupt or _tune.autotune_dir():
        line = f"[autotune] applied={t_applied}"
        if t_stale:
            line += " stale=" + ",".join(
                f"{k}:{v}" for k, v in sorted(t_stale.items()))
        if t_corrupt:
            line += f" corrupt={t_corrupt}"
        line += f" dir={_tune.autotune_dir()}"
        lines.append(line)
    # serving fast path (ISSUE 12): the speculative acceptance
    # distribution — the regression signal for the draft proposer (a
    # falling mean/p95 means more turns per committed token)
    for h in _reg.series("serve_spec_accepted_tokens"):
        snap = h.snapshot()
        if snap["count"]:
            lines.append(
                f"[serve-spec] accepted/turn: n={snap['count']} "
                f"mean={snap['mean']:.3f} p95={snap['p95']:.3g} "
                f"max={snap['max']:.3g}")
    # graft-lint gate (ISSUE 13): the last check_static run in this
    # process — rules run, finding counts, baseline size; a growing
    # baseline or a nonzero "new" count is drift the supervisor
    # contract should surface
    rules_run = next((g.value for g in _reg.series("static_rules_run")),
                     0)
    if rules_run:
        by_kind = {dict(g.labels).get("kind"): int(g.value)
                   for g in _reg.series("static_findings")}
        bl = next((int(g.value) for g in
                   _reg.series("static_baseline_size")), 0)
        lines.append(
            f"[static] rules={int(rules_run)} "
            f"findings={by_kind.get('total', 0)} "
            f"new={by_kind.get('new', 0)} "
            f"suppressed={by_kind.get('suppressed', 0)} baseline={bl}")
    if reset:
        _state["ops"].clear()
        reset_dispatches()
        _buckets_gauge.reset()
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Reference profiler.dump: write the Chrome-trace `profile.json`
    (host spans — engine tasks, collectives, Trainer steps, sampled ops;
    the jax device trace lives beside it in the same directory) and echo
    the host op table to stderr. Returns the trace path."""
    import sys
    path = _state["filename"] or os.path.join(_state["dir"], "profile.json")
    _tracer.dump(path)
    print(dumps(), file=sys.stderr)
    return path


@contextlib.contextmanager
def Scope(name="profile"):
    """Annotate a region in the device trace AND account it in the host
    op tally (so `dumps()` shows scoped regions) and the host tracer."""
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        with _tracer.span(name, cat="scope"):
            yield
    record_op(name, time.perf_counter() - t0)
