"""Feasibility probe for Pallas conv(1x1)+BN-stats epilogue fusion.

The round-4 profile says the ResNet-50 step is bandwidth-bound on
BN-stat reduce fusions (the fwd stats pass re-reads every conv output).
A 1x1 NHWC conv is a (B*H*W, Cin) @ (Cin, Cout) matmul, and Pallas can
compute the per-channel fp32 sum/sumsq WHILE the output tile is still
in VMEM — deleting one full HBM read of the activation per fused layer.

This probe times, for the three bottleneck 1x1 shapes of ResNet-50 at
batch 128: (a) XLA conv + separate fused stats reduce (today's path)
vs (b) the Pallas fused kernel. Keep-or-reject evidence for wiring it
into the model (docs/PERF.md discipline).

Usage: python tools/probe_fused_convbn.py [--steps N]
"""
from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, w_ref, y_ref, s_ref, q_ref):
    i = pl.program_id(0)
    x = x_ref[...]                                     # (bm, K) bf16
    w = w_ref[...]                                     # (K, N) bf16
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)   # (bm, N) f32
    y_ref[...] = y.astype(y_ref.dtype)
    s = jnp.sum(y, axis=0)                             # (N,) f32
    q = jnp.sum(y * y, axis=0)

    @pl.when(i == 0)
    def _init():
        s_ref[...] = jnp.zeros_like(s_ref)
        q_ref[...] = jnp.zeros_like(q_ref)

    # every row of the (8, N) accumulator gets the same partial: row 0
    # holds the true total at the end (lane-aligned stats block — a
    # (1, N) block would violate Mosaic's (8, 128) min tile)
    s_ref[...] += jnp.broadcast_to(s[None, :], s_ref.shape)
    q_ref[...] += jnp.broadcast_to(q[None, :], q_ref.shape)


@functools.partial(jax.jit, static_argnames=("bm",))
def fused_conv1x1_stats(x2d, w, bm=1024):
    m, k = x2d.shape
    n = w.shape[1]
    pad = (-m) % bm
    xp = jnp.pad(x2d, ((0, pad), (0, 0))) if pad else x2d
    grid = (xp.shape[0] // bm,)
    y, s, q = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0)),
                  pl.BlockSpec((k, n), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((bm, n), lambda i: (i, 0)),
                   pl.BlockSpec((8, n), lambda i: (0, 0)),
                   pl.BlockSpec((8, n), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((xp.shape[0], n), x2d.dtype),
                   jax.ShapeDtypeStruct((8, n), jnp.float32),
                   jax.ShapeDtypeStruct((8, n), jnp.float32)],
    )(xp, w)
    inv = 1.0 / m
    return y[:m], s[0] * inv, q[0] * inv   # mean, E[y^2]


@jax.jit
def xla_conv_stats(x2d, w):
    y = jnp.dot(x2d, w, preferred_element_type=jnp.bfloat16)
    yf = y.astype(jnp.float32)
    return y, jnp.mean(yf, 0), jnp.mean(yf * yf, 0)


def bench_one(m, k, n, steps):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (m, k), jnp.bfloat16)
    w = jax.random.normal(key, (k, n), jnp.bfloat16) * 0.05

    def time_fn(fn):
        outs = fn(x, w)
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), outs)
        float(outs[1][0])  # host-fetch sync (axon tunnel)
        t0 = time.monotonic()
        for _ in range(steps):
            outs = fn(x, w)
        float(outs[1][0])
        return (time.monotonic() - t0) / steps * 1e3

    t_xla = time_fn(xla_conv_stats)
    t_pal, best_bm = None, None
    for bm in (256, 512, 1024):
        if m < bm:
            continue
        t = time_fn(functools.partial(fused_conv1x1_stats, bm=bm))
        if t_pal is None or t < t_pal:
            t_pal, best_bm = t, bm
    print(f"  best bm={best_bm}", flush=True)
    # numerics check while we're here
    y0, m0, q0 = xla_conv_stats(x, w)
    y1, m1, q1 = fused_conv1x1_stats(x, w)
    err = float(jnp.abs(m0 - m1).max())
    print(f"M={m} K={k} N={n}: xla {t_xla:.3f} ms  pallas {t_pal:.3f} ms "
          f"({t_xla / t_pal:.2f}x)  mean-err {err:.2e}", flush=True)
    return t_xla, t_pal


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()
    print(f"backend={jax.default_backend()}", flush=True)
    B = 128
    shapes = [
        (B * 56 * 56, 64, 256),     # stage1 bottleneck expand
        (B * 28 * 28, 512, 128),    # stage2 reduce
        (B * 14 * 14, 1024, 256),   # stage3 reduce
        (B * 7 * 7, 512, 2048),     # stage4 expand
    ]
    tot_x = tot_p = 0.0
    for m, k, n in shapes:
        tx, tp = bench_one(m, k, n, args.steps)
        tot_x += tx
        tot_p += tp
    print(f"TOTAL: xla {tot_x:.3f} ms  pallas {tot_p:.3f} ms "
          f"({tot_x / tot_p:.2f}x)", flush=True)


if __name__ == "__main__":
    main()
