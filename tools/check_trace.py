#!/usr/bin/env python
"""Standalone Chrome-trace JSON validator.

Loads a trace (either `{"traceEvents": [...]}` object form or a bare
event array) and fails on malformed or unbalanced events, so trace-format
regressions in `mx.profiler.dump()` / `mx.observability.tracer` fail
tier-1 (tests/test_observability.py invokes this; it also runs standalone:

    python tools/check_trace.py profile.json

exit 0 = valid, 1 = invalid (errors on stderr), 2 = unreadable input).

Checks
  * top-level shape: object with a `traceEvents` list, or a list.
  * every event is an object with a one-char `ph`.
  * duration/instant/counter events (`B`/`E`/`X`/`i`/`C`) carry the
    required keys: numeric non-negative `ts`, `pid`, `tid`; `name` for
    everything except `E` (Chrome emits nameless `E`s).
  * `X` events carry a non-negative numeric `dur`.
  * `ts` is monotonically non-decreasing in file order (the exporters
    here sort; an unsorted trace loads wrong in some viewers).
  * `B`/`E` balance per (pid, tid): every `E` pops a matching `B`
    (name-checked when the `E` is named), nothing left open at EOF.

No framework imports — usable on traces from any writer.
"""
from __future__ import annotations

import json
import numbers
import sys

_PHASES_NEEDING_TS = ("B", "E", "X", "i", "I", "C")


def _is_num(v):
    return isinstance(v, numbers.Real) and not isinstance(v, bool)


def validate_events(events):
    """Validate a traceEvents list; returns a list of error strings
    (empty = valid)."""
    errors = []
    last_ts = None
    stacks = {}            # (pid, tid) -> [name, ...] of open B spans
    for i, ev in enumerate(events):
        where = f"event {i}"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object: {ev!r}")
            continue
        ph = ev.get("ph")
        if not isinstance(ph, str) or len(ph) != 1:
            errors.append(f"{where}: missing/malformed 'ph': {ph!r}")
            continue
        if ph == "M":
            if "name" not in ev:
                errors.append(f"{where}: metadata event without 'name'")
            continue
        if ph not in _PHASES_NEEDING_TS:
            continue            # other phases (async, flow, ...) pass
        for key in ("ts", "pid", "tid"):
            if key not in ev:
                errors.append(f"{where} (ph={ph}): missing '{key}'")
        if ph != "E" and not isinstance(ev.get("name"), str):
            errors.append(f"{where} (ph={ph}): missing/malformed 'name'")
        ts = ev.get("ts")
        if ts is not None:
            if not _is_num(ts) or ts < 0:
                errors.append(f"{where}: 'ts' not a non-negative number: "
                              f"{ts!r}")
            else:
                if last_ts is not None and ts < last_ts:
                    errors.append(f"{where}: 'ts' went backwards "
                                  f"({ts} < {last_ts})")
                last_ts = ts
        if ph == "X":
            dur = ev.get("dur")
            if not _is_num(dur) or dur < 0:
                errors.append(f"{where}: X event needs non-negative "
                              f"'dur', got {dur!r}")
        track = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks.setdefault(track, []).append(ev.get("name"))
        elif ph == "E":
            stack = stacks.get(track)
            if not stack:
                errors.append(f"{where}: 'E' with no open 'B' on "
                              f"pid/tid {track}")
                continue
            opened = stack.pop()
            name = ev.get("name")
            if name and name != opened:
                errors.append(f"{where}: 'E' name {name!r} does not close "
                              f"open span {opened!r} on pid/tid {track}")
    for track, stack in stacks.items():
        if stack:
            errors.append(f"EOF: {len(stack)} unclosed 'B' span(s) on "
                          f"pid/tid {track}: {stack[-3:]!r}")
    return errors


def validate(trace):
    """Validate a loaded trace (dict or list form); returns error list."""
    if isinstance(trace, dict):
        events = trace.get("traceEvents")
        if not isinstance(events, list):
            return ["top-level object has no 'traceEvents' list"]
    elif isinstance(trace, list):
        events = trace
    else:
        return [f"top level must be an object or array, got "
                f"{type(trace).__name__}"]
    return validate_events(events)


def validate_file(path):
    with open(path) as f:
        return validate(json.load(f))


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: check_trace.py <trace.json>", file=sys.stderr)
        return 2
    try:
        errors = validate_file(argv[0])
    except (OSError, json.JSONDecodeError) as e:
        print(f"check_trace: cannot read {argv[0]}: {e}", file=sys.stderr)
        return 2
    for err in errors:
        print(f"check_trace: {err}", file=sys.stderr)
    if errors:
        print(f"check_trace: INVALID ({len(errors)} error(s))",
              file=sys.stderr)
        return 1
    print(f"check_trace: OK ({argv[0]})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
