"""Checkpoint/resume tests (SURVEY.md §2 #36, §5)."""
import os
import tempfile

import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, checkpoint, gluon
from mxnet_tpu.gluon import nn


def test_save_load_checkpoint_roundtrip():
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "model")
        arg = {"w": nd.array([1.0, 2.0]), "b": nd.array([0.5])}
        aux = {"mean": nd.array([0.1])}
        checkpoint.save_checkpoint(prefix, 3, None, arg, aux)
        sym, arg2, aux2 = checkpoint.load_checkpoint(prefix, 3)
        np.testing.assert_allclose(arg2["w"].asnumpy(), [1.0, 2.0])
        np.testing.assert_allclose(aux2["mean"].asnumpy(), [0.1])


def test_gluon_save_load_parameters():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "net.params.npz")
        net = nn.Dense(3, in_units=2)
        net.initialize(mx.init.Normal(1.0))
        net.save_parameters(path)
        net2 = nn.Dense(3, in_units=2)
        net2.load_parameters(path)
        np.testing.assert_allclose(net.weight.data().asnumpy(),
                                   net2.weight.data().asnumpy())


def test_sharded_roundtrip():
    with tempfile.TemporaryDirectory() as d:
        params = {"layer": {"w": jnp.arange(6.0).reshape(2, 3),
                            "b": jnp.zeros(3)}}
        checkpoint.save_sharded(d, 100, params)
        template = {"layer": {"w": jnp.zeros((2, 3)), "b": jnp.ones(3)}}
        restored = checkpoint.load_sharded(d, 100, template)
        np.testing.assert_allclose(np.asarray(restored["layer"]["w"]),
                                   np.arange(6.0).reshape(2, 3))


def test_checkpoint_manager_rolls():
    with tempfile.TemporaryDirectory() as d:
        mgr = checkpoint.CheckpointManager(d, max_to_keep=2)
        for step in (1, 2, 3):
            mgr.save(step, {"w": jnp.full((2,), float(step))})
        assert mgr.steps() == [2, 3]
        step, restored = mgr.restore_latest({"w": jnp.zeros(2)})
        assert step == 3
        np.testing.assert_allclose(np.asarray(restored["w"]), [3.0, 3.0])


def test_sharded_checkpoint_of_sharded_params():
    """Save params laid out on an 8-device mesh; restore matches."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"dp": 8})
    w = jnp.arange(32.0).reshape(8, 4)
    sharded = jax.device_put(w, NamedSharding(mesh, P("dp", None)))
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save_sharded(d, 0, {"w": sharded})
        restored = checkpoint.load_sharded(d, 0, {"w": jnp.zeros((8, 4))})
        np.testing.assert_allclose(np.asarray(restored["w"]), np.asarray(w))
