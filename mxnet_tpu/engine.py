"""Execution engine facade (reference: src/engine/threaded_engine.cc).

Two layers:
  * Device-side op scheduling is owned by XLA/PJRT — JAX dispatch is already
    asynchronous (ops enqueue on the device stream and Python returns
    immediately), which is exactly the role MXNet's ThreadedEngine plays for
    kernels. `wait_to_read`/`waitall` map onto PJRT readiness.
  * Host-side async work (data pipeline, IO, parameter serialisation, the
    serving decode loop) runs on the native C++ dependency engine in
    cpp/engine.cc when built (see mxnet_tpu/_native.py), with a pure-Python
    fallback providing identical semantics: push(fn, read_vars, write_vars)
    with read/write dependency ordering per variable, wait_for_var,
    wait_for_all.

QoS (ISSUE 7) — the engine is a multi-tenant scheduler, not a FIFO:

  * **Priority classes** — `push(..., priority=PRIORITY_HIGH | NORMAL |
    BACKGROUND)`. Ready tasks dispatch best-class-first, so a serve decode
    turn (high) preempts QUEUED prefetch/checkpoint work (background) at
    dispatch time; running tasks are never interrupted. **Aging** bounds
    starvation: a ready task's effective class drops by one per
    `set_aging_ms` interval waited, FLOORED at the high class — promoted
    background work beats fresh normal work and ties among promoted
    classes go to the longest waiter, but the native high class wins its
    ties, so a decode turn's dispatch wait stays bounded by one running
    task no matter how stale the backlog (the high class is sparse by
    construction: one serve loop task at a time).
  * **Task groups** — `TaskGroup` is the first-class cancellation handle
    (generalising PR 5's prefetch cancellation and PR 6's scheduler
    shutdown): `cancel()` atomically skips every member task that has not
    started (futures resolve to `engine.CANCELLED` in dependency order —
    nothing is poisoned, no failure is recorded, the race detector stays
    quiet), `drain()` waits for in-flight members to settle.
  * **Bounded queues** — `set_queue_limit(class, limit, policy)` bounds
    queued-not-started tasks per class with a backpressure policy: `reject`
    (push raises `EngineQueueFull`), `block` (push waits for room), or
    `shed_oldest` (the class's oldest queued task is cancelled to make
    room). Surfaced via `engine_queue_rejections{class}` and the
    `engine_queue_high_water{class}` gauge.
  * **Deadlines** — `push(..., deadline_ms=)` bounds a task's QUEUED
    lifetime: not started in time -> skipped (future resolves to
    `engine.EXPIRED`, `engine_deadline_expired` counts it). Tasks running
    past their deadline show as `overdue` in `pending_report()`, which the
    step watchdog (fault/watchdog.py) embeds in its stall post-mortem.

Engine-var users today: data prefetch (io.py / gluon DataLoader /
prefetch.DevicePrefetcher — background class), NDArray save/load
(ndarray/utils.py), async checkpoint saves (checkpoint.py — background
class), recordio writes (recordio.py), and the serving decode loop
(serve/engine_bridge.py — high class).

Debug mode (MXTPU_ENGINE_DEBUG=1 or `set_debug(True)`) turns on the race /
deadlock detector: write-write and read-write hazard checks on every
release, self-dependency (deadlock-cycle) detection at push, and a bounded
`wait_for_all_timeout` for stall watchdogs. Errors are reported via
`last_error()` / raised by `debug_check_raise()`.
"""
from __future__ import annotations

import atexit as _atexit
import collections as _collections
import os as _os
import threading
import time as _time
from concurrent.futures import Future, InvalidStateError

from ._env import env_int as _env_int
from ._engine_common import FailureLog as _FailureLog
from ._engine_common import failure_site as _failure_site
from ._engine_common import reraise_unless_cancelled as _reraise_unless_cancelled
from ._engine_common import set_exc as _set_exc
from .base import MXNetError
from .observability import tracer as _tracer
from .observability import registry as _obs_registry
from .fault import injection as _finj

__all__ = ["Var", "push", "wait_for_var", "wait_for_all", "set_bulk_size",
           "get_bulk_size", "num_workers", "native_engine_loaded", "file_var",
           "set_debug", "debug_enabled", "debug_check", "debug_check_raise",
           "last_error", "clear_error", "wait_for_all_timeout",
           "failures", "clear_failures", "pending_tasks", "tasks_completed",
           # QoS (ISSUE 7)
           "PRIORITY_HIGH", "PRIORITY_NORMAL", "PRIORITY_BACKGROUND",
           "PRIORITY_NAMES", "NUM_PRIORITIES", "TaskGroup", "EngineQueueFull",
           "CANCELLED", "EXPIRED", "skipped", "skipped_future",
           "inline_future", "failed_future", "set_queue_limit",
           "get_queue_limit", "set_aging_ms", "get_aging_ms", "set_qos",
           "qos_enabled", "active_groups", "pending_report"]

# ------------------------------------------------------ priority classes
NUM_PRIORITIES = 3
PRIORITY_HIGH = 0         # serve decode turns — latency-critical
PRIORITY_NORMAL = 1       # default: save/load, recordio, user pushes
PRIORITY_BACKGROUND = 2   # prefetch staging, async checkpoint saves
PRIORITY_NAMES = ("high", "normal", "background")

_DEFAULT_AGING_MS = 100


def _clamp_pri(priority):
    return min(max(int(priority), 0), NUM_PRIORITIES - 1)


class EngineQueueFull(MXNetError):
    """Bounded-queue backpressure: the priority class's queue is at its
    limit and the policy is `reject` — retry later or shed load."""


class _SkipResult:
    """Result sentinel of a task whose fn was skipped (cancelled task
    group, shed-oldest victim, or expired deadline). Falsy, identity-
    compared; dependents see a CLEAN completion — nothing is poisoned."""
    __slots__ = ("reason",)

    def __init__(self, reason):
        self.reason = reason

    def __repr__(self):
        return f"<engine.{self.reason.upper()}>"

    def __bool__(self):
        return False


CANCELLED = _SkipResult("cancelled")
EXPIRED = _SkipResult("expired")


def skipped(result):
    """True when an engine-task result is a skip sentinel (the task's fn
    never ran: cancelled group / shed / expired deadline)."""
    return isinstance(result, _SkipResult)


def skipped_future():
    """An already-done future resolved to `engine.CANCELLED`. Framework
    push sites (DataLoader batchify, PrefetchingIter fetch) substitute
    it when a bounded class under the `reject` policy raises
    EngineQueueFull: the consumer's existing shed fallback (inline
    recompute) then absorbs the rejection instead of the exception
    crashing the caller's loop mid-epoch."""
    f = Future()
    f.set_result(CANCELLED)
    return f


def inline_future(fn, site=None, write_vars=()):
    """Run fn synchronously NOW and return an already-done future holding
    its result (or exception). The other half of the reject-policy
    degradation story: framework push sites whose work cannot simply be
    skipped (DevicePrefetcher staging, async checkpoint saves) substitute
    this for `push` when a bounded class raises EngineQueueFull —
    backpressure slows the caller by one task instead of dropping work,
    and errors keep riding the future's `result()` contract. A failure
    is recorded into `failures()` / `engine_task_failures` exactly like
    an engine-task failure, so fire-and-forget callers (an async save
    whose future nobody waits on) don't lose the report to the
    degradation path.

    With `write_vars` (AT MOST ONE var), the inline task takes the var's
    write slot ATOMICALLY (under the var lock) before waiting on the
    displaced writer/readers, so two degraded pushers of the same var
    serialize instead of both passing a wait-then-run window and
    interleaving. Single-var only: per-var slot-taking across several
    vars could interleave with a concurrent push of the same vars and
    form a dependency cycle (inline waits on the pushed task, whose dep
    is the inline future) — a permanent hang, so multi-var is rejected
    outright. A poisoned predecessor rides the returned future as a
    dependency re-raise (fn never runs, not recorded as a root cause) —
    parity with a queued dependent. Residual window (documented): the
    native engine's dependency tracking cannot see an inline writer, so
    a task PUSHED while the inline fn runs orders after it only on
    _PyEngine."""
    if len(write_vars) > 1:
        raise MXNetError("inline_future supports at most one write var "
                         "(multi-var slot-taking can deadlock against a "
                         "concurrent push of the same vars)")
    f = Future()
    deps = []
    for v in write_vars:
        with v._lock:
            if v._last_write is not None:
                deps.append(v._last_write)
            deps.extend(v._reads)
            v._last_write = f
            v._reads = []
    for d in deps:
        try:
            _reraise_unless_cancelled(d)   # blocks behind in-flight writers
        except BaseException as exc:
            f.set_exception(exc)
            return f
    try:
        f.set_result(fn())
    except BaseException as exc:
        _record_failure(site or _dispatch_site(fn), exc)
        f.set_exception(exc)
    return f


def failed_future(exc):
    """An already-done future carrying `exc`. Degraded push sites that
    find their ordering var POISONED substitute this for running the
    work inline: the error rides the future exactly as a queued
    dependent's re-raise would, and the work (which would be discarded
    by the caller's failure recovery anyway) never runs."""
    f = Future()
    f.set_exception(exc)
    return f


class Var:
    """A dependency variable (reference: engine::Var). Ops that write a var
    are serialised; readers wait for the last writer."""
    __slots__ = ("_lock", "_last_write", "_reads", "_native_id")

    def __init__(self):
        self._lock = threading.Lock()
        self._last_write = None       # Future of last writer
        self._reads = []              # Futures of readers since last write


class _PyTask:
    __slots__ = ("fn", "fut", "deps", "pri", "_nwait", "_nlock", "_t_ready")

    def __init__(self, fn, fut, deps, pri):
        self.fn = fn
        self.fut = fut
        self.deps = deps
        self.pri = pri
        self._nwait = len(deps) + 1    # +1 guard dropped by push()
        self._nlock = threading.Lock()
        self._t_ready = 0.0


class _PyEngine:
    """Pure-Python fallback engine, rebuilt (ISSUE 7) from a dep-blocking
    threadpool into the same ready-queue design as cpp/engine.cc: a task
    enters a per-priority-class READY queue only once every dependency
    future has settled (dep waits no longer park workers), and workers
    drain the queues best-effective-class-first with aging — identical
    dispatch semantics to the native engine."""

    NUM_CLASSES = NUM_PRIORITIES

    def __init__(self, workers=4, aging_ms=None):
        if aging_ms is None:
            # Mirror the C++ engine's strtol+endptr parse exactly
            # (engine.cc: ms >= 0 and <= INT32_MAX, else default) — the
            # shared `_env` parser IS that discipline, so the parity
            # pair cannot run with different starvation bounds.
            aging_ms = _env_int("MXTPU_ENGINE_AGING_MS",
                                _DEFAULT_AGING_MS, minimum=0,
                                maximum=2**31 - 1)
        self._aging_ms = max(0, int(aging_ms))
        self._aging_s = self._aging_ms / 1000.0
        self.workers = workers
        self._ready = [_collections.deque() for _ in range(self.NUM_CLASSES)]
        self._rcv = threading.Condition(threading.Lock())
        self._pending = set()
        self._plock = threading.Lock()
        self._debug = bool(_os.environ.get("MXTPU_ENGINE_DEBUG"))
        self._last_error = ""
        self._hazard = False
        self._failures = _FailureLog()
        self._admit_lock = threading.Lock()
        self._stopped = False
        for i in range(workers):
            threading.Thread(target=self._worker, daemon=True,
                             name=f"mxtpu-engine-{i}").start()

    def close(self):
        """Stop the worker threads once the ready queues drain (call
        after `wait_for_all`; push nothing afterwards). The workers hold
        a strong ref to the engine, so a discarded instance that is
        never closed leaks its threads for the process lifetime — the
        global facade engine deliberately never closes, but transient
        instances (tools, tests, benches) must."""
        with self._rcv:
            self._stopped = True
            self._rcv.notify_all()

    # debug surface mirroring NativeEngine (the Python engine admits in
    # program order under per-var locks so bypass-injection does not
    # apply; self-dep and stall detection are the meaningful checks here)
    def set_debug(self, on):
        self._debug = bool(on)

    def debug_enabled(self):
        return self._debug

    def debug_check(self):
        # invariant violations only — a recorded stall is informational,
        # matching the native engine's per-var invariant scan
        return 1 if self._hazard else 0

    def last_error(self):
        return self._last_error

    def clear_error(self):
        self._last_error = ""
        self._hazard = False

    def _record(self, msg, hazard=False):
        if hazard:
            self._hazard = True
        if len(self._last_error) > 4096:
            return  # bounded: keep the earliest messages
        self._last_error = (self._last_error + "; " if self._last_error
                            else "") + msg

    def set_aging_ms(self, ms):
        """Starvation-aging interval: a READY task's effective priority
        class drops by one per `ms` waited (0 disables aging; negative
        values are IGNORED, matching the native SetAgingMs — disabling
        the starvation bound must be an explicit 0)."""
        ms = int(ms)
        if ms >= 0:
            self._aging_ms = ms
            self._aging_s = ms / 1000.0

    def get_aging_ms(self):
        # the stored int, NOT int(_aging_s * 1000): float truncation would
        # return ms-1 for values like 1001 while the native engine returns
        # the exact int — a save/restore round-trip must not decay
        return self._aging_ms

    def wait_for_all_timeout(self, timeout_ms):
        import time
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._plock:
            futs = list(self._pending)
        for f in futs:
            rem = deadline - time.monotonic()
            if rem <= 0 or not _done_within(f, rem):
                self._record(f"stall: engine did not drain within "
                             f"{timeout_ms}ms")
                return 1
        return 0

    def push(self, fn, read_vars=(), write_vars=(), priority=PRIORITY_NORMAL):
        if self._stopped:
            # parity with NativeEngine's _live() guard: a push onto a
            # closed engine must RAISE, not enqueue onto worker-less
            # ready queues where the future silently never settles
            raise MXNetError("engine is closed")
        if self._debug:
            overlap = [v for v in read_vars if v in write_vars]
            for _v in overlap:
                self._record("deadlock: op reads AND writes the same var "
                             "(self-dependency cycle; read dep dropped)",
                             hazard=True)
            if overlap:
                read_vars = [v for v in read_vars if v not in write_vars]
        # dedup (identity): a var repeated in a list, or present in both
        # lists, must not make the task depend on its OWN future now that
        # collection and registration share one lock hold below
        read_vars = list(dict.fromkeys(read_vars))
        write_vars = list(dict.fromkeys(write_vars))
        read_vars = [v for v in read_vars if v not in write_vars]
        deps = []
        fut = Future()
        # dep COLLECTION and var REGISTRATION must be one atomic admission
        # (program order, like engine.cc's Push under its global mutex):
        # two threads pushing writes on the same var could otherwise both
        # snapshot the old last_write — neither depends on the other and
        # the writes run concurrently on two workers. Per var, collect
        # and register under ONE v._lock hold: inline_future takes only
        # the var lock (not _admit_lock), so a gap between the two would
        # let an inline writer swap its slot in unseen — the queued and
        # inline writer would then run concurrently
        with self._admit_lock:
            for v in read_vars:
                with v._lock:
                    if v._last_write is not None:
                        deps.append(v._last_write)
                    v._reads.append(fut)
            for v in write_vars:
                with v._lock:
                    if v._last_write is not None:
                        deps.append(v._last_write)
                    deps.extend(v._reads)
                    v._last_write = fut
                    v._reads = []
            task = _PyTask(fn, fut, deps, _clamp_pri(priority))
            with self._plock:
                self._pending.add(fut)
        fut.add_done_callback(self._discard)
        for d in deps:
            d.add_done_callback(lambda _f, t=task: self._dep_done(t))
        self._dep_done(task)          # drop the +1 guard
        return fut

    def _discard(self, fut):
        with self._plock:
            self._pending.discard(fut)

    def _dep_done(self, task):
        with task._nlock:
            task._nwait -= 1
            if task._nwait:
                return
        task._t_ready = _time.monotonic()
        with self._rcv:
            self._ready[task.pri].append(task)
            self._rcv.notify()

    # _rcv must be held. Effective class of a queue head = its class minus
    # one per aging interval waited, FLOORED at class 0: promoted work can
    # tie the high class but never outrank it — a decode turn's dispatch
    # wait stays bounded by one running task no matter how stale the
    # backlog, while promoted background beats fresh normal work. Ties go
    # to the NATIVE high class first, then to the longest-waiting head
    # (fairness among promoted classes). Per-class queues are FIFO, so
    # each head is its class's oldest — the candidate aging promoted
    # furthest. Mirrors cpp/engine.cc PopBestLocked exactly.
    def _pop_best_locked(self):
        now = _time.monotonic()
        best = None
        best_key = None
        for c, q in enumerate(self._ready):
            if not q:
                continue
            eff = c
            if self._aging_s > 0:
                eff = max(0, eff - int((now - q[0]._t_ready)
                                       / self._aging_s))
            key = (eff, c != 0, q[0]._t_ready)
            if best is None or key < best_key:
                best, best_key = c, key
        return self._ready[best].popleft() if best is not None else None

    def _worker(self):
        while True:
            with self._rcv:
                task = self._pop_best_locked()
                while task is None:
                    if self._stopped:
                        return         # close(): drained, nothing to do
                    self._rcv.wait()
                    task = self._pop_best_locked()
            self._run_task(task)

    def _run_task(self, task):
        fut = task.fut
        if fut.cancelled():
            return                     # externally cancelled: skip cleanly
        dep_exc = None
        for d in task.deps:            # all settled once the task is ready
            if d.cancelled():
                continue               # a cancelled dep poisons nothing
            e = d.exception()
            if e is not None:
                dep_exc = e
                break
        if dep_exc is not None:        # dependency re-raise: NOT a root cause
            _set_exc(fut, dep_exc)
            return
        try:
            res = task.fn()
        except BaseException as exc:   # noqa: BLE001 — stored, not swallowed
            self._record_task_failure(task.fn, exc)
            _set_exc(fut, exc)
        else:
            try:
                fut.set_result(res)
            except InvalidStateError:
                pass

    # sticky per-instance failure report: ROOT-CAUSE task errors only
    # (dependency re-raises excluded by construction above; cancelled /
    # skipped tasks never run fn so they cannot appear) — parity with
    # NativeEngine.failures()
    def _record_task_failure(self, fn, exc):
        self._failures.record(_failure_site(fn, _dispatch_site), exc)

    def failures(self):
        return self._failures.list()

    def clear_failures(self):
        return self._failures.clear()

    def wait_for_var(self, var):
        with var._lock:
            futs = list(var._reads)
            if var._last_write is not None:
                futs.append(var._last_write)
        for f in futs:
            _reraise_unless_cancelled(f)

    def wait_for_all(self):
        with self._plock:
            futs = list(self._pending)
        for f in futs:
            _reraise_unless_cancelled(f)


def _done_within(fut, seconds):
    from concurrent.futures import TimeoutError as _FTimeout
    try:
        fut.exception(timeout=seconds)
        return True
    except _FTimeout:
        return False
    except Exception:
        return True  # completed (with error) counts as done


_engine = None
_native = None


def _get():
    global _engine, _native
    if _engine is None:
        try:
            from ._native import NativeEngine
            _engine = NativeEngine()
            _native = True
        except Exception:
            _engine = _PyEngine()
            _native = False
            # the executor-era Python engine drained at interpreter exit
            # via non-daemon pool threads; the rebuilt worker threads are
            # daemonic, so drain explicitly at exit — UNBOUNDED, matching
            # both the old executor and NativeEngine._shutdown's WaitAll
            # (a >2s in-flight async checkpoint save must not be killed
            # mid-write by a short exit window); task errors were already
            # surfaced through failures(), don't re-raise them at exit

            def _drain_at_exit():
                try:
                    _engine.wait_for_all()
                # mxtpu: disable=E04 interpreter exit: errors already in failures(), nothing to cancel
                except BaseException:
                    pass

            _atexit.register(_drain_at_exit)
        # idle time is derivable: elapsed * workers - engine_busy_seconds
        _reg.gauge("engine_workers").set(getattr(_engine, "workers", 1))
    return _engine


def native_engine_loaded():
    _get()
    return bool(_native)


# ------------------------------------------------- observability hooks
# Always-on metrics (queue depth, worker busy time, task/var-wait latency)
# plus per-task tracer spans named by dispatch site when a trace is being
# captured. Instrumentation lives in the module facade so the native C++
# engine and the Python fallback are measured identically. Engine pushes
# are IO-scale (prefetch batches, checkpoint writes), so one clock pair +
# a gauge store per task is noise; op-scale dispatch goes through XLA, not
# here.
_queue_depth = 0
_qlock = threading.Lock()
_reg = _obs_registry()
_q_gauge = _reg.gauge("engine_queue_depth")
_q_gauge.set(0)
_busy_counter = _reg.counter("engine_busy_seconds")
_task_hist = _reg.histogram("engine_task_seconds")
_wait_hist = _reg.histogram("engine_var_wait_seconds")

# ------------------------------------------------ sticky failure report
# A task that raises poisons its vars (dependents re-raise), but the only
# carrier used to be the Future — callers that never call .result() (fire
# and forget pushes: prefetch, async checkpoint saves) would lose the
# error entirely. Every ROOT-CAUSE task failure (fn itself raised, not a
# dependency re-raise) is recorded here and counted, so supervisors can
# poll `failures()` / the `engine_task_failures` counter. The engine
# INSTANCES additionally keep their own bounded failure deques
# (`_PyEngine.failures()` / `NativeEngine.failures()` — parity pair) so
# direct-engine users get the same report. Cancelled / shed / expired
# tasks never run fn and are recorded NOWHERE as failures.
_failures = _FailureLog()
_fail_counter = _reg.counter("engine_task_failures")


def _record_failure(site, exc):
    _fail_counter.inc()
    _failures.record(site, exc)


def failures():
    """Sticky engine-task failure report: the most recent root-cause task
    errors (site + repr, newest last; bounded). Dependency re-raises are
    not double-counted; cancelled tasks never appear."""
    return _failures.list()


def clear_failures():
    return _failures.clear()


def _dispatch_site(fn):
    """Span name for an engine task: module.qualname of the pushed fn —
    e.g. `io.task`, `utils.do_save` — the dispatch site, not the worker."""
    qn = getattr(fn, "__qualname__", None) or \
        getattr(fn, "__name__", None) or type(fn).__name__
    mod = getattr(fn, "__module__", None) or ""
    return f"{mod.rsplit('.', 1)[-1]}.{qn}" if mod else qn


def _queue_delta(d):
    global _queue_depth
    with _qlock:
        _queue_depth += d
        depth = _queue_depth
    _q_gauge.set(depth)
    if _tracer.ACTIVE:
        _tracer.counter("engine_queue_depth", depth)
    return depth


# ------------------------------------------------------ QoS bookkeeping
# Admission control (bounded per-class queues), task-group membership,
# deadlines and cancellation all live HERE in the facade so the native
# and Python engines share one policy; the inner engines only order the
# ready queue by priority class.
_qos_lock = threading.Lock()
_admission_cv = threading.Condition(_qos_lock)
_queued_count = [0] * NUM_PRIORITIES
_deadline_queued = [0] * NUM_PRIORITIES   # queued recs carrying a deadline
_queued_records = [_collections.deque() for _ in range(NUM_PRIORITIES)]
_deadline_records = [_collections.deque() for _ in range(NUM_PRIORITIES)]
_queue_limits = [None] * NUM_PRIORITIES
_queue_policies = ["reject"] * NUM_PRIORITIES
_queue_high_water = [0] * NUM_PRIORITIES
_live_records = set()
_active_group_count = 0
_qos_on = True

_rej_counters = [_reg.counter("engine_queue_rejections", **{"class": n})
                 for n in PRIORITY_NAMES]
_hw_gauges = [_reg.gauge("engine_queue_high_water", **{"class": n})
              for n in PRIORITY_NAMES]
_dispatch_wait_hists = [
    _reg.histogram("engine_dispatch_wait_seconds", **{"class": n})
    for n in PRIORITY_NAMES]
_cancel_counter = _reg.counter("engine_tasks_cancelled")
_expired_counter = _reg.counter("engine_deadline_expired")
_groups_gauge = _reg.gauge("engine_task_groups")
_groups_gauge.set(0)
for _g in _hw_gauges:
    _g.set(0)


class _TaskRecord:
    """Facade-side lifecycle record of one pushed task: admission class,
    group membership, deadline, and the queued->running->done transition
    that cancellation races against."""
    __slots__ = ("site", "pri", "group", "deadline", "t_push", "state",
                 "skip_reason", "fut", "_lock", "_left_queue", "_done_evt")

    def __init__(self, site, pri, group, deadline):
        self.site = site
        self.pri = pri
        self.group = group
        self.deadline = deadline
        self.t_push = _time.monotonic()
        self.state = "queued"          # queued -> running -> done
        self.skip_reason = None        # "cancelled" | "shed" | "expired"
        self.fut = None
        self._lock = threading.Lock()
        self._left_queue = False
        self._done_evt = threading.Event()

    def _try_start(self):
        with self._lock:
            if self.state != "queued" or self.skip_reason:
                return False
            self.state = "running"
        self._leave_queue()
        return True

    def _try_cancel(self, reason="cancelled"):
        with self._lock:
            if self.state != "queued" or self.skip_reason:
                return False
            self.skip_reason = reason
        self._leave_queue()
        return True

    def _leave_queue(self):
        with self._lock:
            if self._left_queue:
                return
            self._left_queue = True
        with _admission_cv:
            _queued_count[self.pri] -= 1
            if self.deadline is not None:
                _deadline_queued[self.pri] -= 1
            _admission_cv.notify_all()

    def _on_done(self, _fut=None):
        with self._lock:
            # under the lock, BEFORE _leave_queue: a racing _try_cancel
            # must not observe "queued" on an already-settled record and
            # report a cancellation (inflating cancel counts / shedding
            # a slot that was never freed)
            self.state = "done"
        self._leave_queue()            # dep-failed tasks never start
        self.fut = None    # settled records may linger in bookkeeping
                           # deques until compaction — don't pin results
        if self.group is not None:
            self.group._remove(self)
        with _qos_lock:
            _live_records.discard(self)
        self._done_evt.set()


def _append_bounded(q, rec, live_hint):
    """Append rec to a bookkeeping deque of queued records (shed order /
    deadline carriers): drop settled HEADS cheaply, and when settled
    records accumulate behind a head pinned queued by a slow dependency,
    compact — at most ~live_hint survive, so the deque tracks live
    queued tasks (O(1) amortised per append), not history. Settled
    records pin nothing heavy either way (_on_done drops rec.fut)."""
    while q and (q[0].state != "queued" or q[0].skip_reason):
        q.popleft()
    q.append(rec)
    if len(q) > 4 * max(1, live_hint) + 16:
        live = [r for r in q if r.state == "queued" and not r.skip_reason]
        q.clear()
        q.extend(live)


def _admit(rec):
    """Bounded-queue admission for one record. Returns after the record
    is accounted into its class's queued count; raises EngineQueueFull
    (reject policy), blocks (block policy), or cancels the class's
    oldest queued task to make room (shed_oldest policy). A full class
    first sweeps queued occupants whose DEADLINE already passed —
    an expired task waiting on a wedged dependency must not hold an
    admission slot against live work (its future still resolves to
    engine.EXPIRED, in dependency order)."""
    pri = rec.pri
    while True:
        victim = None
        expired = None
        with _admission_cv:
            limit = _queue_limits[pri]
            if limit is not None and _queued_count[pri] >= limit \
                    and _deadline_queued[pri]:
                # sweep gated on the per-class deadline count and scoped
                # to the per-class deadline-carrier deque, so deadline-
                # free workloads (the common flood) never pay it and the
                # cost scales with deadline carriers, not engine load
                now = _time.monotonic()
                expired = [r for r in _deadline_records[pri]
                           if r.state == "queued" and not r.skip_reason
                           and now > r.deadline]
            if limit is None or _queued_count[pri] < limit:
                _queued_count[pri] += 1
                if rec.deadline is not None:
                    _deadline_queued[pri] += 1
                    _append_bounded(_deadline_records[pri], rec,
                                    _deadline_queued[pri])
                if limit is not None and \
                        _queue_policies[pri] == "shed_oldest":
                    # shed bookkeeping only when the policy needs it —
                    # an unbounded class must not accumulate records
                    _append_bounded(_queued_records[pri], rec, limit)
                if _queued_count[pri] > _queue_high_water[pri]:
                    _queue_high_water[pri] = _queued_count[pri]
                    _hw_gauges[pri].set(_queue_high_water[pri])
                _live_records.add(rec)
                return
            policy = _queue_policies[pri]
            if policy == "reject":
                if not expired:
                    _rej_counters[pri].inc()
                    raise EngineQueueFull(
                        f"engine {PRIORITY_NAMES[pri]!r} queue full "
                        f"(limit {limit}, policy=reject); retry later")
            elif policy == "shed_oldest":
                if not expired:
                    q = _queued_records[pri]
                    while q:
                        cand = q.popleft()
                        if cand.state == "queued" and not cand.skip_reason:
                            victim = cand
                            break
                    if victim is None:
                        # nothing sheddable (everything at the limit is
                        # already running): briefly wait for room
                        _admission_cv.wait(0.05)
                        continue
            else:                      # block
                if not expired:
                    # bounded wait, not wait(): a slot-holder's deadline
                    # may pass with no notify — wake and re-sweep
                    _admission_cv.wait(0.05)
                    continue
        # cancel OUTSIDE the admission lock: _try_cancel re-enters it via
        # _leave_queue, which frees the slot(s) this loop then claims
        if expired:
            for r in expired:
                r._try_cancel("expired")
            continue
        if victim._try_cancel("shed"):
            _rej_counters[pri].inc()


def _resolve_priority(priority):
    if priority is None:
        return PRIORITY_NORMAL
    pri = _clamp_pri(priority)
    return pri if _qos_on else PRIORITY_NORMAL


class TaskGroup:
    """First-class cancellable group of engine tasks (ISSUE 7).

    Generalises PR 5's prefetch cancellation and PR 6's
    `Scheduler.shutdown` into one engine API (`DevicePrefetcher`, async
    checkpoint saves and the serve loop all push through one):
    `cancel()` atomically flags every member task that has not STARTED —
    their user fn never runs and their futures resolve to
    `engine.CANCELLED` in dependency order, so var release stays
    race-free and nothing is poisoned — while in-flight members run to
    completion; `drain()` blocks until everything settles. One edge is
    deliberate: a cancelled member queued behind an ALREADY-FAILED
    dependency resolves to that dependency's error, like any other
    dependent — cancellation skips the member's own work, it does not
    mask an upstream failure (consumers using
    `engine.skipped(f.result())` should expect the re-raise there). Cancelled
    tasks are NOT failures: they appear in no failure report, do not
    count into `engine_task_failures`, and cannot trip the race
    detector. Groups are reusable (new pushes after `cancel()` run
    normally) and leak-free: settled tasks drop out of the group, and a
    group with no live tasks stops counting into `active_groups()` /
    the `engine_task_groups` gauge.

        g = engine.TaskGroup("prefetch")
        g.push(stage, write_vars=[slot], priority=engine.PRIORITY_BACKGROUND)
        ...
        g.cancel_and_drain()    # or: with engine.TaskGroup("x") as g: ...
    """

    def __init__(self, name="group"):
        self.name = str(name)
        self._lock = threading.Lock()
        self._records = set()

    def push(self, fn, read_vars=(), write_vars=(), priority=None,
             deadline_ms=None):
        return push(fn, read_vars, write_vars, priority=priority,
                    group=self, deadline_ms=deadline_ms)

    def _add(self, rec):
        # the live delta is applied INSIDE the group lock (lock order:
        # group._lock -> _qos_lock, nothing takes them reversed): applied
        # outside, a member completing on a worker could land its -1
        # before this +1 and a concurrent poller would read
        # active_groups() == -1
        with self._lock:
            if not self._records:
                _group_live_delta(+1)
            self._records.add(rec)

    def _remove(self, rec):
        with self._lock:
            self._records.discard(rec)
            if not self._records:
                _group_live_delta(-1)

    def cancel(self):
        """Cancel every member task that has not started; returns how
        many were cancelled. In-flight members keep running — `drain()`
        waits for them. New pushes into the group remain allowed."""
        with self._lock:
            recs = list(self._records)
        n = 0
        for r in recs:
            if r._try_cancel():
                n += 1
        return n

    def drain(self, timeout=None):
        """Block until every member task settles (completed, failed, or
        resolved cancelled). True when drained, False on timeout."""
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            with self._lock:
                rec = next(iter(self._records), None)
            if rec is None:
                return True
            rem = None
            if deadline is not None:
                rem = deadline - _time.monotonic()
                if rem <= 0:
                    return False
            rec._done_evt.wait(rem)
            if not rec._done_evt.is_set():
                return False

    def cancel_and_drain(self, timeout=None):
        self.cancel()
        return self.drain(timeout)

    def pending(self):
        """Member tasks queued-not-started (cancellable)."""
        with self._lock:
            return sum(1 for r in self._records
                       if r.state == "queued" and not r.skip_reason)

    def inflight(self):
        """Member tasks currently running (cancel cannot stop these)."""
        with self._lock:
            return sum(1 for r in self._records if r.state == "running")

    def live(self):
        """Member tasks not yet settled (queued + running + cancelled-
        but-not-yet-resolved)."""
        with self._lock:
            return len(self._records)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.cancel_and_drain()
        return False


def _group_live_delta(delta):
    global _active_group_count
    with _qos_lock:
        _active_group_count += delta
        # publish under the lock: two racing deltas could otherwise set
        # the gauge out of order and leave engine_task_groups stale
        _groups_gauge.set(_active_group_count)


def active_groups():
    """Number of TaskGroups that currently have live (unsettled) member
    tasks — zero once every group has drained (tools/check_qos.py's
    group-leak gate)."""
    with _qos_lock:
        return _active_group_count


def push(fn, read_vars=(), write_vars=(), priority=None, group=None,
         deadline_ms=None):
    """Schedule fn after its dependencies (reference: Engine::PushAsync).

    QoS (ISSUE 7): `priority` is PRIORITY_HIGH / PRIORITY_NORMAL
    (default) / PRIORITY_BACKGROUND — ready tasks dispatch
    best-class-first with aging (`set_aging_ms`), so background floods
    cannot starve serve turns and vice versa. `group` attaches the task
    to a `TaskGroup` (cancellable as a unit). `deadline_ms` bounds the
    QUEUED lifetime: a task that has not started in time is skipped —
    its future resolves to `engine.EXPIRED`, nothing is poisoned, and
    `engine_deadline_expired` counts it."""
    pri = _resolve_priority(priority)
    site0 = _dispatch_site(fn)
    rec = _TaskRecord(site0, pri, group,
                      None if deadline_ms is None
                      else _time.monotonic() + deadline_ms / 1000.0)
    _admit(rec)
    # group membership only AFTER admission: a concurrent group.cancel()
    # must never cancel a record the bounded-queue accounting has not
    # admitted yet — its _leave_queue would decrement a count that was
    # never incremented (and a reject-policy raise would leave the class
    # permanently under-counted). The cost is that a push parked at a
    # full `block`-policy class is not group-cancellable until admitted.
    if group is not None:
        group._add(rec)
    _queue_delta(+1)
    # one-shot: the normal decrement runs in _task's finally, but a task
    # whose DEPENDENCY failed never runs fn (the engine re-raises the dep
    # error before entering it) — the done-callback below catches that
    # path so the depth gauge cannot leak upward
    dec_once = threading.Lock()

    def _dec():
        if dec_once.acquire(blocking=False):
            _queue_delta(-1)

    def _run_fn():
        # fault point + sticky failure report wrap the USER fn only:
        # dependency re-raises happen in the inner engines before _task's
        # fn runs, so a recorded failure is always the root cause
        try:
            if _finj.ENABLED:
                _finj.check("engine.task", context=site0)
            return fn()
        except BaseException as exc:
            _record_failure(site0, exc)
            raise

    def _task():
        if not rec._try_start():
            # cancelled (TaskGroup) or shed while queued: skip the user
            # fn and resolve CLEAN, in dependency order — dependents and
            # var release proceed as if the task ran and did nothing
            (_expired_counter if rec.skip_reason == "expired"
             else _cancel_counter).inc()
            _dec()
            return EXPIRED if rec.skip_reason == "expired" else CANCELLED
        now = _time.monotonic()
        if rec.deadline is not None and now > rec.deadline:
            rec.skip_reason = "expired"
            _expired_counter.inc()
            _dec()
            return EXPIRED
        _dispatch_wait_hists[rec.pri].observe(now - rec.t_push)
        t0 = _time.perf_counter()
        try:
            if _tracer.ACTIVE:
                with _tracer.span(f"engine:{site0}", cat="engine"):
                    return _run_fn()
            return _run_fn()
        finally:
            dt = _time.perf_counter() - t0
            _busy_counter.inc(dt)
            _task_hist.observe(dt)
            _dec()

    _task._mxtpu_site = site0      # instance failure logs name the USER fn
    try:
        fut = _get().push(_task, read_vars, write_vars, priority=pri)
    except BaseException:
        # inner-engine push failed AFTER admission (bad var object, a
        # closed native engine): roll the admission back or the class
        # permanently loses a bounded-queue slot, the group never drains
        # and pending_report() carries a phantom queued entry forever
        _queue_delta(-1)
        rec._on_done()
        raise
    rec.fut = fut
    if hasattr(fut, "add_done_callback"):
        fut.add_done_callback(lambda _f: _dec())
        fut.add_done_callback(rec._on_done)
    return fut


def set_queue_limit(priority, limit, policy="reject"):
    """Bound the number of queued-not-started tasks of one priority
    class (None removes the bound — the default). Backpressure policy:

      * ``reject``      — an over-limit push raises `EngineQueueFull`;
      * ``block``       — an over-limit push blocks until the class
                          drains below the limit (do NOT use from code
                          that itself runs on an engine worker);
      * ``shed_oldest`` — the class's OLDEST queued task is cancelled to
                          make room (its future resolves to
                          engine.CANCELLED).

    Rejected and shed tasks count into `engine_queue_rejections{class}`;
    the deepest queue each class ever reached is the
    `engine_queue_high_water{class}` gauge. Shed candidacy starts at the
    moment the shed_oldest policy is set — tasks already queued before
    that are waited out, not shed. Returns the previous (limit, policy)
    pair so scopes can restore it."""
    pri = _clamp_pri(priority)
    if policy not in ("reject", "block", "shed_oldest"):
        raise MXNetError(f"unknown queue policy {policy!r}; use 'reject', "
                         "'block' or 'shed_oldest'")
    with _admission_cv:
        prev = (_queue_limits[pri], _queue_policies[pri])
        _queue_limits[pri] = None if limit is None else max(1, int(limit))
        _queue_policies[pri] = policy
        if _queue_limits[pri] is None or policy != "shed_oldest":
            # shed bookkeeping holds strong record refs (futures +
            # closures); a class leaving shed_oldest must drop them or
            # every record admitted during the shed window leaks
            _queued_records[pri].clear()
        _admission_cv.notify_all()
    return prev


def get_queue_limit(priority):
    """The (limit, policy) pair of a priority class (limit None =
    unbounded)."""
    pri = _clamp_pri(priority)
    with _qos_lock:
        return (_queue_limits[pri], _queue_policies[pri])


def set_aging_ms(ms):
    """Starvation-aging interval shared by both engine implementations:
    every `ms` milliseconds a READY task waits promotes it one priority
    class, floored at the high class (promoted work ties but never
    outranks native high-class tasks; ties among promoted classes go to
    the longest waiter). Background work therefore overtakes fresh
    normal work after ~NUM_PRIORITIES * ms, while high-class dispatch
    latency stays bounded by the running tasks' duration (0 disables
    aging; env default MXTPU_ENGINE_AGING_MS, 100). Returns the
    previous value."""
    eng = _get()
    prev = eng.get_aging_ms() if hasattr(eng, "get_aging_ms") else 0
    if hasattr(eng, "set_aging_ms"):
        eng.set_aging_ms(int(ms))
    return prev


def get_aging_ms():
    eng = _get()
    return eng.get_aging_ms() if hasattr(eng, "get_aging_ms") else 0


def set_qos(on):
    """Enable/disable priority scheduling at the facade. Disabled maps
    every push to PRIORITY_NORMAL — pure FIFO, the `bench_serve.py
    --background-train` baseline. Returns the previous setting."""
    global _qos_on
    prev = _qos_on
    _qos_on = bool(on)
    return prev


def qos_enabled():
    return _qos_on


def pending_report():
    """Snapshot of facade-pushed tasks that have not settled: site,
    priority class, group, state (queued/running), age, and whether the
    task is past its deadline (`overdue`) — oldest first. The step
    watchdog embeds this in its stall post-mortem so a wedged queue
    names its offender (e.g. a stuck background task ahead of queued
    high-priority work)."""
    now = _time.monotonic()
    with _qos_lock:
        recs = list(_live_records)
    out = []
    for r in recs:
        if r.state == "done":
            continue
        out.append({
            "site": r.site,
            "class": PRIORITY_NAMES[r.pri],
            "group": r.group.name if r.group is not None else None,
            "state": r.state,
            "age_s": round(now - r.t_push, 3),
            "overdue": bool(r.deadline is not None and now > r.deadline),
        })
    out.sort(key=lambda d: -d["age_s"])
    return out


def pending_tasks():
    """Engine tasks currently queued or running (the queue-depth gauge's
    instantaneous value — what the watchdog polls before deciding
    whether a bounded drain is warranted)."""
    with _qlock:
        return _queue_depth


def tasks_completed():
    """Monotonic count of engine tasks that have finished (success or
    failure) since process start — the watchdog's progress signal."""
    return _task_hist.count


def wait_for_var(var):
    t0 = _time.perf_counter()
    with _tracer.span("engine.wait_for_var", cat="engine"):
        _get().wait_for_var(var)
    _wait_hist.observe(_time.perf_counter() - t0)


def wait_for_all():
    with _tracer.span("engine.wait_for_all", cat="engine"):
        _get().wait_for_all()
        from .ndarray.ndarray import waitall
        waitall()


# Bulk size = the fused Trainer path's gradient-bucket byte cap
# (optimizer/multi_tensor.py groups parameters into dtype-homogeneous
# buckets of at most this many bytes; one allreduce + one fused optimizer
# dispatch per bucket). Reference Engine::SetBulkSize counts ops; here the
# analogous dispatch-batching knob is bytes, and 0 keeps the reference's
# "unbulked" meaning: every parameter gets its own bucket.
_DEFAULT_BULK_BYTES = 64 << 20
_OP_COUNT_SCALE = 4096   # below this, `size` is a reference op count
_bulk_size = _DEFAULT_BULK_BYTES


def set_bulk_size(size):
    """Set the fused-update bucket byte cap (reference: Engine::SetBulkSize).
    0 = unbulked/per-parameter buckets. The reference's argument counts
    OPS (typical values 4-15); a byte cap that small would silently
    degrade every bucket to per-param, so op-count-scale sizes
    (0 < size < 4096) mean "bulked at the default byte cap" while
    byte-scale sizes pass through as caps. Returns the previous value so
    scopes can restore it.

    Bulk/captured interplay: the cap shapes the IMPERATIVE fused path's
    bucket layout only. A captured step (`Trainer.capture`,
    mxnet_tpu/cachedop.py) is already one executable — there is nothing
    left to bulk, so the cap (and `engine.bulk()` scopes) neither affect
    it nor invalidate its cache; the imperative fallback path inside a
    CachedStep still honors the cap like any `Trainer.step`."""
    global _bulk_size
    prev = _bulk_size
    size = max(0, int(size))
    if 0 < size < _OP_COUNT_SCALE:
        size = _DEFAULT_BULK_BYTES
    _bulk_size = size
    return prev


def get_bulk_size():
    """The current fused-update bucket byte cap (0 = per-param buckets)."""
    return _bulk_size


def num_workers():
    return getattr(_get(), "workers", 1)


# ---------------------------------------------------------- file vars
_file_vars = {}
_file_vars_lock = threading.Lock()


def file_var(path):
    """The dependency Var for a filesystem path. Host IO (NDArray save,
    recordio writes) pushes write ops on this var; loads/readers wait on it
    — the same var discipline the reference engine applies to NDArray
    save/load (reference: NDArray::Save pushed with the array + output
    vars)."""
    p = _os.path.abspath(str(path))
    with _file_vars_lock:
        v = _file_vars.get(p)
        if v is None:
            if len(_file_vars) > 256:
                _evict_drained_file_vars_locked()
            v = _file_vars[p] = Var()
        return v


def _evict_drained_file_vars_locked():
    """Drop file vars whose ops have all completed (step-stamped checkpoint
    runs would otherwise leak one Var + native var id per path)."""
    eng = _get()
    for p, v in list(_file_vars.items()):
        with v._lock:
            done = (v._last_write is None or v._last_write.done()) and \
                all(f.done() for f in v._reads)
        if done:
            nid = getattr(v, "_native_id", None)
            if nid is not None and hasattr(eng, "del_var"):
                eng.del_var(nid)   # refcount-guarded against a racing close
            del _file_vars[p]


# ---------------------------------------------------------- debug facade
def set_debug(on):
    """Toggle the engine race/deadlock detector (env: MXTPU_ENGINE_DEBUG)."""
    _get().set_debug(on)


def debug_enabled():
    return _get().debug_enabled()


def debug_check():
    """0 = per-var scheduling invariants hold; 1 = hazard recorded."""
    return _get().debug_check()


def debug_check_raise():
    """Raise MXNetError when the detector has recorded a hazard."""
    if _get().debug_check():
        raise MXNetError(f"engine hazard: {last_error()}")


def last_error():
    return _get().last_error()


def clear_error():
    _get().clear_error()


def wait_for_all_timeout(timeout_ms):
    """Bounded drain: 0 = drained, 1 = stall/deadlock suspected."""
    return _get().wait_for_all_timeout(timeout_ms)


class bulk:
    """Bulk-execution scope (reference: mxnet.engine.bulk): upstream
    batches `size` engine ops into one dependency-graph segment and
    restores the previous bulk size on exit — it never synchronizes.
    Here the scope sets `set_bulk_size` (the fused Trainer path's
    gradient-bucket byte cap; 0 = per-param, op-count-scale sizes map to
    the default byte cap — see set_bulk_size) for its extent and restores
    the previous cap on exit. Device-op fusion inside a bucket remains
    XLA's job; no drain on exit, matching the reference's non-blocking
    contract."""

    def __init__(self, size=_DEFAULT_BULK_BYTES):
        self.size = int(size)
        self._prev = None

    def __enter__(self):
        self._prev = set_bulk_size(self.size)
        return self

    def __exit__(self, *exc):
        set_bulk_size(self._prev)
        return False
