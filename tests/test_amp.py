"""AMP tests (SURVEY.md §2 #32)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, nd, autograd, gluon
from mxnet_tpu.gluon import nn


def test_convert_block_casts_matmul_keeps_norms():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.BatchNorm(axis=1, in_channels=8),
            nn.Dense(2, in_units=8))
    net.initialize()
    amp.convert_block(net, "bfloat16")
    dense_w = net[0].weight.data()
    bn_gamma = net[1].gamma.data()
    assert "bfloat16" in str(dense_w.dtype)
    assert "float32" in str(bn_gamma.dtype)


def test_bf16_forward_backward():
    net = nn.Dense(4, in_units=4)
    net.initialize()
    net.cast("bfloat16")
    x = nd.random.uniform(shape=(2, 4), dtype="bfloat16")
    with autograd.record():
        y = net(x)
        loss = (y * y).sum()
    loss.backward()
    g = net.weight.grad()
    assert "bfloat16" in str(g.dtype)
    assert np.isfinite(g.asnumpy().astype(np.float32)).all()


def test_dynamic_loss_scaler_down_on_overflow():
    s = amp.DynamicLossScaler(init_scale=1024.0, scale_factor=2.0,
                              scale_window=2)
    s.update_scale(True)
    assert s.loss_scale == 512.0
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 1024.0  # window hit -> scale back up


def test_dynamic_loss_scaler_floor_at_one():
    """Satellite (ISSUE 3): repeated overflows halve the scale but never
    push it below 1.0 (the floor that keeps grads representable)."""
    s = amp.DynamicLossScaler(init_scale=4.0, scale_factor=2.0,
                              scale_window=100)
    for _ in range(10):
        s.update_scale(True)
    assert s.loss_scale == 1.0
    s.update_scale(True)
    assert s.loss_scale == 1.0      # clamped, not 0.5


def test_dynamic_loss_scaler_window_resets_on_overflow():
    """An overflow inside the growth window resets the unskipped streak:
    growth needs a FULL clean window afterwards."""
    s = amp.DynamicLossScaler(init_scale=1024.0, scale_factor=2.0,
                              scale_window=3)
    s.update_scale(False)
    s.update_scale(False)
    s.update_scale(True)            # overflow 1 step before growth
    assert s.loss_scale == 512.0
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 512.0    # streak restarted: no growth yet
    s.update_scale(False)
    assert s.loss_scale == 1024.0   # full clean window -> doubles
    # and the window counter resets after growth too
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 1024.0


def test_scale_loss_and_unscale_roundtrip():
    amp.init(target_dtype="float16")
    try:
        net = nn.Dense(2, in_units=2)
        net.initialize()
        x = nd.ones((1, 2))
        with autograd.record():
            y = net(x).sum()
            scaled = amp.scale_loss(y)
        scaled.backward()
        scale = amp._state["scaler"].loss_scale
        g_scaled = net.weight.grad().asnumpy().copy()
        amp.unscale([p for p in net.collect_params().values()])
        g = net.weight.grad().asnumpy()
        np.testing.assert_allclose(g * scale, g_scaled, rtol=1e-3)
    finally:
        amp._state["scaler"] = None
        amp._state["initialized"] = False


def test_overflow_detection():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    x = nd.ones((1, 2))
    with autograd.record():
        y = net(x).sum() * float("inf")
    y.backward()
    s = amp.DynamicLossScaler()
    assert s.has_overflow(list(net.collect_params().values()))


@pytest.fixture
def _amp_off():
    yield
    amp.reset()


def test_init_autocasts_dense_compute(_amp_off):
    """amp.init() must actually change op compute dtype: fp32 in, bf16 out."""
    net = nn.Dense(4, in_units=4)
    net.initialize()
    x = nd.ones((2, 4))
    assert "float32" in str(net(x).dtype)
    amp.init("bfloat16")
    y = net(x)
    assert "bfloat16" in str(y.dtype)
    # params stay fp32 masters
    assert "float32" in str(net.weight.data().dtype)


def test_convert_block_fixes_blanket_cast(_amp_off):
    """_KEEP_FP32 is live: convert_block after net.cast('bfloat16') restores
    the norm layers to fp32."""
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.BatchNorm(axis=1, in_channels=8))
    net.initialize()
    net.cast("bfloat16")
    assert "bfloat16" in str(net[1].gamma.data().dtype)
    amp.convert_block(net, "bfloat16")
    assert "float32" in str(net[1].gamma.data().dtype)
    assert "bfloat16" in str(net[0].weight.data().dtype)


def test_trainer_skips_update_on_overflow_and_halves_scale(_amp_off):
    """The VERDICT-mandated test: force an overflow, assert the update is
    skipped and the loss scale halves."""
    amp.init("float16")
    scaler = amp._state["scaler"]
    scaler.loss_scale = 1024.0
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    w0 = net.weight.data().asnumpy().copy()
    x = nd.ones((1, 2))
    with autograd.record():
        loss = amp.scale_loss(net(x).sum() * float("inf"))
    loss.backward()
    trainer.step(1)
    np.testing.assert_allclose(net.weight.data().asnumpy(), w0)  # skipped
    assert scaler.loss_scale == 512.0                            # halved
    # a clean step afterwards must update
    with autograd.record():
        loss = amp.scale_loss(net(x).sum())
    loss.backward()
    trainer.step(1)
    assert not np.allclose(net.weight.data().asnumpy(), w0)


def test_init_busts_hybridize_cache(_amp_off):
    """amp.init() after a hybridized net compiled must still take effect
    (the jit cache is keyed on the autocast dtype)."""
    net = nn.Dense(4, in_units=4)
    net.initialize()
    net.hybridize()
    x = nd.ones((2, 4))
    assert "float32" in str(net(x).dtype)   # compiled pre-AMP
    amp.init("bfloat16")
    assert "bfloat16" in str(net(x).dtype)  # fresh trace post-AMP
    amp.reset()
    assert "float32" in str(net(x).dtype)   # and back


def test_trainer_update_also_guarded(_amp_off):
    """The allreduce_grads()+update() flow must hit the same AMP
    unscale/overflow guard as step()."""
    amp.init("float16")
    scaler = amp._state["scaler"]
    scaler.loss_scale = 1024.0
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    w0 = net.weight.data().asnumpy().copy()
    x = nd.ones((1, 2))
    with autograd.record():
        loss = amp.scale_loss(net(x).sum() * float("inf"))
    loss.backward()
    trainer.allreduce_grads()
    trainer.update(1)
    np.testing.assert_allclose(net.weight.data().asnumpy(), w0)
    assert scaler.loss_scale == 512.0
    # clean grads: update() must unscale before applying
    with autograd.record():
        loss = amp.scale_loss(net(x).sum())
    loss.backward()
    trainer.allreduce_grads()
    trainer.update(1)
    w1 = net.weight.data().asnumpy()
    assert not np.allclose(w1, w0)
    # grad of sum(xW^T+b) wrt W is x=1; unscaled update = lr*1 = 0.1
    np.testing.assert_allclose(w0 - w1, np.full_like(w0, 0.1), rtol=1e-3)


def test_trainer_skip_nonfinite(_amp_off):
    """skip_nonfinite guards non-AMP training too (§5 failure detection)."""
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, skip_nonfinite=True)
    w0 = net.weight.data().asnumpy().copy()
    x = nd.ones((1, 2))
    with autograd.record():
        loss = net(x).sum() * float("nan")
    loss.backward()
    trainer.step(1)
    np.testing.assert_allclose(net.weight.data().asnumpy(), w0)
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(1)
    assert not np.allclose(net.weight.data().asnumpy(), w0)
