"""Unified tracing & metrics subsystem (mx.observability + profiler wiring):
Chrome-trace schema, metrics registry semantics, engine/KVStore/Trainer
instrumentation, satellites (pause/resume, Scope tally, Monitor handles,
device-side numeric checks), and the disabled-path overhead smoke test."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, nd, profiler
from mxnet_tpu.observability import metrics_registry, registry, tracer

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import check_trace  # noqa: E402

CHECK_TRACE = os.path.join(os.path.dirname(__file__), "..", "tools",
                           "check_trace.py")


@pytest.fixture(autouse=True)
def _quiesce_tracer():
    yield
    profiler._state["running"] = False
    profiler._state["jax_paused"] = False
    tracer.set_jax_annotation(False)
    tracer.stop()
    tracer.clear()


def _tiny_trainer(fused=True, kvstore="ici"):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    X = nd.array(np.random.randn(4, 6).astype(np.float32))
    net(X)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, fused=fused,
                       kvstore=kvstore)
    lossf = gluon.loss.L2Loss()
    y = nd.array(np.zeros((4, 4), np.float32))

    def step():
        with autograd.record():
            L = lossf(net(X), y).mean()
        L.backward()
        tr.step(4)
    return step


# ------------------------------------------------------------- tracer core
def test_chrome_trace_schema_valid(tmp_path):
    path = str(tmp_path / "profile.json")
    profiler.set_config(filename=path)
    profiler.start()
    with tracer.span("outer", args={"k": 1}):
        with tracer.span("inner"):
            tracer.instant("marker")
        tracer.counter("queue", 3)

    def worker():
        with tracer.span("worker-span"):
            pass
    t = threading.Thread(target=worker, name="obs-worker")
    t.start()
    t.join()
    profiler.stop()
    out = profiler.dump()
    assert out == path and os.path.exists(path)   # full path preserved
    assert check_trace.validate_file(path) == []
    trace = json.load(open(path))
    events = trace["traceEvents"]
    names = {e.get("name") for e in events}
    assert {"outer", "inner", "marker", "queue"} <= names
    # required keys + monotonic ts on the duration events
    body = [e for e in events if e["ph"] != "M"]
    for e in body:
        assert {"ph", "ts", "pid", "tid"} <= set(e)
    ts = [e["ts"] for e in body]
    assert ts == sorted(ts) and all(t >= 0 for t in ts)
    assert len([e for e in body if e["ph"] == "B"]) == \
        len([e for e in body if e["ph"] == "E"])
    # per-thread tracks: worker span on its own tid with thread_name meta
    wtid = [e["tid"] for e in body if e.get("name") == "worker-span"][0]
    thread_names = [e["args"]["name"] for e in events
                    if e["ph"] == "M" and e["name"] == "thread_name"]
    assert any("obs-worker" in n for n in thread_names)
    assert wtid != [e["tid"] for e in body if e.get("name") == "outer"][0]


def test_ring_buffer_bounded_and_balance_repaired(tmp_path):
    tracer.start(buffer_size=64)
    for i in range(500):
        with tracer.span(f"s{i}"):
            pass
    assert tracer.events_recorded() <= 64
    path = tracer.dump(str(tmp_path / "ring.json"))
    assert check_trace.validate_file(path) == []   # orphan E repaired
    tracer.stop()


def test_check_trace_cli_and_rejects_malformed(tmp_path):
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"traceEvents": [
        {"ph": "B", "ts": 1, "pid": 1, "tid": 0, "name": "a"},
        {"ph": "E", "ts": 2, "pid": 1, "tid": 0},
    ]}))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"traceEvents": [
        {"ph": "B", "ts": 5, "pid": 1, "tid": 0, "name": "a"},
        {"ph": "B", "ts": 4, "pid": 1, "tid": 0, "name": "b"},   # ts back
        {"ph": "X", "ts": 6, "pid": 1, "tid": 0, "name": "x"},   # no dur
    ]}))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    assert subprocess.run([sys.executable, CHECK_TRACE, str(good)],
                          env=env, capture_output=True).returncode == 0
    proc = subprocess.run([sys.executable, CHECK_TRACE, str(bad)],
                          env=env, capture_output=True, text=True)
    assert proc.returncode == 1
    assert "backwards" in proc.stderr and "unclosed" in proc.stderr
    assert check_trace.validate({"nope": 1}) != []
    errs = check_trace.validate_file(str(bad))
    assert any("dur" in e for e in errs)


# ------------------------------------------------------------- metrics
def test_metrics_registry_semantics(tmp_path):
    reg = metrics_registry.MetricsRegistry()
    c = reg.counter("requests", route="push")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert reg.counter("requests", route="push") is c      # cached handle
    c2 = reg.counter("requests", route="pull")             # labels split
    c2.inc()
    assert [m.value for m in reg.series("requests")] == [5, 1]
    g = reg.gauge("depth")
    g.set(7)
    assert g.value == 7
    h = reg.histogram("lat")
    for v in (0.001, 0.002, 0.004, 0.4):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4 and abs(snap["sum"] - 0.407) < 1e-9
    assert snap["min"] == 0.001 and snap["max"] == 0.4
    assert 0.001 <= snap["p50"] <= 0.01 and snap["p99"] >= 0.1
    # quantile-snapshot satellite (ISSUE 6): p95 in the snapshot, and
    # quantiles() walks the buckets once for all requested points,
    # agreeing with the one-at-a-time quantile() estimates
    assert snap["p50"] <= snap["p95"] <= snap["p99"]
    qs = h.quantiles((0.5, 0.95, 0.99))
    assert qs == {0.5: h.quantile(0.5), 0.95: h.quantile(0.95),
                  0.99: h.quantile(0.99)}
    # empty histogram: quantiles are 0.0 (separate registry so this
    # test's series/JSONL counts below stay unchanged)
    empty = metrics_registry.MetricsRegistry().histogram("lat_empty")
    assert empty.quantiles((0.5,)) == {0.5: 0.0}
    full = reg.snapshot()
    assert {"requests", "depth", "lat"} <= set(full)
    assert {s["labels"]["route"] for s in full["requests"]} == \
        {"push", "pull"}
    # kind conflict on the same (name, labels) is an error
    with pytest.raises(TypeError):
        reg.gauge("requests", route="push")
    # JSONL sink: one line per series, parseable
    p = str(tmp_path / "metrics.jsonl")
    reg.dump_jsonl(p)
    lines = [json.loads(ln) for ln in open(p)]
    assert len(lines) == 4
    assert {ln["name"] for ln in lines} == {"requests", "depth", "lat"}
    # reset zeroes values but keeps handles valid
    reg.reset()
    assert c.value == 0 and g.value is None and h.count == 0
    c.inc()
    assert reg.counter("requests", route="push").value == 1


def test_profiler_counters_ride_the_registry():
    profiler.reset_dispatches()
    profiler.record_dispatch("unit_test_site", 3)
    profiler.record_jit_cache(True)
    assert profiler.dispatch_count("unit_test_site") == 3
    assert profiler.jit_cache_stats() == (1, 0)
    snap = registry().snapshot()
    sites = {s["labels"]["site"]: s["value"] for s in snap["dispatch"]}
    assert sites["unit_test_site"] == 3
    assert "[dispatch] unit_test_site=3" in profiler.dumps()
    profiler.dumps(reset=True)
    assert profiler.dispatch_count() == 0
    assert profiler.jit_cache_stats() == (0, 0)
    assert "[dispatch]" not in profiler.dumps()


# ------------------------------------------------------------- engine
def test_engine_queue_depth_gauge_under_concurrent_push():
    gauge = registry().gauge("engine_queue_depth")
    busy = registry().counter("engine_busy_seconds")
    engine.wait_for_all()
    assert gauge.value == 0
    busy0 = busy.value
    release = threading.Event()
    seen = []

    def pusher():
        engine.push(lambda: (release.wait(5), seen.append(1)))

    threads = [threading.Thread(target=pusher) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert gauge.value == 6          # all queued/blocked, none finished
    release.set()
    engine.wait_for_all()
    assert gauge.value == 0
    assert len(seen) == 6
    assert busy.value > busy0        # worker busy time accumulated
    assert registry().gauge("engine_workers").value >= 1


def test_engine_task_span_named_by_dispatch_site(tmp_path):
    tracer.start()

    def my_io_task():
        return 42
    fut = engine.push(my_io_task)
    engine.wait_for_all()
    assert fut.result() == 42
    path = tracer.dump(str(tmp_path / "engine.json"))
    tracer.stop()
    assert check_trace.validate_file(path) == []
    names = [e.get("name") for e in json.load(open(path))["traceEvents"]]
    assert any(n and n.startswith("engine:") and "my_io_task" in n
               for n in names)
    # var-wait latency histogram observed something
    v = engine.Var()
    engine.push(lambda: time.sleep(0.01), write_vars=[v])
    engine.wait_for_var(v)
    assert registry().histogram("engine_var_wait_seconds").count >= 1


# ------------------------------------------------------------- kvstore
def test_kvstore_collective_span_labels(tmp_path):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = np.asarray(jax.devices())
    if devs.size < 2:
        pytest.skip("needs the multi-device CPU mesh")
    mesh = Mesh(devs, ("dp",))
    kv = mx.kv.create("ici").set_mesh(mesh)
    n = devs.size
    stacked = jax.device_put(np.ones((n, 4), np.float32),
                             NamedSharding(mesh, P("dp")))
    bytes0 = registry().counter("kv_collective_bytes",
                                op="psum_stacked").value
    tracer.start()
    out = kv.allreduce_([stacked], layout="stacked")
    kv.allreduce_flat([np.ones((3,), np.float32)] * 2)
    path = tracer.dump(str(tmp_path / "kv.json"))
    tracer.stop()
    np.testing.assert_allclose(np.asarray(out), np.full((4,), n))
    assert check_trace.validate_file(path) == []
    events = json.load(open(path))["traceEvents"]
    span = [e for e in events if e.get("name") == "kv.psum_stacked"
            and e["ph"] == "B"][0]
    assert span["args"]["bytes"] == n * 4 * 4
    assert span["args"]["devices"] == n
    assert span["args"]["axis"] == "dp"
    flat = [e for e in events if e.get("name") == "kv.allreduce_flat"
            and e["ph"] == "B"][0]
    assert flat["args"]["arrays"] == 2 and flat["args"]["bytes"] == 24
    # always-on byte accounting moved too
    assert registry().counter("kv_collective_bytes",
                              op="psum_stacked").value - bytes0 == n * 16


# ------------------------------------------------- trainer + acceptance
def test_train_steps_produce_valid_trace_with_all_span_kinds(tmp_path):
    path = str(tmp_path / "profile.json")
    step = _tiny_trainer()
    step()                                   # warm compile outside trace
    profiler.set_config(filename=path)
    tracer.set_op_sample_rate(2)             # tiny net: few imperative ops
    try:
        profiler.start()
        for _ in range(3):
            step()
        engine.push(lambda: None)
        engine.wait_for_all()
        profiler.stop()
    finally:
        tracer.set_op_sample_rate(16)
    assert profiler.dump() == path
    assert check_trace.validate_file(path) == []
    events = json.load(open(path))["traceEvents"]
    names = [e.get("name") for e in events if e["ph"] in "BX"]
    steps = [e for e in events if e.get("name") == "Trainer.step"
             and e["ph"] == "B"]
    assert len(steps) == 3
    assert steps[0]["args"] == {"batch_size": 4, "params": 4, "fused": True}
    assert any(n == "Trainer.fused_bucket" for n in names)
    assert any(n == "Trainer.allreduce_grads" for n in names)
    assert any(n == "kv.allreduce_flat" for n in names)   # collective span
    assert any(n and n.startswith("engine:") for n in names)
    assert any(n and n.startswith("nd.") for n in names)   # sampled ops
    # gauges fed by the instrumented step
    assert registry().gauge("trainer_steps_per_s").value > 0
    # set async on the step path; snapshot coerces the device scalar
    norm = registry().gauge("trainer_grad_norm").snapshot()
    assert isinstance(norm, float) and norm >= 0
    assert registry().counter("trainer_steps").value >= 4
    rep = mx.observability.summary()
    assert "Trainer.step" in rep and "trainer_steps_per_s" in rep


def test_compile_spans_in_trace_and_summary(tmp_path):
    """ISSUE 11 satellite: a compile that happens while tracing lands a
    `compile.<executable>` span the Chrome-trace validator accepts
    (balanced like every other track — 'X' events carry their own dur),
    the compile/HLO series ride the registry with p95s in snapshot and
    summary(), and profiler.dumps() prints the [compile] breakdown."""
    path = str(tmp_path / "compile_trace.json")
    rng = np.random.RandomState(3)
    X = nd.array(rng.randn(8, 16).astype(np.float32))
    y = nd.array(rng.randint(0, 4, 8).astype(np.float32))
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    mx.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(X)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    step = tr.capture(lambda a, b: lossf(net(a), b).mean())
    tracer.start()
    step(X, y)                           # compiles INSIDE the trace
    step(X, y)
    tracer.stop()
    assert tracer.dump(path) == path
    assert check_trace.validate_file(path) == []
    events = json.load(open(path))["traceEvents"]
    comp = [e for e in events if str(e.get("name", ""))
            .startswith("compile.")]
    assert comp, "no compile span recorded"
    assert comp[0]["ph"] == "X" and comp[0]["dur"] > 0
    assert comp[0]["args"]["executable"] == "captured_step"
    # registry: compile_seconds histogram with a p95 in its snapshot
    snap = registry().snapshot()
    series = [s for s in snap["compile_seconds"]
              if dict(s["labels"]).get("executable") == "captured_step"]
    assert series and series[0]["value"]["count"] >= 1
    assert "p95" in series[0]["value"]
    # summary() and profiler.dumps() render the new families
    rep = mx.observability.summary()
    assert "compile_seconds" in rep
    dump = profiler.dumps()
    assert "[compile] captured_step:" in dump and "p95=" in dump


def test_sampled_op_spans_feed_host_tally(tmp_path):
    tracer.set_op_sample_rate(1)             # deterministic: every op
    try:
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.start()
        (nd.ones((4,)) + 1).asnumpy()
        profiler.stop()
        assert "nd." in profiler.dumps()     # Scope/op tally sees ops now
    finally:
        tracer.set_op_sample_rate(16)
        profiler.dumps(reset=True)


def test_disabled_path_overhead_smoke():
    """With tracing off the instrumented paths reduce to one module-attr
    check; nothing records, and a trainer step still runs full speed."""
    assert not tracer.ACTIVE
    step = _tiny_trainer()
    step()
    before = tracer.events_recorded()
    t0 = time.perf_counter()
    for _ in range(3):
        step()
    wall = time.perf_counter() - t0
    assert tracer.events_recorded() == before == 0
    # the disabled fast path itself: ~1e5 gate checks in well under a
    # second even on a loaded CI box (generous 50x headroom)
    t0 = time.perf_counter()
    for _ in range(100_000):
        if tracer.ACTIVE:
            raise AssertionError
    assert time.perf_counter() - t0 < 1.0
    assert wall < 60.0


# ------------------------------------------------------------- satellites
def test_pause_resume_suspends_both_traces(tmp_path):
    path = str(tmp_path / "profile.json")
    profiler.set_config(filename=path)
    profiler.start()
    with tracer.span("before-pause"):
        pass
    profiler.pause()
    assert not tracer.ACTIVE
    assert not profiler._state["jax_trace"]    # device trace closed too
    with tracer.span("while-paused"):
        pass
    profiler.resume()
    assert tracer.ACTIVE
    with tracer.span("after-resume"):
        pass
    profiler.stop()
    profiler.dump()
    names = {e.get("name")
             for e in json.load(open(path))["traceEvents"]}
    assert "before-pause" in names and "after-resume" in names
    assert "while-paused" not in names
    # stop() must finalize FROM the paused state too (stale jax_paused
    # would let a later resume() silently reopen recording)
    profiler.start()
    profiler.pause()
    profiler.stop()
    assert not tracer.ACTIVE
    assert not profiler._state["jax_paused"]
    # resume() after stop() must NOT silently reopen recording
    profiler.resume()
    assert not tracer.ACTIVE and not profiler._state["running"]


def test_set_config_preserves_full_target_path(tmp_path):
    target = tmp_path / "nested" / "dir" / "my_trace.json"
    profiler.set_config(filename=str(target))
    profiler.start()
    profiler.stop()
    assert profiler.dump() == str(target)
    assert target.exists()                    # not truncated to the dir


def test_scope_records_into_host_tally(tmp_path):
    profiler.set_config(filename=str(tmp_path / "p.json"))
    profiler.start()
    with profiler.Scope("my_region"):
        time.sleep(0.002)
    profiler.stop()
    dump = profiler.dumps(reset=True)
    line = [ln for ln in dump.splitlines() if ln.startswith("my_region")]
    assert line and int(line[0].split()[1]) == 1
    assert float(line[0].split()[2]) >= 1.0   # >= 1ms recorded


def test_monitor_handles_removable():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(3), gluon.nn.Dense(2))
    net.initialize()
    X = nd.ones((2, 4))
    net(X)
    mon = mx.monitor.Monitor(1, pattern=".*").install(net)
    assert len(mon.handles) >= 3              # root + children
    mon.tic()
    net(X)
    assert len(mon.toc()) > 0
    mon.remove()
    assert mon.handles == []
    assert net._forward_hooks == []           # actually detached
    mon.tic()
    net(X)
    assert mon.toc() == []
    mon.remove()                              # idempotent


def test_hook_handle_detach():
    from mxnet_tpu.gluon.utils import HookHandle
    net = gluon.nn.Dense(2)
    calls = []
    h = net.register_forward_hook(lambda b, i, o: calls.append(1))
    assert isinstance(h, HookHandle)
    net.initialize()
    net(nd.ones((1, 3)))
    assert calls == [1]
    h.detach()
    h.detach()
    net(nd.ones((1, 3)))
    assert calls == [1]
    with net.register_forward_pre_hook(lambda b, i: calls.append(2)):
        net(nd.ones((1, 3)))
    assert calls == [1, 2]
    net(nd.ones((1, 3)))                      # context exit detached it
    assert calls == [1, 2]


def test_check_numerics_on_device():
    ok = nd.array(np.array([1.0, 2.0], np.float32))
    assert mx.monitor.check_numerics(ok, "w") is ok
    ints = nd.array(np.array([1, 2], np.int32))
    assert mx.monitor.check_numerics(ints, "i") is ints
    bad = nd.array(np.array([1.0, np.nan, np.inf], np.float32))
    with pytest.raises(mx.MXNetError, match="1 NaN and 1 Inf"):
        mx.monitor.check_numerics(bad, "g")
    with pytest.raises(mx.MXNetError, match="plain has"):
        mx.monitor.check_numerics(np.array([np.nan]), "plain")


def test_nan_detector_scans_without_host_pull():
    net = gluon.nn.Dense(2)
    net.initialize()
    X = nd.ones((1, 3))
    with autograd.record():
        L = net(X).sum()
    L.backward()
    det = mx.monitor.NanDetector(net.collect_params())
    assert det.check()
    p = list(net.collect_params().values())[0]
    p._grad._rebind(p._grad._data * np.nan)
    with pytest.raises(mx.MXNetError, match="_grad"):
        det.check()
