"""Symbol auto-naming (reference: python/mxnet/name.py).

The reference names anonymous symbol ops `{op}{N}` with a process-global
counter held by a NameManager; checkpoint name stability across processes is
achieved by installing a fresh NameManager (or a Prefix) around model
construction. Same contract here: `with NameManager():` gives the block its
own zeroed counters, `with Prefix("p_"):` prepends a prefix.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = [NameManager()]
    return _tls.stack


def current():
    return _stack()[-1]


class NameManager:
    """Scoped auto-name counters: `{op}{N}` per op type (reference
    behaviour), isolated per manager so model construction can be made
    deterministic regardless of what was built earlier in the process."""

    def __init__(self):
        self._counts = {}

    def get(self, hint):
        i = self._counts.get(hint, 0)
        self._counts[hint] = i + 1
        return f"{hint}{i}"

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()


class Prefix(NameManager):
    """NameManager that prepends a fixed prefix (reference: mx.name.Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, hint):
        return self._prefix + super().get(hint)
