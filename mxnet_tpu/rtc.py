"""Runtime kernel compilation (reference: python/mxnet/rtc.py).

The reference's ``mx.rtc`` JIT-compiles CUDA C source at runtime via NVRTC
(``CudaModule``/``CudaKernel``). The TPU has no user-facing ISA to hand
raw source to — the runtime-compilation story here is **Pallas**: a kernel
is Python source describing per-tile math, lowered through Mosaic at call
time. ``TpuModule`` keeps the reference's workflow (source string in,
named callable kernels out) with Pallas as the backend; the CUDA entry
points raise with that guidance (SURVEY §8 designed divergence).

Example::

    mod = mx.rtc.TpuModule('''
    def axpy(x_ref, y_ref, o_ref):
        o_ref[...] = 2.0 * x_ref[...] + y_ref[...]
    ''', exports=["axpy"])
    kern = mod.get_kernel("axpy")
    z = kern(x, y)            # NDArrays in, NDArray out (same shape as x)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray.ndarray import NDArray, _apply

try:
    from jax.experimental import pallas as pl
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["TpuModule", "TpuKernel", "CudaModule", "CudaKernel"]


class TpuKernel:
    """One compiled kernel: NDArray positional args, one NDArray out whose
    shape/dtype mirror the first input (the reference kernel contract is
    likewise caller-declared; elementwise is the common case)."""

    def __init__(self, fn, name, interpret):
        self._fn = fn
        self._name = name
        self._interpret = interpret

    def __call__(self, *args, out_shape=None, out_dtype=None):
        if not args:
            raise MXNetError(f"rtc kernel {self._name}: need >=1 input")
        first = args[0]
        shape = out_shape or first.shape
        dtype = out_dtype or first.dtype

        def run(*raw):
            return pl.pallas_call(
                self._fn,
                out_shape=jax.ShapeDtypeStruct(shape, jnp.dtype(dtype)),
                interpret=self._interpret,
            )(*raw)
        nd_args = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
                   for a in args]
        return _apply(run, nd_args)


class TpuModule:
    """Compile Pallas kernel bodies from source at runtime.

    ``source`` is Python defining one function per kernel (Pallas ref
    signature: inputs..., output ref last). ``exports`` names the kernels
    to expose, mirroring the reference's ``CudaModule(source, exports=)``.
    """

    def __init__(self, source, options=(), exports=(), interpret=None):
        if not _HAS_PALLAS:  # pragma: no cover
            raise MXNetError("rtc.TpuModule: Pallas unavailable")
        if interpret is None:
            # CPU hosts run the same kernel bodies via interpret mode
            interpret = jax.default_backend() != "tpu"
        self._interpret = interpret
        namespace = {"jax": jax, "jnp": jnp, "pl": pl}
        try:
            exec(compile(source, "<mx.rtc>", "exec"), namespace)
        except SyntaxError as e:
            raise MXNetError(f"rtc.TpuModule: source does not compile: {e}")
        self._kernels = {}
        for name in (exports or
                     [k for k, v in namespace.items() if callable(v)
                      and getattr(v, "__module__", None) is None]):
            if name not in namespace or not callable(namespace[name]):
                raise MXNetError(f"rtc.TpuModule: no kernel {name!r} "
                                 "in source")
            self._kernels[name] = namespace[name]

    def get_kernel(self, name, signature=None):
        if name not in self._kernels:
            raise MXNetError(
                f"rtc.TpuModule: kernel {name!r} not exported "
                f"(have {sorted(self._kernels)})")
        return TpuKernel(self._kernels[name], name, self._interpret)


def CudaModule(*a, **kw):
    raise MXNetError(
        "mx.rtc.CudaModule compiles CUDA C, which has no TPU equivalent. "
        "Use mx.rtc.TpuModule with a Pallas kernel body instead "
        "(SURVEY.md §8).")


CudaKernel = CudaModule
