"""mx.contrib (reference: python/mxnet/contrib).

Quantization is REAL on TPU: the MXU multiplies int8 natively, so
`contrib.quantization` implements calibrated symmetric int8 inference
(see that module). ONNX export stays a gated stub — the `onnx` package is
not available in this environment, and the TPU-native deployment path is
the XLA executable exported by HybridBlock.export.
"""
from ..base import MXNetError
from . import quantization
from .quantization import quantize_model, quantize_net


def export_onnx(*args, **kwargs):
    raise MXNetError(
        "ONNX export requires the `onnx` package, which is not available "
        "here; deploy the jitted XLA executable via HybridBlock.export")
