"""mx.contrib (reference: python/mxnet/contrib).

Quantization/ONNX are explicitly stubbed (SURVEY.md §2 #49): int8 inference
and ONNX interchange target GPU/cpu toolchains the reference wraps; on TPU
the equivalent deployment path is the XLA executable exported by
HybridBlock.export. Calling these raises with that guidance.
"""
from ..base import MXNetError


def quantize_model(*args, **kwargs):
    raise MXNetError("int8 quantization is stubbed on TPU; use bf16 via "
                     "mxnet_tpu.amp (SURVEY.md §2 #49)")


def export_onnx(*args, **kwargs):
    raise MXNetError("ONNX export is stubbed; deploy the jitted XLA "
                     "executable via HybridBlock.export (SURVEY.md §2 #49)")
