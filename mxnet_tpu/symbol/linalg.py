"""mx.sym.linalg — symbolic mirror of mx.nd.linalg (reference:
src/operator/tensor/la_op.cc registered under linalg_*).

Each op registers a raw-array kernel (shared with ops/linalg_ops where a
packing helper exists) so linalg graphs serialize through symbol JSON
like any other node."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.linalg_ops import (extractdiag_k, extracttrian_k, makediag_k,
                              maketrian_k)
from .symbol import _make, register_op

__all__ = ["gemm", "gemm2", "potrf", "potri", "trsm", "trmm", "syrk",
           "sumlogdiag", "extractdiag", "makediag", "extracttrian",
           "maketrian", "inverse", "det"]


def _gemm_eval(a, b, c, alpha=1.0, beta=1.0, transpose_a=False,
               transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b) + beta * c


def _gemm2_eval(a, b, alpha=1.0, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


def _trsm_eval(a, b, alpha=1.0, rightside=False, lower=True,
               transpose=False):
    if rightside:
        xt = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * b, -1, -2),
            lower=not lower if not transpose else lower)
        return jnp.swapaxes(xt, -1, -2)
    return jax.scipy.linalg.solve_triangular(a, alpha * b, lower=lower,
                                             trans=int(transpose))


def _trmm_eval(a, b, alpha=1.0, rightside=False, lower=True,
               transpose=False):
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    return alpha * (jnp.matmul(b, tri) if rightside
                    else jnp.matmul(tri, b))


def _potri_eval(a):
    eye = jnp.eye(a.shape[-1], dtype=a.dtype)
    linv = jax.scipy.linalg.solve_triangular(a, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


def _syrk_eval(a, alpha=1.0, transpose=False):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


register_op("_linalg_gemm", _gemm_eval)
register_op("_linalg_gemm2", _gemm2_eval)
register_op("_linalg_potrf", jnp.linalg.cholesky)
register_op("_linalg_potri", _potri_eval)
register_op("_linalg_trsm", _trsm_eval)
register_op("_linalg_trmm", _trmm_eval)
register_op("_linalg_syrk", _syrk_eval)
register_op("_linalg_sumlogdiag",
            lambda a: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)),
                              axis=-1))
register_op("_linalg_extractdiag", extractdiag_k)
register_op("_linalg_makediag", makediag_k)
register_op("_linalg_extracttrian", extracttrian_k)
register_op("_linalg_maketrian", maketrian_k)
register_op("_linalg_inverse", jnp.linalg.inv)
register_op("_linalg_det", jnp.linalg.det)


def gemm(A, B, C, alpha=1.0, beta=1.0, transpose_a=False,
         transpose_b=False, name=None):
    return _make("_linalg_gemm", [A, B, C],
                 {"alpha": alpha, "beta": beta, "transpose_a": transpose_a,
                  "transpose_b": transpose_b}, name=name)


def gemm2(A, B, alpha=1.0, transpose_a=False, transpose_b=False,
          name=None):
    return _make("_linalg_gemm2", [A, B],
                 {"alpha": alpha, "transpose_a": transpose_a,
                  "transpose_b": transpose_b}, name=name)


def potrf(A, name=None):
    return _make("_linalg_potrf", [A], {}, name=name)


def potri(A, name=None):
    return _make("_linalg_potri", [A], {}, name=name)


def trsm(A, B, alpha=1.0, rightside=False, lower=True, transpose=False,
         name=None):
    return _make("_linalg_trsm", [A, B],
                 {"alpha": alpha, "rightside": rightside, "lower": lower,
                  "transpose": transpose}, name=name)


def trmm(A, B, alpha=1.0, rightside=False, lower=True, transpose=False,
         name=None):
    return _make("_linalg_trmm", [A, B],
                 {"alpha": alpha, "rightside": rightside, "lower": lower,
                  "transpose": transpose}, name=name)


def syrk(A, alpha=1.0, transpose=False, name=None):
    return _make("_linalg_syrk", [A],
                 {"alpha": alpha, "transpose": transpose}, name=name)


def sumlogdiag(A, name=None):
    return _make("_linalg_sumlogdiag", [A], {}, name=name)


def extractdiag(A, offset=0, name=None):
    return _make("_linalg_extractdiag", [A], {"offset": int(offset)},
                 name=name)


def makediag(A, offset=0, name=None):
    return _make("_linalg_makediag", [A], {"offset": int(offset)},
                 name=name)


def extracttrian(A, offset=0, lower=True, name=None):
    return _make("_linalg_extracttrian", [A],
                 {"offset": int(offset), "lower": bool(lower)}, name=name)


def maketrian(A, offset=0, lower=True, name=None):
    return _make("_linalg_maketrian", [A],
                 {"offset": int(offset), "lower": bool(lower)}, name=name)


def inverse(A, name=None):
    return _make("_linalg_inverse", [A], {}, name=name)


def det(A, name=None):
    return _make("_linalg_det", [A], {}, name=name)
