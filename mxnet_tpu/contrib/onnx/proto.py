"""Self-contained ONNX protobuf wire-format encoder/decoder.

The `onnx` package is unavailable offline, so this module hand-encodes the
~8 message types an ONNX model file needs (reference:
python/mxnet/contrib/onnx/mx2onnx uses the onnx helper API; the wire format
itself is standard protobuf: https://protobuf.dev/programming-guides/encoding).

Field numbers below are from onnx/onnx.proto (stable since IR version 3):

ModelProto:      1 ir_version, 2 producer_name, 3 producer_version,
                 7 graph, 8 opset_import
OperatorSetIdProto: 1 domain, 2 version
GraphProto:      1 node, 2 name, 5 initializer, 11 input, 12 output,
                 13 value_info
NodeProto:       1 input, 2 output, 3 name, 4 op_type, 5 attribute,
                 7 domain
AttributeProto:  1 name, 2 f, 3 i, 4 s, 5 t, 7 floats, 8 ints, 9 strings,
                 20 type
TensorProto:     1 dims, 2 data_type, 8 name, 9 raw_data
ValueInfoProto:  1 name, 2 type
TypeProto:       1 tensor_type
TypeProto.Tensor: 1 elem_type, 2 shape
TensorShapeProto: 1 dim;  Dimension: 1 dim_value, 2 dim_param

The decoder returns nested dicts keyed by field number — enough for tests
to validate an exported graph node-by-node without the onnx package.
"""
from __future__ import annotations

import struct

from ...base import MXNetError

# TensorProto.DataType
FLOAT = 1
UINT8 = 2
INT8 = 3
INT32 = 6
INT64 = 7
BOOL = 9
FLOAT16 = 10
DOUBLE = 11
BFLOAT16 = 16

# AttributeProto.AttributeType
ATTR_FLOAT = 1
ATTR_INT = 2
ATTR_STRING = 3
ATTR_TENSOR = 4
ATTR_FLOATS = 6
ATTR_INTS = 7
ATTR_STRINGS = 8

_NP_TO_ONNX = {
    "float32": FLOAT, "uint8": UINT8, "int8": INT8, "int32": INT32,
    "int64": INT64, "bool": BOOL, "float16": FLOAT16, "float64": DOUBLE,
    "bfloat16": BFLOAT16,
}


def onnx_dtype(np_dtype):
    name = str(np_dtype)
    if name not in _NP_TO_ONNX:
        raise ValueError(f"no ONNX dtype for {name}")
    return _NP_TO_ONNX[name]


# ------------------------------------------------------------------ encoder
def _varint(n):
    n &= 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field, wire):
    return _varint((field << 3) | wire)


def f_varint(field, value):
    """Wire type 0: int64 / enum / bool fields."""
    return _tag(field, 0) + _varint(int(value))


def f_float(field, value):
    """Wire type 5: float fields."""
    return _tag(field, 5) + struct.pack("<f", float(value))


def f_bytes(field, data):
    """Wire type 2: string / bytes / embedded message fields."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    return _tag(field, 2) + _varint(len(data)) + data


def message(*fields):
    return b"".join(fields)


# ------------------------------------------------------------------ decoder
class WireError(Exception):
    """Raised by the wire layer on structurally invalid input (truncation,
    unsupported wire type, scalar where a submessage was expected)."""


def _read_varint(buf, pos):
    result = shift = 0
    while True:
        if pos >= len(buf):
            raise WireError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def decode(buf):
    """Decode one protobuf message into {field_number: [values]} (repeated
    fields accumulate in order). Length-delimited values stay as bytes —
    callers descend with another decode() where a field is a submessage.
    Raises WireError on structural garbage; always terminates (lengths
    only ever ADVANCE the cursor)."""
    if not isinstance(buf, (bytes, bytearray, memoryview)):
        raise WireError(
            f"expected a submessage, found {type(buf).__name__}")
    fields = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 0x7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            if pos + 8 > len(buf):
                raise WireError("truncated fixed64")
            val = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            if pos + ln > len(buf):
                raise WireError("length-delimited field overruns buffer")
            val = bytes(buf[pos:pos + ln])
            pos += ln
        elif wire == 5:
            if pos + 4 > len(buf):
                raise WireError("truncated fixed32")
            val = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        else:
            raise WireError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append(val)
    return fields


def _packed_ints(values):
    """Repeated int64 fields arrive either as individual varints (our
    encoder) or as ONE length-delimited packed blob (proto3 writers like
    the onnx package / torch exporters). Normalise to a tuple of ints."""
    out = []
    for v in values:
        if isinstance(v, bytes):
            pos = 0
            while pos < len(v):
                n, pos = _read_varint(v, pos)
                out.append(n)
        else:
            out.append(v)
    return tuple(out)


def _packed_floats(values):
    out = []
    for v in values:
        if isinstance(v, bytes):
            out.extend(x[0] for x in struct.iter_unpack("<f", v))
        else:
            out.append(v)
    return tuple(out)


def decode_model(buf):
    """Parse a serialized ModelProto into a friendly dict for tests:
    {ir_version, opset, graph: {name, inputs, outputs, initializers:
    {name: (dims, data_type, raw)}, nodes: [{op_type, name, inputs,
    outputs, attrs: {name: value}}]}}. Handles both unpacked (this repo's
    encoder) and proto3-packed repeated int/float fields (external ONNX
    writers). Truncated/garbage input raises MXNetError (the wire walk
    always terminates — lengths only ever ADVANCE the cursor)."""
    try:
        m = decode(buf)
        graph = decode(m[7][0])
        out = {
            "ir_version": m.get(1, [None])[0],
            "opset": [(decode(o).get(1, [b""])[0].decode(),
                       decode(o).get(2, [0])[0]) for o in m.get(8, [])],
            "graph": {
                "name": graph.get(2, [b""])[0].decode(),
                "inputs": [_value_info(v) for v in graph.get(11, [])],
                "outputs": [_value_info(v) for v in graph.get(12, [])],
                "initializers": {},
                "nodes": [],
            },
        }
        for t in graph.get(5, []):
            td = decode(t)
            name = td.get(8, [b""])[0].decode()
            out["graph"]["initializers"][name] = (
                _packed_ints(td.get(1, [])), td.get(2, [None])[0],
                td.get(9, [b""])[0])
        for n in graph.get(1, []):
            nd = decode(n)
            out["graph"]["nodes"].append({
                "op_type": nd.get(4, [b""])[0].decode(),
                "name": nd.get(3, [b""])[0].decode(),
                "inputs": [s.decode() for s in nd.get(1, [])],
                "outputs": [s.decode() for s in nd.get(2, [])],
                "attrs": {a["name"]: a["value"]
                          for a in (_attr(x) for x in nd.get(5, []))},
            })
        return out
    except (WireError, KeyError, UnicodeDecodeError, AttributeError,
            struct.error, TypeError) as e:
        # WireError covers the structural garbage the hardened wire layer
        # detects; the rest are value-level shapes it can't type-check:
        # KeyError = required field absent; AttributeError/TypeError = a
        # field arrived with the wrong wire type (.decode()/compare on a
        # number, or bytes where an int was declared); struct.error = a
        # packed blob whose length isn't a multiple of the element size.
        # The chained original (`from e`) keeps any real decoder bug
        # visible under the wrapper.
        raise MXNetError(
            f"malformed ONNX file: {type(e).__name__}: {e} "
            "(truncated or not an ONNX model?)") from e


def _value_info(buf):
    v = decode(buf)
    name = v.get(1, [b""])[0].decode()
    shape = ()
    if 2 in v:
        tp = decode(v[2][0])
        if 1 in tp:
            tt = decode(tp[1][0])
            if 2 in tt:
                dims = decode(tt[2][0]).get(1, [])
                shape = tuple(decode(d).get(1, [0])[0] for d in dims)
    return (name, shape)


def _attr(buf):
    a = decode(buf)
    name = a.get(1, [b""])[0].decode()
    atype = a.get(20, [0])[0]
    if atype == ATTR_FLOAT:
        value = a[2][0]
    elif atype == ATTR_INT:
        value = _signed(a[3][0])
    elif atype == ATTR_STRING:
        value = a[4][0].decode()
    elif atype == ATTR_INTS:
        value = tuple(_signed(i) for i in _packed_ints(a.get(8, [])))
    elif atype == ATTR_FLOATS:
        value = _packed_floats(a.get(7, []))
    else:
        value = a
    return {"name": name, "value": value}


def _signed(u):
    return u - (1 << 64) if u >= (1 << 63) else u
